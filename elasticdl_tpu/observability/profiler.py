"""Continuous profiling plane: always-on sampling profiler + master
profile store.

The metrics plane says *how much*, the trace plane says *which phase*;
this module says *which code*. A ``SamplingProfiler`` walks
``sys._current_frames()`` on a daemon thread (default ~67 Hz — a prime
rate, so it can't alias against 10/50/100 Hz periodic work), folds each
thread's Python stack into a **bounded flame table** keyed
``<thread-class>;<frame>;<frame>;...`` (root first, leaf last — the
standard folded-flamegraph form), and closes a window every
``window_secs`` into a ring with a monotonic ``seq`` — the exact shape
the tracing plane uses, so windows ride the same piggyback path
(worker snapshot ``profiles`` key, ``ComponentMetricsReporter``) into
the master's ``ProfileStore`` and serve on ``/profile`` next to
``/metrics``.

Cost discipline (the PR 4 span lesson, enforced by
tests/test_profile_plane.py): one sample is a GIL-held dict walk with
frame names cached per code object — tens of microseconds — so at the
default rate the profiler costs well under 1% of a busy worker loop
(``overhead_fraction`` measures it; the drill and the fast-lane pin
both gate on ≤ 1%). The flame table is bounded (``max_stacks``): under
pathological stack churn new distinct stacks collapse into
``OVERFLOW_KEY`` instead of growing without bound.

Device/phase attribution: ``fold_spans`` folds collected trace spans
(MeshRunner step phases, host-engine pulls, rpc handlers) into the
same folded format under a ``phases`` pseudo-thread-class, weighted by
self-time × hz — so host stacks and device phases render in one flame
view on ``/profile`` (docs/observability.md "Continuous profiling &
exemplars").

Differential profiles: ``ProfileStore.render(... base_secs=N)``
compares the current window against the same-length window ending N
seconds earlier — the before/after-resize regression view.
"""

import re
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("profiler")

DEFAULT_HZ = 67.0
DEFAULT_WINDOW_SECS = 10.0
DEFAULT_MAX_STACKS = 512
DEFAULT_MAX_WINDOWS = 64
MAX_DEPTH = 48
OVERFLOW_KEY = "__overflow__"
# Pseudo thread-class for span-derived (device/phase) samples — never
# produced by the sampler, excluded from the per-class sample-count
# consistency check in tools/check_profile.py.
SPAN_CLASS = "phases"

# ---- process-global profiler seam (None = profiling off) ----------------

_PROFILER: Optional["SamplingProfiler"] = None


def install_profiler(prof: "SamplingProfiler") -> "SamplingProfiler":
    """Install (or replace) the process profiler. Does not start it —
    callers start() explicitly (tests drive sample() by hand)."""
    global _PROFILER
    if _PROFILER is not None and _PROFILER is not prof:
        _PROFILER.stop()
    _PROFILER = prof
    return prof


def uninstall_profiler():
    global _PROFILER
    if _PROFILER is not None:
        _PROFILER.stop()
    _PROFILER = None


def profiler() -> Optional["SamplingProfiler"]:
    return _PROFILER


def windows_since(cursor: int) -> Tuple[List[dict], int]:
    """Closed windows with seq > cursor plus the new cursor — the
    piggyback reporters' incremental read. ([], cursor) when off."""
    prof = _PROFILER
    if prof is None:
        return [], cursor
    return prof.windows_since(cursor)


def maybe_start_from_args(args, role: str,
                          instance: str = "0"
                          ) -> Optional["SamplingProfiler"]:
    """Install + start a profiler when the process main was given
    ``--profile_hz > 0``; the standard gate every component main uses
    (master / worker / row-service / router / serving)."""
    hz = float(getattr(args, "profile_hz", 0.0) or 0.0)
    if hz <= 0:
        return None
    prof = SamplingProfiler(
        hz=hz,
        window_secs=float(
            getattr(args, "profile_window_secs", DEFAULT_WINDOW_SECS)
            or DEFAULT_WINDOW_SECS
        ),
        role=role,
        instance=str(instance),
    )
    install_profiler(prof)
    prof.start()
    logger.info(
        "sampling profiler on: %.0f Hz, %.0fs windows (role %s/%s)",
        hz, prof.window_secs, role, instance,
    )
    return prof


def thread_class(name: str) -> str:
    """Collapse thread names into a bounded class set: pool workers
    (grpc handlers run on ``ThreadPoolExecutor-N_M`` threads) fold
    together, numbered clones of named daemons fold with their base
    name."""
    if name == "MainThread":
        return "main"
    if name.startswith(("ThreadPoolExecutor", "Dummy-")):
        return "pool"
    # "Thread-3 (worker_fn)" (the 3.10+ default) → "thread".
    name = re.sub(r"\s*\(.*\)$", "", name)
    base = re.sub(r"[-_ ]?[0-9]+(_[0-9]+)?$", "", name)
    return (base or "thread").lower()


class SamplingProfiler:
    """Always-on wall-clock sampling profiler for one process.

    ``clock`` (wall time) is injectable so window-boundary tests are
    deterministic; the daemon loop paces itself on ``time.monotonic``
    regardless. ``sample()`` is public: tests drive it directly."""

    def __init__(self, hz: float = DEFAULT_HZ,
                 window_secs: float = DEFAULT_WINDOW_SECS,
                 max_stacks: int = DEFAULT_MAX_STACKS,
                 max_windows: int = DEFAULT_MAX_WINDOWS,
                 role: str = "process", instance: str = "0",
                 clock: Callable[[], float] = time.time,
                 metrics_registry=None):
        if hz <= 0:
            raise ValueError("hz must be positive")
        self.hz = float(hz)
        self.window_secs = float(window_secs)
        self.max_stacks = int(max_stacks)
        self.role = str(role)
        self.instance = str(instance)
        self._clock = clock
        self._lock = threading.Lock()
        self._windows = deque(maxlen=int(max_windows))
        self._seq = 0
        self._samples: Dict[str, int] = {}
        self._window_t0: Optional[float] = None
        self._passes = 0
        self._thread_peaks: Dict[str, int] = {}
        self._dropped = 0
        # frame-name cache keyed by code object: the stack walk's cost
        # is dominated by string building; code objects are stable, so
        # after warm-up a sample is dict lookups only.
        self._names: Dict[object, str] = {}
        # thread-name map refreshed every _THREAD_REFRESH passes —
        # threading.enumerate() per sample would double the walk cost.
        self._thread_names: Dict[int, str] = {}
        self._thread_refresh_left = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._own_ident: Optional[int] = None
        # EWMA of one sample's wall cost — overhead_fraction() input.
        self.sample_cost_ewma = 0.0
        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self._m_samples = registry.counter(
            "profile_samples_total",
            "Sampling-profiler stack-walk passes taken",
        )
        self._m_overflow = registry.counter(
            "profile_stack_overflow_total",
            "Samples folded into the overflow bucket because the "
            "flame table hit max_stacks",
        )

    _THREAD_REFRESH = 32

    # ---- lifecycle -----------------------------------------------------

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="sampling-profiler"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
            self._thread = None

    def _run(self):
        self._own_ident = threading.get_ident()
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                self.sample()
            except Exception:
                # One bad walk (a frame dying mid-read) must not kill
                # the profiler for the rest of the process's life.
                logger.exception("profiler sample failed")

    # ---- sampling ------------------------------------------------------

    def _refresh_threads(self):
        self._thread_names = {
            t.ident: thread_class(t.name)
            for t in threading.enumerate()
            if t.ident is not None
        }
        self._thread_refresh_left = self._THREAD_REFRESH

    def _frame_label(self, frame) -> str:
        code = frame.f_code
        name = self._names.get(code)
        if name is None:
            mod = frame.f_globals.get("__name__", "") or ""
            qual = getattr(code, "co_qualname", code.co_name)
            name = f"{mod}.{qual}" if mod else str(qual)
            self._names[code] = name
        return name

    def sample(self, now: Optional[float] = None):
        """One stack-walk pass over every live thread (except the
        profiler's own); rolls the window first when it has aged out."""
        t_cost = time.perf_counter()
        now = self._clock() if now is None else now
        with self._lock:
            if self._window_t0 is None:
                self._window_t0 = now
            elif now - self._window_t0 >= self.window_secs:
                self._close_window_locked(now)
            if self._thread_refresh_left <= 0:
                self._refresh_threads()
            self._thread_refresh_left -= 1
            frames = sys._current_frames()
            own = self._own_ident
            per_class: Dict[str, int] = {}
            for ident, frame in frames.items():
                if ident == own:
                    continue
                tclass = self._thread_names.get(ident, "thread")
                per_class[tclass] = per_class.get(tclass, 0) + 1
                stack = []
                depth = 0
                while frame is not None and depth < MAX_DEPTH:
                    stack.append(self._frame_label(frame))
                    frame = frame.f_back
                    depth += 1
                truncated = frame is not None
                stack.reverse()
                if truncated:
                    stack.insert(0, "...")
                folded = tclass + ";" + ";".join(stack)
                if (folded not in self._samples
                        and len(self._samples) >= self.max_stacks):
                    folded = OVERFLOW_KEY
                    self._dropped += 1
                    self._m_overflow.inc()
                self._samples[folded] = self._samples.get(folded, 0) + 1
            for tclass, n in per_class.items():
                if n > self._thread_peaks.get(tclass, 0):
                    self._thread_peaks[tclass] = n
            self._passes += 1
        self._m_samples.inc()
        cost = time.perf_counter() - t_cost
        self.sample_cost_ewma = (
            cost if self.sample_cost_ewma == 0.0
            else 0.9 * self.sample_cost_ewma + 0.1 * cost
        )

    def _close_window_locked(self, now: float):
        if self._passes:
            self._seq += 1
            self._windows.append({
                "seq": self._seq,
                "t0": float(self._window_t0),
                "t1": float(now),
                "hz": self.hz,
                "role": self.role,
                "instance": self.instance,
                "sample_count": self._passes,
                "threads": dict(self._thread_peaks),
                "samples": dict(self._samples),
                "dropped": self._dropped,
            })
        self._window_t0 = now
        self._passes = 0
        self._samples = {}
        self._thread_peaks = {}
        self._dropped = 0

    def close_window(self, now: Optional[float] = None):
        """Force-close the open window (shutdown flush / tests)."""
        now = self._clock() if now is None else now
        with self._lock:
            self._close_window_locked(now)

    # ---- reads ---------------------------------------------------------

    def windows_since(self, cursor: int) -> Tuple[List[dict], int]:
        with self._lock:
            return (
                [w for w in self._windows if w["seq"] > cursor],
                self._seq,
            )

    def snapshot_windows(self, include_open: bool = True) -> List[dict]:
        """All retained windows, plus (optionally) a copy of the open
        one — how a component's own ``/profile`` route stays fresh
        instead of lagging a full window."""
        with self._lock:
            out = list(self._windows)
            if include_open and self._passes:
                out.append({
                    "seq": None,
                    "t0": float(self._window_t0),
                    "t1": float(self._clock()),
                    "hz": self.hz,
                    "role": self.role,
                    "instance": self.instance,
                    "sample_count": self._passes,
                    "threads": dict(self._thread_peaks),
                    "samples": dict(self._samples),
                    "dropped": self._dropped,
                    "open": True,
                })
            return out

    def overhead_fraction(self) -> float:
        """Estimated fraction of one core the profiler consumes at its
        configured rate — the ≤1% pin's measurement."""
        return self.sample_cost_ewma * self.hz


# ---- folded / pprof rendering -------------------------------------------


def folded_text(samples: Dict[str, int]) -> str:
    """Standard folded-flamegraph text: ``frame;frame;frame count``
    per line, heaviest first (stable for goldens: count desc, then
    stack)."""
    lines = [
        f"{stack} {int(count)}"
        for stack, count in sorted(
            samples.items(), key=lambda kv: (-kv[1], kv[0])
        )
    ]
    return "\n".join(lines) + ("\n" if lines else "")


def pprof_json(window: dict) -> dict:
    """pprof-shaped JSON for one (merged) window: a string table plus
    location-index sample stacks — loadable by anything that speaks
    the gzipped-proto profile.proto *shape* without the proto dep.
    ``tools/check_profile.py`` validates it."""
    samples = window.get("samples", {})
    strings: List[str] = []
    index: Dict[str, int] = {}

    def intern(s: str) -> int:
        at = index.get(s)
        if at is None:
            at = index[s] = len(strings)
            strings.append(s)
        return at

    hz = float(window.get("hz") or DEFAULT_HZ)
    out_samples = []
    for stack, count in sorted(
        samples.items(), key=lambda kv: (-kv[1], kv[0])
    ):
        frames = stack.split(";")
        out_samples.append({
            "location_id": [intern(f) for f in frames],
            # value[0] = sample count, value[1] = estimated seconds.
            "value": [int(count), round(count / hz, 6)],
        })
    return {
        "sample_type": [
            {"type": "samples", "unit": "count"},
            {"type": "wall", "unit": "seconds"},
        ],
        "period": 1.0 / hz,
        "duration_seconds": round(
            float(window.get("t1", 0.0)) - float(window.get("t0", 0.0)),
            6,
        ),
        "string_table": strings,
        "samples": out_samples,
    }


def merge_windows(windows: List[dict]) -> Optional[dict]:
    """Fold several windows into one (sample counts sum, bounds span,
    thread peaks max). None for an empty list."""
    if not windows:
        return None
    merged_samples: Dict[str, int] = {}
    threads: Dict[str, int] = {}
    passes = 0
    dropped = 0
    for w in windows:
        passes += int(w.get("sample_count", 0))
        dropped += int(w.get("dropped", 0))
        for stack, count in (w.get("samples") or {}).items():
            merged_samples[stack] = (
                merged_samples.get(stack, 0) + int(count)
            )
        for tclass, peak in (w.get("threads") or {}).items():
            if int(peak) > threads.get(tclass, 0):
                threads[tclass] = int(peak)
    last = windows[-1]
    return {
        "t0": min(float(w.get("t0", 0.0)) for w in windows),
        "t1": max(float(w.get("t1", 0.0)) for w in windows),
        "hz": float(last.get("hz") or DEFAULT_HZ),
        "role": last.get("role", "process"),
        "instance": last.get("instance", "0"),
        "sample_count": passes,
        "threads": threads,
        "samples": merged_samples,
        "dropped": dropped,
        "windows": len(windows),
    }


def diff_profiles(cur: dict, base: dict, top: int = 100) -> List[dict]:
    """Per-stack share deltas between two merged windows — the
    before/after-resize regression view. Shares (count / total), not
    raw counts, so windows of different lengths compare."""
    cur_samples = cur.get("samples") or {}
    base_samples = base.get("samples") or {}
    cur_total = sum(cur_samples.values()) or 1
    base_total = sum(base_samples.values()) or 1
    out = []
    for stack in set(cur_samples) | set(base_samples):
        c = cur_samples.get(stack, 0)
        b = base_samples.get(stack, 0)
        cf = c / cur_total
        bf = b / base_total
        out.append({
            "stack": stack,
            "cur": int(c),
            "base": int(b),
            "cur_frac": round(cf, 6),
            "base_frac": round(bf, 6),
            "delta_frac": round(cf - bf, 6),
        })
    out.sort(key=lambda d: (-abs(d["delta_frac"]), d["stack"]))
    return out[: int(top)]


# ---- span folding (device/phase attribution) ----------------------------


def fold_spans(spans: List[dict], hz: float,
               role: Optional[str] = None,
               instance: Optional[str] = None) -> Dict[str, int]:
    """Collected trace spans → folded pseudo-samples under the
    ``phases`` class, weighted by SELF time (duration minus child
    durations) × hz — so a MeshRunner ``device_step`` or a host-engine
    ``row_pull`` lands in the same flame view as the Python stacks
    that surround it. ``role``/``instance`` filter to one component's
    spans (None = all)."""
    by_id = {}
    child_dur: Dict[str, float] = {}
    for s in spans:
        if not isinstance(s, dict) or not s.get("span_id"):
            continue
        by_id[s["span_id"]] = s
    for s in by_id.values():
        parent = s.get("parent_id")
        if parent in by_id:
            child_dur[parent] = (
                child_dur.get(parent, 0.0) + float(s.get("dur", 0.0))
            )

    def path(span, depth=0) -> List[str]:
        if depth > MAX_DEPTH:
            return ["..."]
        parent = by_id.get(span.get("parent_id"))
        prefix = path(parent, depth + 1) if parent is not None else []
        return prefix + [str(span.get("name", "span"))]

    folded: Dict[str, int] = {}
    for s in by_id.values():
        if role is not None and s.get("role") != role:
            continue
        if instance is not None and str(
            s.get("instance", "0")
        ) != str(instance):
            continue
        self_secs = max(
            0.0,
            float(s.get("dur", 0.0)) - child_dur.get(s["span_id"], 0.0),
        )
        weight = int(round(self_secs * hz))
        if weight <= 0:
            continue
        key = ";".join(
            [SPAN_CLASS, f"{s.get('role', 'process')}/"
                         f"{s.get('instance', '0')}"] + path(s)
        )
        folded[key] = folded.get(key, 0) + weight
    return folded


# ---- component naming ---------------------------------------------------


def component_role(component: str) -> Tuple[str, str]:
    """Map a cluster-view reporter key to its trace (role, instance):
    ``""`` → master, bare ints → workers, ``rowservice-1`` /
    ``router-0`` / ``serving-2`` → themselves."""
    component = str(component)
    if component in ("", "master"):
        return "master", "0"
    try:
        return "worker", str(int(component))
    except ValueError:
        pass
    name, _, inst = component.rpartition("-")
    if name and inst.isdigit():
        return name, inst
    return component, "0"


# ---- master-side store --------------------------------------------------


class ProfileStore:
    """Piggybacked profile windows per reporter, bounded, deduped by
    (seq, t0) — several RPCs can offer the same un-acked window (the
    span-cursor discipline), and a restarted process's seq restarts.
    Source ``""`` is this process itself (``pull_local``)."""

    def __init__(self, max_windows_per_source: int = 360):
        self._lock = threading.Lock()
        self._max = int(max_windows_per_source)
        self._sources: Dict[str, deque] = {}
        self._local_cursor = 0
        # The local profiler's OPEN window, refreshed on every
        # pull_local and held OUTSIDE the ring: ingesting it would
        # double-count once the same window closes with a real seq
        # (and the (None, t0) dedup would freeze it at its first
        # snapshot). merged("") folds it in at read time instead.
        self._local_open: Optional[dict] = None

    def ingest(self, source, windows) -> int:
        if not windows:
            return 0
        source = str(source)
        added = 0
        with self._lock:
            ring = self._sources.get(source)
            if ring is None:
                ring = self._sources[source] = deque(maxlen=self._max)
            seen = {(w.get("seq"), w.get("t0")) for w in ring}
            for w in windows:
                if not isinstance(w, dict) or not w.get("samples"):
                    continue
                key = (w.get("seq"), w.get("t0"))
                if key in seen:
                    continue
                seen.add(key)
                ring.append(dict(w))
                added += 1
        return added

    def pull_local(self):
        """Fold this process's own profiler windows in under source
        ``""`` — the master's own profile must not depend on a
        piggyback loop it doesn't have. Closed windows enter the ring;
        the open window is held aside (refreshed every pull, merged at
        read time) so /profile on a freshly started process is not
        empty for a full window length."""
        windows, self._local_cursor = windows_since(self._local_cursor)
        if windows:
            self.ingest("", windows)
        prof = _PROFILER
        open_window = None
        if prof is not None:
            for w in prof.snapshot_windows(include_open=True):
                if w.get("open"):
                    open_window = w
        with self._lock:
            self._local_open = open_window

    def components(self) -> List[dict]:
        with self._lock:
            out = []
            for source, ring in sorted(self._sources.items()):
                if not ring:
                    continue
                last = ring[-1]
                out.append({
                    "component": source,
                    "role": last.get("role"),
                    "instance": last.get("instance"),
                    "windows": len(ring),
                    "t1": last.get("t1"),
                    "hz": last.get("hz"),
                })
            return out

    def drop_source(self, source: str):
        with self._lock:
            self._sources.pop(str(source), None)

    def merged(self, component: str, window_secs: float = 60.0,
               now: Optional[float] = None,
               end_offset_secs: float = 0.0) -> Optional[dict]:
        """Windows of ``component`` overlapping the ``window_secs``
        span ending ``end_offset_secs`` ago, merged. None = no data."""
        now = time.time() if now is None else now
        end = now - float(end_offset_secs)
        lo = end - float(window_secs)
        with self._lock:
            ring = self._sources.get(str(component), ())
            picked = [
                w for w in ring
                if float(w.get("t1", 0.0)) > lo
                and float(w.get("t0", 0.0)) < end
            ]
            if str(component) == "" and self._local_open is not None:
                o = self._local_open
                closed_t0s = {w.get("t0") for w in picked}
                # Skip once the same accumulation has closed into the
                # ring (same t0) — else its samples would count twice.
                if (o.get("t0") not in closed_t0s
                        and float(o.get("t1", 0.0)) > lo
                        and float(o.get("t0", 0.0)) < end):
                    picked = picked + [o]
        return merge_windows(picked) if picked else None

    def render(self, component: str, window_secs: float = 60.0,
               base_secs: Optional[float] = None,
               spans: Optional[List[dict]] = None,
               now: Optional[float] = None, top: int = 100) -> dict:
        """The ``/profile`` JSON body. With ``spans``, span-derived
        phase samples merge into the flame view under the ``phases``
        class; with ``base_secs``, a same-length window ending that
        many seconds earlier renders as a differential."""
        self.pull_local()
        component = str(component)
        now = time.time() if now is None else now
        window = self.merged(component, window_secs, now=now)
        if window is None:
            return {
                "component": component,
                "window_secs": window_secs,
                "error": f"no profile windows for {component!r}",
                "components": self.components(),
            }
        combined = dict(window["samples"])
        if spans:
            role, instance = component_role(component)
            for stack, count in fold_spans(
                spans, window["hz"], role=role, instance=instance
            ).items():
                combined[stack] = combined.get(stack, 0) + count
        window = dict(window)
        window["samples"] = combined
        out = {
            "component": component,
            "window_secs": float(window_secs),
            "window": window,
            "folded": folded_text(combined),
            "pprof": pprof_json(window),
        }
        if base_secs is not None:
            base = self.merged(
                component, window_secs, now=now,
                end_offset_secs=float(base_secs),
            )
            if base is not None:
                out["base"] = base
                out["diff"] = diff_profiles(window, base, top=top)
            else:
                out["base"] = None
                out["diff"] = []
        return out

    def bundle_capture(self, window_secs: float = 120.0,
                       now: Optional[float] = None) -> dict:
        """The incident bundle's ``profile.json`` payload: one merged
        window + folded text per component with recent data — the
        2 a.m. flame graph of every role at the moment the rule
        fired."""
        self.pull_local()
        now = time.time() if now is None else now
        components = {}
        with self._lock:
            names = [s for s, ring in self._sources.items() if ring]
        for name in names:
            window = self.merged(name, window_secs, now=now)
            if window is None:
                continue
            components[name] = {
                "window": window,
                "folded": folded_text(window["samples"]),
            }
        return {
            "window_secs": float(window_secs),
            "captured_at": now,
            "components": components,
        }


# ---- flame-table reductions (dump_metrics --profile) --------------------


def top_frames(samples: Dict[str, int], top: int = 25) -> List[dict]:
    """Per-frame self/total attribution over a folded flame table:
    ``self`` counts stacks where the frame is the leaf, ``total``
    counts every stack containing it — the two columns a human reads
    first."""
    grand = sum(samples.values()) or 1
    self_counts: Dict[str, int] = {}
    total_counts: Dict[str, int] = {}
    for stack, count in samples.items():
        frames = stack.split(";")
        self_counts[frames[-1]] = (
            self_counts.get(frames[-1], 0) + count
        )
        for frame in set(frames):
            total_counts[frame] = total_counts.get(frame, 0) + count
    out = [
        {
            "frame": frame,
            "self": int(self_counts.get(frame, 0)),
            "total": int(total),
            "self_pct": round(
                100.0 * self_counts.get(frame, 0) / grand, 2
            ),
            "total_pct": round(100.0 * total / grand, 2),
        }
        for frame, total in total_counts.items()
    ]
    out.sort(key=lambda d: (-d["self"], -d["total"], d["frame"]))
    return out[: int(top)]
