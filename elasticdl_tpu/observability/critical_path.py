"""Critical-path + straggler attribution over collected span trees.

Reduces a run's spans (``tracing`` dicts) into the question the
histogram tail can't answer: *which phase dominated the slow tasks and
the slow steps?* A task span's direct children are its phases (the
get_task RPC, per-batch fetch / device_step, checkpoint, the report
RPC); whatever the children don't cover is ``self`` time. The report
names the dominant phase of the p99 task and the p99 step, and breaks
p50 vs p99 down per phase so a fat tail with a healthy median reads as
"row pulls stall the stragglers", not just "p99 is high".
"""

import json
from typing import Dict, List, Optional

TASK_SPAN = "task"
STEP_SPAN = "device_step"
SELF_PHASE = "self"


def build_index(spans: List[dict]):
    """(by_id, children) maps; children lists keep recording order."""
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    children: Dict[str, List[dict]] = {}
    for s in spans:
        parent = s.get("parent_id")
        if parent:
            children.setdefault(parent, []).append(s)
    return by_id, children


def subtree(span: dict, children: Dict[str, List[dict]]) -> List[dict]:
    """The span plus every descendant reachable through parent links."""
    out = []
    todo = [span]
    while todo:
        node = todo.pop()
        out.append(node)
        todo.extend(children.get(node.get("span_id"), ()))
    return out


def phase_breakdown(span: dict,
                    children: Dict[str, List[dict]]) -> Dict[str, float]:
    """Direct-child durations grouped by span name, plus ``self`` (the
    parent's time not covered by any child). Children overlapping the
    parent's end (async stragglers) are clamped into it."""
    total = float(span.get("dur", 0.0))
    phases: Dict[str, float] = {}
    covered = 0.0
    for child in children.get(span.get("span_id"), ()):
        dur = min(float(child.get("dur", 0.0)), total)
        phases[child["name"]] = phases.get(child["name"], 0.0) + dur
        covered += dur
    phases[SELF_PHASE] = max(0.0, total - covered)
    return phases


def dominant_phase(phases: Dict[str, float]) -> str:
    if not phases:
        return SELF_PHASE
    return max(sorted(phases), key=lambda k: phases[k])


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile; 0.0 on empty input."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = min(len(ordered) - 1, max(0, int(round(
        (q / 100.0) * (len(ordered) - 1)
    ))))
    return ordered[rank]


def _attributed(span: dict, children) -> dict:
    phases = phase_breakdown(span, children)
    return {
        "dur_secs": round(float(span.get("dur", 0.0)), 6),
        "role": span.get("role"),
        "instance": span.get("instance"),
        "attrs": span.get("attrs", {}),
        "dominant_phase": dominant_phase(phases),
        "phases": {k: round(v, 6) for k, v in sorted(phases.items())},
    }


def _group_report(group: List[dict], children, top_k: int) -> dict:
    durs = [float(s.get("dur", 0.0)) for s in group]
    p50 = percentile(durs, 50)
    p99 = percentile(durs, 99)
    by_dur = sorted(group, key=lambda s: float(s.get("dur", 0.0)))
    # The attributed exemplar is the span AT the nearest-rank p99, not
    # the max — in large groups a single extreme outlier must not make
    # the headline "p99 task" contradict p99_secs (the outlier still
    # shows up in stragglers).
    p99_span = None
    if by_dur:
        idx = min(len(by_dur) - 1, max(0, int(round(
            0.99 * (len(by_dur) - 1)
        ))))
        p99_span = by_dur[idx]

    def mean_phases(selection: List[dict]) -> Dict[str, float]:
        acc: Dict[str, float] = {}
        for s in selection:
            for name, dur in phase_breakdown(s, children).items():
                acc[name] = acc.get(name, 0.0) + dur
        n = max(1, len(selection))
        return {k: round(v / n, 6) for k, v in sorted(acc.items())}

    fast = [s for s in by_dur if float(s.get("dur", 0.0)) <= p50]
    slow = [s for s in by_dur if float(s.get("dur", 0.0)) >= p99] or (
        [p99_span] if p99_span else []
    )
    return {
        "count": len(group),
        "p50_secs": round(p50, 6),
        "p99_secs": round(p99, 6),
        "p50_phase_means": mean_phases(fast),
        "p99_phase_means": mean_phases(slow),
        "p99": _attributed(p99_span, children) if p99_span else None,
        "stragglers": [
            _attributed(s, children) for s in reversed(by_dur[-top_k:])
        ],
    }


def analyze(spans: List[dict], top_k: int = 3) -> dict:
    """The critical-path / straggler report for one collected run."""
    _, children = build_index(spans)
    tasks = [s for s in spans if s.get("name") == TASK_SPAN]
    steps = [s for s in spans if s.get("name") == STEP_SPAN]
    report = {
        "span_count": len(spans),
        "trace_count": len({s.get("trace_id") for s in spans}),
        "tasks": _group_report(tasks, children, top_k) if tasks else None,
        "steps": _group_report(steps, children, top_k) if steps else None,
    }
    return report


def render_report(report: dict) -> str:
    """Human-oriented text rendering of ``analyze()``'s dict."""
    lines = [
        f"spans: {report['span_count']}  "
        f"traces: {report['trace_count']}",
    ]
    for kind in ("tasks", "steps"):
        group = report.get(kind)
        if not group:
            lines.append(f"{kind}: none recorded")
            continue
        lines.append(
            f"{kind}: n={group['count']}  p50={group['p50_secs']:.4f}s  "
            f"p99={group['p99_secs']:.4f}s"
        )
        p99 = group.get("p99")
        if p99:
            phases = ", ".join(
                f"{name}={dur:.4f}s"
                for name, dur in p99["phases"].items() if dur > 0
            )
            lines.append(
                f"  p99 {kind[:-1]}: {p99['dur_secs']:.4f}s "
                f"dominated by [{p99['dominant_phase']}]  ({phases})"
            )
        lines.append(
            "  p50 phase means: " + json.dumps(group["p50_phase_means"])
        )
        lines.append(
            "  p99 phase means: " + json.dumps(group["p99_phase_means"])
        )
    return "\n".join(lines) + "\n"
