"""Distributed tracing: spans, flight recorder, cross-RPC context.

The metrics plane (registry/aggregator) answers *how much / how often*;
this module answers *where the time went for one task or one request*.
A ``Tracer`` produces **spans** — named intervals with a ``trace_id``
(the tree they belong to), ``span_id``, ``parent_id``, attributes, and
monotonic ``t0``/``dur`` — into a bounded per-process ring buffer, the
**flight recorder**. Trace context rides thread-locally within a
process and as a ``_trace_ctx`` field on the framework's RPCs
(``comm/rpc.py``), so one task's tree spans master dispatch → worker
step phases → row-service pulls in a single connected tree.

Cost discipline (same as the chaos seams): with **no recorder
installed** every ``span()`` call is one module-global read returning a
shared no-op span — the instrumented step loop pays nothing measurable
(guarded by a microbenchmark in tests/test_tracing.py). Span ids come
from ``uuid4`` (urandom), never wall-clock, so installing a recorder
cannot perturb chaos determinism (same-seed reports stay
byte-identical; the recorder is only *dumped* into red reports).

Collection piggybacks on the worker-snapshot RPCs the metrics
aggregator already uses: ``spans_since`` gives each reporter an
incremental cursor into the ring, and the master's ``TraceCollector``
dedups by span id (several in-process workers may share one recorder).
Export to Chrome/Perfetto JSON lives in ``trace_export.py``;
critical-path / straggler attribution in ``critical_path.py``.
"""

import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

# ---- process-global recorder seam (None = tracing off) ------------------

_RECORDER: Optional["FlightRecorder"] = None
_PROCESS_ROLE: Tuple[str, str] = ("process", "0")
_local = threading.local()  # .stack: [(trace_id, span_id, role, instance)]


def enabled() -> bool:
    return _RECORDER is not None


def install_recorder(rec: "FlightRecorder") -> "FlightRecorder":
    """Install (or replace) the process flight recorder; spans start
    recording on the next ``span()`` call."""
    global _RECORDER
    _RECORDER = rec
    return rec


def uninstall_recorder():
    global _RECORDER
    _RECORDER = None


def recorder() -> Optional["FlightRecorder"]:
    return _RECORDER


def recorder_spans() -> List[dict]:
    """Current ring contents, oldest first; [] when tracing is off."""
    rec = _RECORDER
    return rec.snapshot() if rec is not None else []


def spans_since(cursor: int) -> Tuple[List[dict], int]:
    """Incremental ring read for piggyback reporters: spans recorded
    after ``cursor`` plus the new cursor. ([], cursor) when off."""
    rec = _RECORDER
    if rec is None:
        return [], cursor
    return rec.since(cursor)


def set_process_role(role: str, instance: str = "0"):
    """Default (role, instance) for spans opened with no enclosing
    context — process mains set this once (master / worker / serving)."""
    global _PROCESS_ROLE
    _PROCESS_ROLE = (str(role), str(instance))


def current_ctx() -> Optional[dict]:
    """Wire form of the innermost open span, or None — what
    ``RpcStub.call`` injects as ``_trace_ctx``."""
    stack = getattr(_local, "stack", None)
    if not stack:
        return None
    trace_id, span_id, _role, _instance = stack[-1]
    return {"trace_id": trace_id, "span_id": span_id}


def current_trace_id() -> Optional[str]:
    """Trace id of the innermost open span, or None — what
    exemplar-enabled histograms stamp onto sampled observations
    (``registry.py``): two thread-local reads, cheap enough for any
    hot path."""
    stack = getattr(_local, "stack", None)
    return stack[-1][0] if stack else None


def _new_id() -> str:
    # uuid4 = urandom: identity never derives from wall-clock (chaos
    # same-seed byte-identity must survive a recorder being installed).
    return uuid.uuid4().hex[:16]


class _NullSpan:
    """Shared no-op span returned whenever no recorder is installed —
    the entire disabled-path cost of an instrumented region."""

    __slots__ = ()
    trace_id = None
    span_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def discard(self):
        return self

    def ctx(self):
        return None


NULL_SPAN = _NullSpan()


class Span:
    """One named interval; a context manager that records itself into
    the flight recorder on exit (unless ``discard()``-ed)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "role",
                 "instance", "attrs", "t0", "dur", "tid", "_recorder",
                 "_discard", "_stack")

    def __init__(self, rec: "FlightRecorder", name: str, trace_id: str,
                 parent_id: Optional[str], role: str, instance: str,
                 attrs: dict):
        self._recorder = rec
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.role = role
        self.instance = instance
        self.attrs = attrs
        self.t0 = 0.0
        self.dur = 0.0
        self.tid = 0
        self._discard = False
        self._stack = None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def discard(self) -> "Span":
        """Drop this span at exit (e.g. a task-cycle that turned out to
        be a WAIT poll — recording it would pollute latency stats)."""
        self._discard = True
        return self

    def ctx(self) -> dict:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def __enter__(self) -> "Span":
        self.t0 = time.monotonic()
        self.tid = threading.get_ident()
        stack = getattr(_local, "stack", None)
        if stack is None:
            stack = _local.stack = []
        stack.append((self.trace_id, self.span_id, self.role,
                      self.instance))
        # Remember WHICH stack we pushed onto: a span held open across
        # a generator yield can be finalized on a different thread
        # (GeneratorExit during GC) — exiting must remove our own entry
        # from the entering thread's stack, never blind-pop whatever is
        # innermost on the finalizing thread.
        self._stack = stack
        return self

    def __exit__(self, exc_type, exc, tb):
        self.dur = time.monotonic() - self.t0
        stack = self._stack
        if stack:
            if stack[-1][1] == self.span_id:
                stack.pop()
            else:
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i][1] == self.span_id:
                        del stack[i]
                        break
        if self._discard:
            return False
        if exc_type is not None and "error" not in self.attrs:
            self.attrs["error"] = exc_type.__name__
        self._recorder.add(self.to_dict())
        return False

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "role": self.role,
            "instance": self.instance,
            "tid": int(self.tid),
            "t0": float(self.t0),
            "dur": float(self.dur),
            "attrs": self.attrs,
        }


class Tracer:
    """Span factory pinned to one (role, instance) — e.g.
    ``Tracer("worker", "3")``. Parenthood comes from the thread's
    innermost open span; a span opened with no parent starts a new
    trace."""

    __slots__ = ("role", "instance")

    def __init__(self, role: str, instance: str = "0"):
        self.role = str(role)
        self.instance = str(instance)

    def span(self, name: str, **attrs):
        rec = _RECORDER
        if rec is None:
            return NULL_SPAN
        stack = getattr(_local, "stack", None)
        if stack:
            trace_id, parent_id = stack[-1][0], stack[-1][1]
        else:
            trace_id, parent_id = _new_id(), None
        return Span(rec, name, trace_id, parent_id, self.role,
                    self.instance, attrs)


def span(name: str, **attrs):
    """Span under the ambient context: role/instance inherit from the
    enclosing span (so e.g. an RPC retry span inside a worker's task
    tree lands on the worker track), falling back to the process
    role."""
    rec = _RECORDER
    if rec is None:
        return NULL_SPAN
    stack = getattr(_local, "stack", None)
    if stack:
        trace_id, parent_id, role, instance = stack[-1]
        return Span(rec, name, trace_id, parent_id, role, instance, attrs)
    role, instance = _PROCESS_ROLE
    return Span(rec, name, _new_id(), None, role, instance, attrs)


def child_span(name: str, ctx: Optional[dict], **attrs):
    """Span with an EXPLICIT parent context — for work fanned out to a
    pool thread whose thread-local stack does not carry the caller's
    open span (the in-process analogue of ``server_span``; e.g. the
    host engine's per-table pull futures). Role/instance come from the
    calling thread's innermost span when one is open (same-thread
    callers keep their track), else the process role. ``ctx`` of None
    starts a fresh trace."""
    rec = _RECORDER
    if rec is None:
        return NULL_SPAN
    stack = getattr(_local, "stack", None)
    if stack:
        role, instance = stack[-1][2], stack[-1][3]
    else:
        role, instance = _PROCESS_ROLE
    if ctx and ctx.get("trace_id"):
        return Span(rec, name, str(ctx["trace_id"]),
                    str(ctx.get("span_id") or "") or None,
                    role, instance, attrs)
    return Span(rec, name, _new_id(), None, role, instance, attrs)


def server_span(name: str, wire_ctx: Optional[dict], role: str,
                instance: str = "0", **attrs):
    """Server-side child of a propagated ``_trace_ctx`` (or a fresh
    root when the caller sent none) — what the RPC handler wrap opens."""
    rec = _RECORDER
    if rec is None:
        return NULL_SPAN
    if wire_ctx and wire_ctx.get("trace_id"):
        return Span(rec, name, str(wire_ctx["trace_id"]),
                    str(wire_ctx.get("span_id") or "") or None,
                    role, instance, attrs)
    return Span(rec, name, _new_id(), None, role, instance, attrs)


def record_span(name: str, t0: float, dur: float, *,
                trace_id: Optional[str] = None,
                parent_id: Optional[str] = None,
                role: Optional[str] = None, instance: str = "0",
                tid: Optional[int] = None, **attrs):
    """Retro-record a span whose interval was measured elsewhere (e.g.
    serving queue-wait: enqueue happened on the handler thread, the
    wait is known only when the batcher pops the request)."""
    rec = _RECORDER
    if rec is None:
        return None
    if role is None:
        role = _PROCESS_ROLE[0]
    entry = {
        "name": name,
        "trace_id": trace_id or _new_id(),
        "span_id": _new_id(),
        "parent_id": parent_id,
        "role": str(role),
        "instance": str(instance),
        "tid": int(tid if tid is not None else threading.get_ident()),
        "t0": float(t0),
        "dur": float(dur),
        "attrs": attrs,
    }
    rec.add(entry)
    return entry


class FlightRecorder:
    """Bounded ring of finished spans (oldest evicted first) with a
    monotonic sequence number for incremental piggyback reads."""

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring = deque(maxlen=self.capacity)
        self._seq = 0

    def add(self, span_dict: dict):
        with self._lock:
            self._seq += 1
            span_dict["seq"] = self._seq
            self._ring.append(span_dict)

    def snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def since(self, cursor: int) -> Tuple[List[dict], int]:
        """Spans with seq > cursor (bounded by what the ring still
        holds) and the new cursor."""
        with self._lock:
            return (
                [s for s in self._ring if s.get("seq", 0) > cursor],
                self._seq,
            )

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


class TraceCollector:
    """Master-side span accumulator: ingests piggybacked span batches,
    dedups by span id (in-process workers share one recorder, so the
    same span can arrive on two reporters' cursors), bounded FIFO."""

    def __init__(self, capacity: int = 65536):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._spans: "OrderedDict[str, dict]" = OrderedDict()

    def ingest(self, spans) -> int:
        if not spans:
            return 0
        added = 0
        with self._lock:
            for entry in spans:
                if not isinstance(entry, dict):
                    continue
                sid = entry.get("span_id")
                if not sid or sid in self._spans:
                    continue
                self._spans[sid] = entry
                added += 1
            while len(self._spans) > self.capacity:
                self._spans.popitem(last=False)
        return added

    def spans(self) -> List[dict]:
        with self._lock:
            return list(self._spans.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)
