"""Workload principals: who an RPC is *for*, threaded through the fleet.

Every metric/span/profile in the stack is per-component (worker 3,
rowservice 0) — useful for "where is the time going", useless for "who
is spending it" once several workloads share one row tier. A
**principal** is the attribution identity ``{job, component, purpose}``:

- ``job``        — the workload name ("mnist-train", "ranker-serve");
                   free-form, so metering folds overflow to
                   ``__other__`` (``usage.fold_job``) to bound label
                   cardinality;
- ``component``  — which part of the job issued the call ("worker",
                   "router", "master");
- ``purpose``    — WHY the bytes moved, from the closed enum
                   ``PURPOSES``: client training pushes vs serving
                   reads vs the system's own internal traffic
                   (migration streams, replica refresh, WAL replay,
                   checkpoint capture, control-plane chatter).

Propagation mirrors tracing (``tracing.py``): an ambient thread-local
stack, a process-wide default (``set_process_principal`` — analogous
to ``set_process_role``), and a ``_principal`` piggyback field that
``RpcStub.call`` injects next to ``_trace_ctx`` and the server wrap
strips before the handler runs. Internal fan-outs self-tag by wrapping
their loop in ``pushed(purpose=...)`` — migration chunks, replica
refresh threads, and WAL replay inherit job/component from the ambient
principal and override only the purpose, so no per-call-site plumbing.

Unlike tracing, principals flow even with no flight recorder
installed: attribution is always-on metering, not sampling. The cost
with no ambient principal set is one thread-local read per RPC. The
``set_enabled(False)`` kill-switch (used by the attribution drill's
baseline phase) turns both the piggyback and the usage meters into
no-ops so overhead can be measured against a true off state.
"""

import threading
from contextlib import contextmanager
from typing import Optional

# Closed purpose enum. "unknown" is the absence value — never sent on
# the wire, only synthesized server-side when a request carried no (or
# a malformed) principal — so the attribution drill's ">=95% of handler
# time is non-unknown" gate is measurable from the labels alone.
PURPOSES = (
    "training",
    "serving_read",
    "migration",
    "replica_refresh",
    "replay",
    "checkpoint",
    "control",
    "streaming_ingest",
    "canary",
)
UNKNOWN = "unknown"

_local = threading.local()  # .stack: [Principal]
_PROCESS_DEFAULT: Optional["Principal"] = None
_ENABLED = True


class Principal:
    """Immutable attribution identity. ``purpose`` outside ``PURPOSES``
    is coerced to ``unknown`` (the wire is untrusted; the label set is
    closed)."""

    __slots__ = ("job", "component", "purpose")

    def __init__(self, job: str = UNKNOWN, component: str = UNKNOWN,
                 purpose: str = UNKNOWN):
        object.__setattr__(self, "job", str(job) or UNKNOWN)
        object.__setattr__(self, "component", str(component) or UNKNOWN)
        purpose = str(purpose)
        if purpose not in PURPOSES and purpose != UNKNOWN:
            purpose = UNKNOWN
        object.__setattr__(self, "purpose", purpose)

    def __setattr__(self, name, value):
        raise AttributeError("Principal is immutable")

    def __repr__(self):
        return (f"Principal(job={self.job!r}, "
                f"component={self.component!r}, "
                f"purpose={self.purpose!r})")

    def __eq__(self, other):
        return (isinstance(other, Principal)
                and self.job == other.job
                and self.component == other.component
                and self.purpose == other.purpose)

    def __hash__(self):
        return hash((self.job, self.component, self.purpose))

    def wire(self) -> dict:
        """The ``_principal`` piggyback payload."""
        return {"job": self.job, "component": self.component,
                "purpose": self.purpose}

    def replace(self, job: Optional[str] = None,
                component: Optional[str] = None,
                purpose: Optional[str] = None) -> "Principal":
        return Principal(
            self.job if job is None else job,
            self.component if component is None else component,
            self.purpose if purpose is None else purpose,
        )


NOBODY = Principal()  # the all-unknown principal (absence value)


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> bool:
    """Attribution kill-switch: off disables the RPC piggyback and all
    usage metering (``usage.py``). The drill's overhead gate compares
    enabled vs disabled p99 — disabled must be a true zero-cost path,
    not 'enabled but discarded'. Returns the previous state."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(on)
    return prev


def set_process_principal(job: Optional[str] = None,
                          component: Optional[str] = None,
                          purpose: Optional[str] = None):
    """Process-wide fallback for threads with no pushed principal —
    process mains set this once (worker → purpose=training, serving →
    serving_read, master → control), like ``tracing.set_process_role``.
    ``None`` for every field clears it."""
    global _PROCESS_DEFAULT
    if job is None and component is None and purpose is None:
        _PROCESS_DEFAULT = None
    else:
        _PROCESS_DEFAULT = Principal(job or UNKNOWN,
                                     component or UNKNOWN,
                                     purpose or UNKNOWN)


def current() -> Optional["Principal"]:
    """Innermost pushed principal, else the process default, else
    None."""
    stack = getattr(_local, "stack", None)
    if stack:
        return stack[-1]
    return _PROCESS_DEFAULT


def current_wire() -> Optional[dict]:
    """What ``RpcStub.call`` injects as ``_principal`` — None when
    attribution is off or nothing is ambient (the server then meters
    the request as ``unknown``)."""
    if not _ENABLED:
        return None
    principal = current()
    return principal.wire() if principal is not None else None


def from_wire(payload) -> Optional["Principal"]:
    """Parse a ``_principal`` piggyback dict; malformed fields coerce
    to ``unknown`` (the Principal constructor enforces the closed
    purpose enum), non-dicts to None."""
    if not isinstance(payload, dict):
        return None
    return Principal(
        payload.get("job") or UNKNOWN,
        payload.get("component") or UNKNOWN,
        payload.get("purpose") or UNKNOWN,
    )


@contextmanager
def pushed(job: Optional[str] = None, component: Optional[str] = None,
           purpose: Optional[str] = None,
           principal: Optional["Principal"] = None):
    """Push a principal for the dynamic extent of a block. Unset
    fields inherit from the current ambient principal, so internal
    fan-outs override only what changed::

        with principal.pushed(purpose="migration"):
            transport.call("ingest_rows", ...)  # rides as migration

    ``principal=`` pushes an explicit Principal verbatim (the server
    wrap re-establishing the caller's wire identity)."""
    if principal is None:
        base = current() or NOBODY
        principal = base.replace(job=job, component=component,
                                 purpose=purpose)
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append(principal)
    try:
        yield principal
    finally:
        # Remove OUR entry even if a generator finalized out of order
        # (same discipline as tracing.Span.__exit__).
        if stack and stack[-1] is principal:
            stack.pop()
        else:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is principal:
                    del stack[i]
                    break


def span_attrs(principal: Optional["Principal"]) -> dict:
    """Principal fields as span attributes (``principal_job`` /
    ``principal_component`` / ``principal_purpose``) — what the server
    wrap tags onto ``serve/*`` spans so ``critical_path.py`` and
    ``/profile`` can filter by workload. Empty when nothing to tag."""
    if principal is None:
        return {}
    return {
        "principal_job": principal.job,
        "principal_component": principal.component,
        "principal_purpose": principal.purpose,
    }
