"""Declarative SLOs over the time-series store: burn-rate alerting +
triggered black-box incident capture.

Three rule kinds, all evaluated on the master tick against
``observability/timeseries.TimeSeriesStore``:

- ``burn_rate`` — the SRE-workbook shape: an objective ("99% of
  ``rpc_client_seconds`` observations finish under 1 s") defines an
  error *budget* (1 − objective); the observed bad fraction over a
  window, divided by the budget, is the **burn rate** (1.0 = spending
  the budget exactly as fast as allowed). The rule fires only when
  BOTH a long and a short window exceed the threshold — the long
  window gives significance, the short window makes the alert reset
  promptly once the problem stops (no hour-long tail of a transient).
  The SLI is either a latency histogram with ``latency_threshold``
  (bad = observations above it, derived from bucket deltas) or a
  ``bad_series``/``series`` counter pair (bad = e.g. error total).
- ``threshold`` — a windowed aggregation (``p50``/``p99``/``mean``/
  ``rate``/``last``/``max``/``min``) compared against a value.
- ``absence`` — a series that was reporting has gone stale: its
  ``last_seen`` froze more than ``staleness_secs`` ago (the sampler
  freezes it the moment a reporter stops piggybacking snapshots —
  see ``TimeSeriesStore``). Offenders older than ``forget_secs`` are
  dropped from the alert: a worker that legitimately scaled away must
  not page forever.

Rule states surface three ways: the ``/alerts`` JSON endpoint next to
``/metrics``, ``edl_tpu_alert_active{rule}`` gauges (scrapeable, so a
real Prometheus can page on them), and zero-duration spans on the
master trace track at every transition (the alert appears on the same
Perfetto timeline as the tasks it indicts).

When a rule transitions to firing, the ``IncidentRecorder`` captures a
self-contained black-box bundle to disk — flight-recorder spans from
every role (Perfetto-loadable), the time-series window around the
breach, the critical-path p99 attribution, and the master journal tail
— so a transient 2 a.m. degradation leaves an artifact instead of
nothing. ``tools/check_incident.py`` schema-checks bundles;
``make slo-smoke`` drills the whole loop (docs/observability.md).
"""

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability.timeseries import TimeSeriesStore

logger = get_logger("slo")

BURN_RATE = "burn_rate"
THRESHOLD = "threshold"
ABSENCE = "absence"
KINDS = (BURN_RATE, THRESHOLD, ABSENCE)

AGGREGATIONS = ("p50", "p90", "p99", "mean", "rate", "last", "max",
                "min")


@dataclasses.dataclass
class SLORule:
    """One declarative rule (see module docstring for semantics). The
    JSON rule-file form is exactly these field names; unknown fields
    are rejected so a typo'd rule fails at load, not silently never
    fires."""

    name: str
    kind: str
    series: str                       # family name, e.g. edl_tpu_rpc_client_seconds
    labels: Optional[Dict[str, str]] = None  # label subset filter
    source: Optional[str] = None      # reporter filter ("" = master-local)
    # burn_rate:
    objective: float = 0.99
    latency_threshold: Optional[float] = None  # seconds; histogram SLI
    bad_series: str = ""              # counter-pair SLI numerator
    long_window_secs: float = 300.0
    short_window_secs: float = 60.0
    burn_rate_threshold: float = 4.0
    # threshold:
    aggregation: str = "p99"
    op: str = ">"
    value: float = 0.0
    window_secs: float = 60.0
    # absence:
    staleness_secs: float = 120.0
    forget_secs: float = 0.0          # 0 = 4 × staleness_secs
    # common:
    min_count: int = 1                # observations needed before judging
    description: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown SLO rule kind {self.kind!r}")
        if self.kind == BURN_RATE:
            if not (0.0 < self.objective < 1.0):
                raise ValueError(
                    f"{self.name}: objective must be in (0, 1)"
                )
            if self.latency_threshold is None and not self.bad_series:
                raise ValueError(
                    f"{self.name}: burn_rate needs latency_threshold "
                    "(histogram SLI) or bad_series (counter SLI)"
                )
            if self.short_window_secs > self.long_window_secs:
                raise ValueError(
                    f"{self.name}: short window exceeds long window"
                )
        if self.kind == THRESHOLD:
            if self.aggregation not in AGGREGATIONS:
                raise ValueError(
                    f"{self.name}: unknown aggregation "
                    f"{self.aggregation!r}"
                )
            if self.op not in (">", "<", ">=", "<="):
                raise ValueError(f"{self.name}: unknown op {self.op!r}")
        if not self.forget_secs:
            self.forget_secs = 4.0 * self.staleness_secs
        if self.kind == ABSENCE \
                and self.forget_secs <= self.staleness_secs:
            # The offender window is (staleness, forget]; inverted
            # bounds would load cleanly and never fire — exactly the
            # silent misconfiguration this validation exists to stop.
            raise ValueError(
                f"{self.name}: forget_secs ({self.forget_secs}) must "
                f"exceed staleness_secs ({self.staleness_secs})"
            )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SLORule":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown SLO rule fields {sorted(unknown)} "
                f"in {d.get('name', '<unnamed>')!r}"
            )
        return cls(**d)


def load_rules(path: str) -> List[SLORule]:
    """Rule file: JSON ``{"rules": [{...}, ...]}`` (or a bare list)."""
    with open(path) as fh:
        raw = json.load(fh)
    if isinstance(raw, dict):
        raw = raw.get("rules", [])
    return [SLORule.from_dict(d) for d in raw]


def default_rules() -> List[SLORule]:
    """Built-in rules any training master benefits from; rules over
    families that never report simply stay idle (min_count)."""
    return [
        SLORule(
            name="rpc-latency-burn",
            kind=BURN_RATE,
            series="edl_tpu_rpc_client_seconds",
            latency_threshold=1.0,
            objective=0.99,
            long_window_secs=300.0,
            short_window_secs=60.0,
            burn_rate_threshold=4.0,
            min_count=20,
            description="control/row-plane RPC attempts slower than "
                        "1s are burning >4x the 1% error budget",
        ),
        SLORule(
            name="worker-absent",
            kind=ABSENCE,
            series="edl_tpu_worker_step_seconds",
            staleness_secs=600.0,
            description="a worker that was reporting step telemetry "
                        "has gone silent (not scaled away)",
        ),
        SLORule(
            name="primary-heartbeat-absent",
            kind=ABSENCE,
            series="edl_tpu_master_primary_heartbeat_seconds",
            staleness_secs=120.0,
            description="the hot standby stopped confirming primary "
                        "heartbeats (it reports them into the cluster "
                        "view via ComponentMetricsReporter): either "
                        "the standby died or it can no longer see the "
                        "primary — failover protection is gone "
                        "(docs/fault_tolerance.md)",
        ),
        SLORule(
            name="row-push-log-fsync-stall",
            kind=THRESHOLD,
            series="edl_tpu_row_push_log_fsync_seconds",
            aggregation="p99",
            op=">",
            value=0.25,
            window_secs=300.0,
            min_count=10,
            description="push-log group commits stalling >250ms at "
                        "p99: durable-ack pushes are paying the "
                        "stall directly, and in applied-ack mode the "
                        "RPO window is growing past its group-ms "
                        "budget — usually a sick WAL disk "
                        "(docs/fault_tolerance.md 'Zero-RPO row "
                        "plane')",
        ),
        # Per-workload burn (docs/observability.md "Workload
        # attribution"): the usage plane's handler-time histogram
        # carries a bounded ``purpose`` label, so burn accounting can
        # target one workload class without the others' traffic
        # diluting (or inflating) its error budget — serving reads
        # burn against a tight latency bound while training pushes get
        # a looser one, on the SAME family.
        SLORule(
            name="usage-burn-serving-read",
            kind=BURN_RATE,
            series="edl_tpu_usage_handler_seconds",
            labels={"purpose": "serving_read"},
            latency_threshold=0.25,
            objective=0.99,
            long_window_secs=300.0,
            short_window_secs=60.0,
            burn_rate_threshold=4.0,
            min_count=20,
            description="serving-read row handlers slower than 250ms "
                        "are burning >4x the 1% error budget — scoped "
                        "to purpose=serving_read, so a training push "
                        "storm cannot mask (or trigger) it",
        ),
        SLORule(
            name="usage-burn-training",
            kind=BURN_RATE,
            series="edl_tpu_usage_handler_seconds",
            labels={"purpose": "training"},
            latency_threshold=1.0,
            objective=0.99,
            long_window_secs=300.0,
            short_window_secs=60.0,
            burn_rate_threshold=4.0,
            min_count=20,
            description="training push/pull handlers slower than 1s "
                        "are burning >4x the 1% error budget — scoped "
                        "to purpose=training",
        ),
        SLORule(
            name="row-freshness",
            kind=THRESHOLD,
            series="edl_tpu_row_freshness_seconds",
            aggregation="p99",
            op=">",
            value=60.0,
            window_secs=300.0,
            min_count=5,
            description="push-to-servable latency p99 above 60s: "
                        "serving reads are going stale "
                        "(docs/observability.md)",
        ),
        # Streaming watermark stall (master/stream_ingest.py): the
        # oldest uncommitted stream record aging past 5 minutes means
        # the train→serve loop is open — workers are not resolving
        # stream tasks (fleet dead/lagging, backpressure wedge, or a
        # master that stopped pumping). docs/online_learning.md.
        SLORule(
            name="stream-watermark-stall",
            kind=THRESHOLD,
            series="edl_tpu_stream_ingest_watermark_lag_seconds",
            aggregation="max",
            op=">",
            value=300.0,
            window_secs=300.0,
            min_count=5,
            description="a stream partition's committed watermark has "
                        "lagged the tail by >5 minutes across the "
                        "window: online learning has stalled "
                        "(docs/online_learning.md)",
        ),
        # Gang-scheduler starvation (master/scheduler.py): submitted
        # jobs should either schedule or preempt their way in within
        # an arbitration window. The mean of the submitted-state gauge
        # staying above 0.5 for the whole window means at least one
        # job sat admitted-but-never-arbitrated — a wedged tick loop,
        # a gang larger than the fleet will ever be, or priorities
        # starving the tail (docs/scheduler.md "Starvation").
        SLORule(
            name="sched-job-starved",
            kind=THRESHOLD,
            series="edl_tpu_sched_jobs",
            labels={"state": "submitted"},
            aggregation="mean",
            op=">",
            value=0.5,
            window_secs=300.0,
            min_count=10,
            description="a submitted job has sat unscheduled for the "
                        "whole evaluation window: the fleet never fit "
                        "its gang and nothing preempted to admit it "
                        "(docs/scheduler.md)",
        ),
        # Outside-in SLIs (observability/prober.py): the synthetic
        # canary plane's black-box probes are the first rules whose
        # inputs come from OUTSIDE the components — a probe failure
        # means a user-visible contract broke, whatever the white-box
        # families claim.
        SLORule(
            name="probe-failure-burn",
            kind=BURN_RATE,
            series="edl_tpu_probe_attempts_total",
            bad_series="edl_tpu_probe_failures_total",
            objective=0.99,
            long_window_secs=300.0,
            short_window_secs=60.0,
            burn_rate_threshold=4.0,
            min_count=4,
            description="black-box probe failure ratio burns the "
                        "outside-in availability budget across probes "
                        "— a user-visible contract (read-your-writes, "
                        "push-to-servable, reshard convergence, stream "
                        "watermark, dispatch) is failing from outside "
                        "(docs/observability.md 'Synthetic probing')",
        ),
        SLORule(
            name="probe-absent",
            kind=ABSENCE,
            series="edl_tpu_probe_attempts_total",
            staleness_secs=120.0,
            forget_secs=900.0,
            description="the prober stopped running: probe attempts "
                        "went stale, so every outside-in SLI above is "
                        "blind — treat monitoring loss as an incident, "
                        "not as green",
        ),
        # Overload plane (comm/overload.py, docs/fault_tolerance.md
        # "Graceful degradation"): shedding BACKGROUND purposes under
        # pressure is the design working, so no rule fires on it —
        # these rules fire on the shapes that mean the design is NOT
        # working: serving reads being shed (priority inversion),
        # clients giving up because the shared retry budget drained
        # (sustained overload, not a blip), breakers stuck open.
        SLORule(
            name="overload-serving-shed",
            kind=THRESHOLD,
            series="edl_tpu_overload_shed_total",
            labels={"purpose": "serving_read"},
            aggregation="rate",
            op=">",
            value=0.0,
            window_secs=60.0,
            min_count=1,
            description="the admission gate shed serving reads — the "
                        "one purpose load shedding exists to protect. "
                        "Background purposes are already fully shed "
                        "and the fleet is STILL saturated: add "
                        "capacity or cut the serving limit "
                        "(docs/fault_tolerance.md 'Graceful "
                        "degradation')",
        ),
        SLORule(
            name="rpc-retry-budget-exhausted",
            kind=THRESHOLD,
            series="edl_tpu_rpc_retry_budget_exhausted_total",
            aggregation="rate",
            op=">",
            value=0.5,
            window_secs=300.0,
            min_count=5,
            description="clients are abandoning retries faster than "
                        "the token bucket refills, sustained across "
                        "the window: the dependency is in prolonged "
                        "overload and unbudgeted callers would be "
                        "amplifying it (docs/fault_tolerance.md)",
        ),
        SLORule(
            name="rpc-breaker-open",
            kind=THRESHOLD,
            series="edl_tpu_rpc_breaker_state",
            aggregation="min",
            op=">=",
            value=1.0,
            window_secs=120.0,
            min_count=5,
            description="a client circuit breaker has not closed for "
                        "a whole window (state 1=open/2=half-open "
                        "throughout): its target is persistently "
                        "unreachable and every caller is failing "
                        "fast, not slow (docs/fault_tolerance.md)",
        ),
    ]


class RollingWindow:
    """Tiny shared helper: a bounded deque of ``(t, ok, latency)``
    samples with windowed error-ratio / quantile reductions — the
    serving router's per-replica SLO status uses it (one per replica;
    the master side uses the full TimeSeriesStore instead)."""

    def __init__(self, window_secs: float = 60.0, capacity: int = 2048):
        self.window_secs = float(window_secs)
        self._samples = deque(maxlen=int(capacity))
        self._lock = threading.Lock()

    def record(self, ok: bool, latency_secs: float,
               now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        with self._lock:
            self._samples.append((now, bool(ok), float(latency_secs)))

    def status(self, now: Optional[float] = None) -> dict:
        now = time.monotonic() if now is None else now
        cutoff = now - self.window_secs
        with self._lock:
            live = [s for s in self._samples if s[0] >= cutoff]
        n = len(live)
        if not n:
            return {"window_secs": self.window_secs, "requests": 0,
                    "error_ratio": 0.0, "p95_ms": 0.0}
        errors = sum(1 for _t, ok, _l in live if not ok)
        lats = sorted(lat for _t, _ok, lat in live)
        p95 = lats[min(n - 1, int(round(0.95 * (n - 1))))]
        return {
            "window_secs": self.window_secs,
            "requests": n,
            "error_ratio": round(errors / n, 4),
            "p95_ms": round(p95 * 1e3, 3),
        }


class SLOEngine:
    """Evaluate rules against the store each master tick; keep per-rule
    firing state; surface transitions as gauges, trace events, and
    incident captures."""

    def __init__(self, store: TimeSeriesStore,
                 rules: Optional[List[SLORule]] = None,
                 metrics_registry=None,
                 incident_recorder: Optional["IncidentRecorder"] = None,
                 clock: Callable[[], float] = time.time):
        from elasticdl_tpu.observability import default_registry

        self.store = store
        self.rules = list(rules if rules is not None else default_rules())
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO rule names in {names}")
        self.incident_recorder = incident_recorder
        self._clock = clock
        self._lock = threading.Lock()
        # rule name -> {"firing", "since", "value", "detail", "fired_count"}
        self._states: Dict[str, dict] = {
            rule.name: {
                "firing": False, "since": None, "value": 0.0,
                "detail": "", "fired_count": 0,
            }
            for rule in self.rules
        }
        registry = metrics_registry or default_registry()
        self._m_active = registry.gauge(
            "alert_active",
            "1 while the named SLO rule is firing", ["rule"],
        )
        self._m_fired = registry.counter(
            "alerts_fired_total",
            "SLO rule transitions to firing", ["rule"],
        )
        self._m_eval_seconds = registry.histogram(
            "slo_eval_seconds", "One full rule-evaluation pass",
        )
        for rule in self.rules:
            self._m_active.labels(rule.name).set(0.0)

    # ---- evaluation ----------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One pass over every rule; returns the alert states (the
        ``/alerts`` body's ``rules`` list)."""
        now = self._clock() if now is None else now
        t0 = time.monotonic()
        out = []
        for rule in self.rules:
            try:
                firing, value, detail = self._eval_rule(rule, now)
            except Exception:
                logger.exception("SLO rule %s evaluation failed",
                                 rule.name)
                continue
            out.append(self._transition(rule, firing, value, detail, now))
        self._m_eval_seconds.observe(time.monotonic() - t0)
        return out

    def _transition(self, rule: SLORule, firing: bool, value: float,
                    detail: str, now: float) -> dict:
        with self._lock:
            state = self._states[rule.name]
            was = state["firing"]
            state["value"] = value
            state["detail"] = detail
            if firing and not was:
                state["firing"] = True
                state["since"] = now
                state["fired_count"] += 1
        if firing and not was:
            self._m_active.labels(rule.name).set(1.0)
            self._m_fired.labels(rule.name).inc()
            self._emit_trace_event(rule, "firing", value, detail)
            logger.warning("SLO ALERT %s firing: %s (value %.4g)",
                           rule.name, detail, value)
            if self.incident_recorder is not None:
                try:
                    self.incident_recorder.capture(
                        self.alert_state(rule.name), now=now
                    )
                except Exception:
                    logger.exception(
                        "incident capture for %s failed", rule.name
                    )
        elif was and not firing:
            with self._lock:
                self._states[rule.name]["firing"] = False
                self._states[rule.name]["since"] = None
            self._m_active.labels(rule.name).set(0.0)
            self._emit_trace_event(rule, "resolved", value, detail)
            logger.info("SLO alert %s resolved", rule.name)
        return self.alert_state(rule.name)

    def _emit_trace_event(self, rule: SLORule, event: str, value: float,
                          detail: str):
        """Zero-duration span on the master track: the alert transition
        lands on the same Perfetto timeline as the tasks it indicts.
        Free when no flight recorder is installed."""
        from elasticdl_tpu.observability import tracing

        tracing.record_span(
            f"alert/{rule.name}", time.monotonic(), 0.0,
            role="master", event=event, rule=rule.name,
            kind=rule.kind, value=round(float(value), 6),
            detail=detail,
        )

    # ---- rule kinds ----------------------------------------------------

    def _eval_rule(self, rule: SLORule, now: float):
        if rule.kind == BURN_RATE:
            return self._eval_burn_rate(rule, now)
        if rule.kind == THRESHOLD:
            return self._eval_threshold(rule, now)
        return self._eval_absence(rule, now)

    def _error_ratio(self, rule: SLORule, window: float, now: float):
        """(bad fraction, observation count) over one window."""
        if rule.latency_threshold is not None:
            count, _total, deltas, ubs = self.store.window_hist(
                rule.series, window, rule.labels, rule.source, now
            )
            if not deltas or count <= 0:
                return 0.0, 0.0
            # Registry buckets are per-bucket (non-cumulative): good =
            # observations in buckets at or under the threshold.
            thr = float(rule.latency_threshold)
            good = sum(
                d for ub, d in zip(ubs, deltas) if ub <= thr + 1e-12
            )
            return max(0.0, (count - good) / count), count
        bad, _n = self.store.window_counter_delta(
            rule.bad_series, window, rule.labels, rule.source, now
        )
        total, _n = self.store.window_counter_delta(
            rule.series, window, rule.labels, rule.source, now
        )
        if total <= 0:
            return 0.0, 0.0
        return min(1.0, max(0.0, bad / total)), total

    def _eval_burn_rate(self, rule: SLORule, now: float):
        long_ratio, long_n = self._error_ratio(
            rule, rule.long_window_secs, now
        )
        short_ratio, _short_n = self._error_ratio(
            rule, rule.short_window_secs, now
        )
        budget = 1.0 - rule.objective
        burn_long = long_ratio / budget
        burn_short = short_ratio / budget
        firing = (
            long_n >= rule.min_count
            and burn_long >= rule.burn_rate_threshold
            and burn_short >= rule.burn_rate_threshold
        )
        detail = (
            f"burn {burn_long:.2f}x/{burn_short:.2f}x "
            f"(long {int(rule.long_window_secs)}s / short "
            f"{int(rule.short_window_secs)}s) of the "
            f"{budget:.2%} budget on {rule.series}; "
            f"threshold {rule.burn_rate_threshold}x, "
            f"n={int(long_n)}"
        )
        return firing, burn_long, detail

    def _eval_threshold(self, rule: SLORule, now: float):
        agg = rule.aggregation
        value, n = 0.0, 0.0
        if agg in ("p50", "p90", "p99", "mean"):
            if agg == "mean":
                count, total, _deltas, _ubs = self.store.window_hist(
                    rule.series, rule.window_secs, rule.labels,
                    rule.source, now,
                )
                value = total / count if count > 0 else 0.0
                n = count
            else:
                q = {"p50": 0.50, "p90": 0.90, "p99": 0.99}[agg]
                value, n = self.store.window_quantile(
                    rule.series, rule.window_secs, q,
                    rule.labels, rule.source, now,
                )
            if n <= 0 and agg == "mean":
                # No histogram matched: fall through to gauges so
                # "mean over the window" also works on a gauge series
                # (quantiles have no gauge equivalent — don't pay the
                # store scan just to discard it).
                values = self.store.gauge_values(
                    rule.series, rule.window_secs, rule.labels,
                    rule.source, now,
                )
                if values:
                    value, n = sum(values) / len(values), len(values)
        elif agg == "rate":
            delta, n = self.store.window_counter_delta(
                rule.series, rule.window_secs, rule.labels,
                rule.source, now,
            )
            value = delta / rule.window_secs if rule.window_secs else 0.0
        else:  # last / max / min over gauge points
            values = self.store.gauge_values(
                rule.series, rule.window_secs, rule.labels,
                rule.source, now,
            )
            n = len(values)
            if values:
                value = {
                    "last": values[-1],
                    "max": max(values),
                    "min": min(values),
                }[agg]
        cmp = {
            ">": value > rule.value, "<": value < rule.value,
            ">=": value >= rule.value, "<=": value <= rule.value,
        }[rule.op]
        firing = bool(cmp and n >= rule.min_count)
        detail = (
            f"{agg}({rule.series}[{int(rule.window_secs)}s]) = "
            f"{value:.4g} {rule.op} {rule.value:.4g}, n={int(n)}"
        )
        return firing, value, detail

    def _eval_absence(self, rule: SLORule, now: float):
        seen = self.store.last_seen(rule.series, rule.labels, rule.source)
        offenders = []
        worst = 0.0
        for key, t in seen.items():
            age = now - t
            if rule.staleness_secs < age <= rule.forget_secs:
                offenders.append(key)
                worst = max(worst, age)
        firing = bool(offenders)
        detail = (
            f"{len(offenders)} stale series on {rule.series} "
            f"(oldest {worst:.0f}s > {rule.staleness_secs:.0f}s): "
            f"{sorted(offenders)[:4]}"
            if offenders else
            f"all {len(seen)} series on {rule.series} fresh"
        )
        return firing, worst, detail

    # ---- state / endpoint ----------------------------------------------

    def alert_state(self, name: str) -> dict:
        rule = next(r for r in self.rules if r.name == name)
        with self._lock:
            state = dict(self._states[name])
        state["rule"] = name
        state["kind"] = rule.kind
        state["series"] = rule.series
        state["description"] = rule.description
        return state

    def firing(self) -> List[str]:
        with self._lock:
            return sorted(
                name for name, s in self._states.items() if s["firing"]
            )

    def render(self) -> dict:
        """JSON body for ``GET /alerts``."""
        rules = [self.alert_state(rule.name) for rule in self.rules]
        return {
            "now": self._clock(),
            "firing": self.firing(),
            "rules": rules,
        }


class IncidentRecorder:
    """Black-box capture on alert transitions: one self-contained
    bundle directory per firing, rate-limited per rule.

    Bundle layout (``tools/check_incident.py`` is the schema check)::

        <out_dir>/incident_<utc stamp>_<rule>/
            alert.json          # the firing rule state + rule config
            trace.json          # Perfetto trace_event JSON of every
                                # collected span (all roles' flight
                                # recorders, via the metrics pipeline)
            critical_path.json  # p99 task/step attribution over the
                                # same spans
            series.json         # TimeSeriesStore window around the
                                # breach (hot tier)
            journal_tail.json   # last N master-journal records (when
                                # the master runs with --journal_dir)
            profile.json        # per-component flame-table windows
                                # from the ProfileStore (when the fleet
                                # runs with --profile_hz) — WHICH CODE
                                # was burning when the rule fired
            exemplars.json      # the breached series' exemplar traces
                                # (value + trace id), resolvable
                                # against trace.json
    """

    def __init__(self, out_dir: str,
                 metrics_plane=None,
                 store: Optional[TimeSeriesStore] = None,
                 journal_tail_fn: Optional[Callable[[], list]] = None,
                 profile_store=None,
                 window_secs: float = 900.0,
                 profile_window_secs: float = 120.0,
                 cooldown_secs: float = 300.0,
                 background: bool = True,
                 clock: Callable[[], float] = time.time):
        self.out_dir = out_dir
        self.metrics_plane = metrics_plane
        self.store = store
        self.journal_tail_fn = journal_tail_fn
        # Default to the plane's store so every --incident_dir master
        # bundles profiles once any component runs with --profile_hz.
        if profile_store is None and metrics_plane is not None:
            profile_store = getattr(metrics_plane, "profiles", None)
        self.profile_store = profile_store
        self.window_secs = float(window_secs)
        self.profile_window_secs = float(profile_window_secs)
        self.cooldown_secs = float(cooldown_secs)
        # Captures serialize thousands of spans + a long series window
        # to disk — by default that happens on a daemon thread, NOT on
        # the master run loop that called evaluate() (an incident is
        # exactly when the master is already under pressure). Tests
        # and drills call flush() before asserting on bundles, or pass
        # background=False.
        self.background = bool(background)
        self._clock = clock
        self._lock = threading.Lock()
        self._last_capture: Dict[str, float] = {}
        self._writers: List[threading.Thread] = []
        self.bundles: List[str] = []

    def capture(self, alert_state: dict,
                now: Optional[float] = None) -> Optional[str]:
        """Capture one bundle; returns its path (write may still be in
        flight — see ``flush``), or None when the rule is inside its
        capture cooldown (a flapping rule must not fill the disk with
        near-identical bundles)."""
        now = self._clock() if now is None else now
        rule = str(alert_state.get("rule", "unknown"))
        with self._lock:
            last = self._last_capture.get(rule)
            if last is not None and now - last < self.cooldown_secs:
                return None
            self._last_capture[rule] = now
        stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime(now))
        path = os.path.join(self.out_dir, f"incident_{stamp}_{rule}")
        suffix = 0
        while os.path.exists(path):
            suffix += 1
            path = os.path.join(
                self.out_dir, f"incident_{stamp}_{rule}.{suffix}"
            )
        os.makedirs(path, exist_ok=True)
        if not self.background:
            self._write_bundle(path, alert_state, now)
            return path
        writer = threading.Thread(
            target=self._write_bundle, args=(path, alert_state, now),
            daemon=True, name="incident-writer",
        )
        with self._lock:
            self._writers = [
                t for t in self._writers if t.is_alive()
            ] + [writer]
        writer.start()
        return path

    def flush(self, timeout: float = 10.0):
        """Join in-flight bundle writes (shutdown / test barrier)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            writers = list(self._writers)
        for writer in writers:
            writer.join(timeout=max(0.0, deadline - time.monotonic()))

    def _write_bundle(self, path: str, alert_state: dict, now: float):
        """Every stage is individually contained: one failing
        collector (a malformed span, a store hiccup) must degrade the
        bundle — a fallback payload for that file — never abandon it
        half-written on a dead writer thread. This is the 2 a.m.
        artifact; partial beats silently absent. Disk-level failures
        (ENOSPC) are the one thing a fallback can't fix; they log
        loudly instead of killing the thread silently."""
        try:
            self._write_bundle_inner(path, alert_state, now)
        except Exception:
            logger.exception("incident bundle %s failed to write", path)

    def _write_bundle_inner(self, path: str, alert_state: dict,
                            now: float):

        def stage(name, fn, fallback):
            try:
                return fn()
            except Exception:
                logger.exception("incident: %s collection failed", name)
                return fallback

        spans = []
        if self.metrics_plane is not None:
            spans = stage(
                "span", self.metrics_plane.trace_spans, []
            )
        self._write_json(path, "alert.json", {
            "captured_at": now,
            "window_secs": self.window_secs,
            "alert": alert_state,
        })
        from elasticdl_tpu.observability import critical_path
        from elasticdl_tpu.observability.trace_export import chrome_trace

        self._write_json(path, "trace.json", stage(
            "trace", lambda: chrome_trace(spans),
            {"traceEvents": []},
        ))
        self._write_json(path, "critical_path.json", stage(
            "critical-path", lambda: critical_path.analyze(spans),
            {"span_count": 0, "trace_count": 0},
        ))
        series = {}
        if self.store is not None:
            series = stage(
                "series",
                lambda: self.store.render(
                    window_secs=self.window_secs, now=now
                ),
                {"series": {}, "error": "series capture failed"},
            )
        self._write_json(path, "series.json", series)
        tail = []
        if self.journal_tail_fn is not None:
            tail = stage(
                "journal-tail",
                lambda: list(self.journal_tail_fn()), [],
            )
        self._write_json(path, "journal_tail.json", {"records": tail})
        profile = {"window_secs": self.profile_window_secs,
                   "components": {}}
        if self.profile_store is not None:
            profile = stage(
                "profile",
                lambda: self.profile_store.bundle_capture(
                    window_secs=self.profile_window_secs
                ),
                profile,
            )
        self._write_json(path, "profile.json", profile)
        self._write_json(path, "exemplars.json", stage(
            "exemplar",
            lambda: self._collect_exemplars(alert_state),
            {"series": alert_state.get("series"), "exemplars": []},
        ))
        self.bundles.append(path)
        logger.warning("incident bundle written: %s (%d spans)",
                       path, len(spans))

    def _collect_exemplars(self, alert_state: dict) -> dict:
        """The breached rule's exemplar traces: scan the master-local
        registry and every live cluster snapshot for the rule's series
        family, collecting each series' exemplars (bucket bound, value,
        trace id, timestamp). These trace ids resolve against
        ``trace.json`` — the metric→trace rung the bundle exists for."""
        family_name = str(alert_state.get("series") or "")
        out = []
        if not family_name or self.metrics_plane is None:
            return {"series": family_name, "exemplars": out}
        sources = {"": self.metrics_plane.registry.snapshot()}
        sources.update({
            str(wid): snap
            for wid, snap in
            self.metrics_plane.cluster.snapshots().items()
        })
        for source, snapshot in sorted(sources.items()):
            for family in (snapshot or {}).get("families", []):
                if family.get("name") != family_name:
                    continue
                buckets = family.get("buckets") or []
                for series in family.get("series", []):
                    for idx, entry in sorted(
                        (series.get("exemplars") or {}).items()
                    ):
                        try:
                            i = int(idx)
                            value, trace_id, ts = entry
                        except (TypeError, ValueError):
                            continue
                        out.append({
                            "source": source,
                            "labels": list(series.get("labels", [])),
                            "bucket_le": (
                                float(buckets[i]) if i < len(buckets)
                                else None  # +Inf overflow
                            ),
                            "value": float(value),
                            "trace_id": str(trace_id),
                            "ts": float(ts),
                        })
        return {"series": family_name, "exemplars": out}

    @staticmethod
    def _write_json(bundle: str, name: str, payload):
        with open(os.path.join(bundle, name), "w") as fh:
            json.dump(payload, fh, indent=1, sort_keys=True,
                      default=str)
            fh.write("\n")
