"""Synthetic canary plane: black-box probes measuring every SLO from
the outside.

Every other telemetry layer — tracing, SLO burn rates, continuous
profiling, workload attribution — is white-box: it reports what the
components *say* about themselves. A wedged handler, a stale ShardMap
client, or a serving tier silently returning old rows stays green in
white-box metrics until a user notices. This module closes that gap
with **outside-in SLIs**: a ``ProbeScheduler`` runs named black-box
probes on intervals, each exercising a user-visible contract end to
end through the public wire surface (RPC stubs, the serving router's
HTTP API, the stream producer API), never through in-process
shortcuts.

Canary keyspace contract
------------------------
Synthetic traffic must never perturb real training state. Probes write
only to the **reserved canary id range** — ``[CANARY_ID_BASE,
CANARY_ID_BASE + CANARY_ID_SPAN)``, the top of the int64 id space,
far above any hashed feature id — and to the dedicated canary stream
partition (``CANARY_STREAM_PARTITION``). Rows in the canary range live
in the ordinary tables (pushes to unknown tables are rejected as
INVALID_ARGUMENT), so canary writes exercise the exact same apply /
WAL / reshard / serving-cache machinery as real rows while staying
disjoint from every trained embedding. All probe traffic is tagged
with the closed principal purpose ``canary`` so ``/usage`` accounts
synthetic load separately from every real tenant.

Probe catalog (the five shipped probes):

- ``row_ryw``            durable push -> immediate pull, byte-equal:
                         read-your-writes plus measured RPO=0 against
                         the row tier, from outside.
- ``serving_freshness``  push a canary row -> poll the serving router
                         until the prediction for the canary id
                         changes: the outside-in twin of the
                         push-to-servable SLO.
- ``reshard_convergence`` a FRESH client (no cached map) rides
                         REDIRECTs to a converged pull; its latency is
                         the convergence time across live splits.
- ``stream_watermark``   append a canary stream record -> the
                         committed watermark advances past it.
- ``dispatch_roundtrip`` get_task / report_task_result against the
                         master's dispatch plane.

Failures carry a bounded reason label (``REASONS``); a probe turning
red (``unhealthy_after`` consecutive failures) captures a black-box
incident bundle carrying the probe's trace id, and the SLO engine's
default rules burn on the probe failure ratio
(``probe-failure-burn`` in observability/slo.py). The master mounts
``render()`` on ``/probes`` and ``healthz()`` as the aggregated
``/healthz`` verdict, and registers the prober as a low-priority
gang-scheduler tenant (``PROBER_TENANT``) so it survives — and
observes — preemption. docs/observability.md "Synthetic probing".
"""

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

# Reserved canary keyspace: the top 2^20 ids of the non-negative int64
# range. Real ids come from feature hashing / vocab enumeration and
# stay far below this; the drill's fsck validator and the tests pin
# the constant so it cannot silently move.
CANARY_ID_BASE = 1 << 62
CANARY_ID_SPAN = 1 << 20

# Dedicated stream partition for the stream_watermark probe: canary
# records never share a partition (or watermark accounting) with real
# ingest traffic.
CANARY_STREAM_PARTITION = "canary"

# The prober's principal job label and its gang-scheduler tenant id.
CANARY_JOB = "canary-prober"
PROBER_TENANT = "__prober__"

# Closed failure-reason vocabulary — the ``reason`` label on
# ``probe_failures_total`` stays bounded no matter what a probe body
# raises (anything off-vocabulary is folded to "exception").
REASONS = (
    "timeout",      # deadline elapsed waiting on the contract
    "rpc_error",    # transport/stub error against an RPC surface
    "http_error",   # non-200 from an HTTP surface (serving router)
    "mismatch",     # byte-inequality where the contract demands equal
    "stale",        # the write never became visible / fenced answer
    "exception",    # probe body raised something unclassified
)

# Probe latencies span sub-ms in-process roundtrips to multi-second
# convergence waits; the tail bucket must hold a slow-but-green
# freshness poll.
PROBE_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

DEFAULT_INTERVAL_SECS = 15.0


def canary_id(slot: int = 0) -> int:
    """The ``slot``-th reserved canary id (wraps within the span)."""
    return CANARY_ID_BASE + (int(slot) % CANARY_ID_SPAN)


def is_canary_id(row_id: int) -> bool:
    return CANARY_ID_BASE <= int(row_id) < CANARY_ID_BASE + CANARY_ID_SPAN


class ProbeFailure(RuntimeError):
    """A probe's contract check failed. ``reason`` must come from
    ``REASONS`` (off-vocabulary reasons are folded to "exception" at
    record time so the metric label set stays closed)."""

    def __init__(self, reason: str, message: str = ""):
        super().__init__(message or reason)
        self.reason = str(reason)


class ProbeScheduler:
    """Runs registered black-box probes on their intervals.

    Each run is wrapped in the ``canary`` principal purpose (so every
    RPC the probe makes is attributed to synthetic load), traced (the
    span's trace id lands as the ``probe_seconds`` exemplar and in the
    incident bundle on a red transition), and recorded into the
    ``probe_attempts_total{probe}`` / ``probe_failures_total{probe,
    reason}`` / ``probe_seconds{probe}`` families.

    Drive it either with the background thread (``start``/``stop``,
    the master wiring) or deterministically via ``run_pending(now)`` /
    ``run_once(name)`` (tests and the chaos drill's twin).
    """

    def __init__(self, registry=None, incident_recorder=None,
                 job: str = CANARY_JOB, unhealthy_after: int = 2,
                 clock: Callable[[], float] = time.time):
        from elasticdl_tpu.observability import default_registry

        registry = registry or default_registry()
        self._registry = registry
        self._incidents = incident_recorder
        self._job = str(job)
        self._default_unhealthy_after = max(1, int(unhealthy_after))
        self._clock = clock
        self._lock = threading.RLock()
        self._probes: Dict[str, dict] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Gang-scheduler tenancy observation (note_* are wired as the
        # tenant's preempt/resume callbacks): the prober KEEPS probing
        # through its own preemption — black-box monitoring of a busy
        # fleet is the point — but records what the arbiter did to it.
        self._tenant = {"registered": False, "state": "unregistered",
                        "preemptions": 0, "resumes": 0,
                        "last_event_ts": 0.0}
        self._m_attempts = registry.counter(
            "probe_attempts_total",
            "Black-box probe runs, by probe name", ["probe"],
        )
        self._m_failures = registry.counter(
            "probe_failures_total",
            "Black-box probe failures, by probe name and bounded "
            "reason", ["probe", "reason"],
        )
        self._m_seconds = registry.histogram(
            "probe_seconds",
            "Black-box probe end-to-end latency (exemplars carry the "
            "probe run's trace id)", ["probe"],
            buckets=PROBE_BUCKETS, exemplars=True,
        )
        self._m_up = registry.gauge(
            "probe_up",
            "1 while the probe's most recent run succeeded, 0 after "
            "a failure", ["probe"],
        )

    # ---- registration ---------------------------------------------------

    def register(self, name: str, fn: Callable[[], Optional[dict]],
                 interval_secs: float = DEFAULT_INTERVAL_SECS,
                 unhealthy_after: Optional[int] = None,
                 description: str = "") -> None:
        """Add a named probe. ``fn`` is a zero-arg callable that
        raises ``ProbeFailure`` (or anything — folded to reason
        "exception") on contract violation and may return a JSON-able
        detail dict on success."""
        name = str(name)
        if not name:
            raise ValueError("probe name must be non-empty")
        with self._lock:
            if name in self._probes:
                raise ValueError(f"probe {name!r} already registered")
            self._probes[name] = {
                "fn": fn,
                "interval_secs": float(interval_secs),
                "unhealthy_after": max(1, int(
                    self._default_unhealthy_after
                    if unhealthy_after is None else unhealthy_after
                )),
                "description": str(description),
                "status": "init",
                "attempts": 0,
                "failures": 0,
                "consecutive_failures": 0,
                "reds": 0,
                "next_due": 0.0,   # first tick runs every probe once
                "last_run_ts": 0.0,
                "last_ok_ts": 0.0,
                "last_failure_ts": 0.0,
                "last_reason": "",
                "last_error": "",
                "last_latency_secs": 0.0,
                "last_trace_id": "",
                "last_detail": {},
            }

    def probe_names(self) -> List[str]:
        with self._lock:
            return list(self._probes)

    # ---- execution ------------------------------------------------------

    def run_once(self, name: str, now: Optional[float] = None) -> dict:
        """Run one probe immediately; returns its result record."""
        from elasticdl_tpu.observability import principal, tracing

        with self._lock:
            ent = self._probes[name]
            fn = ent["fn"]
        if now is None:
            now = self._clock()
        span = tracing.span(f"probe/{name}", probe=name)
        reason, detail, ok = "", {}, True
        with principal.pushed(job=self._job, component="prober",
                              purpose="canary"):
            t0 = time.perf_counter()
            try:
                with span:
                    out = fn()
                if isinstance(out, dict):
                    detail = out
            except ProbeFailure as exc:
                ok = False
                reason = (exc.reason if exc.reason in REASONS
                          else "exception")
                detail = {"error": str(exc)}
            except Exception as exc:  # a probe bug must not kill the plane
                ok = False
                reason = "exception"
                detail = {"error": f"{type(exc).__name__}: {exc}"}
                logger.exception("probe %s raised", name)
            elapsed = time.perf_counter() - t0
        trace_id = span.trace_id or ""
        self._m_attempts.labels(name).inc()
        self._m_seconds.labels(name).observe(
            elapsed, trace_id=trace_id or None
        )
        went_red = False
        with self._lock:
            ent["attempts"] += 1
            ent["last_run_ts"] = now
            ent["last_latency_secs"] = elapsed
            ent["last_trace_id"] = trace_id
            ent["next_due"] = now + ent["interval_secs"]
            if ok:
                ent["consecutive_failures"] = 0
                ent["last_ok_ts"] = now
                ent["status"] = "green"
                ent["last_detail"] = detail
                self._m_up.labels(name).set(1.0)
            else:
                self._m_failures.labels(name, reason).inc()
                self._m_up.labels(name).set(0.0)
                ent["failures"] += 1
                ent["consecutive_failures"] += 1
                ent["last_failure_ts"] = now
                ent["last_reason"] = reason
                ent["last_error"] = str(detail.get("error", ""))
                if (ent["consecutive_failures"] >= ent["unhealthy_after"]
                        and ent["status"] != "red"):
                    ent["status"] = "red"
                    ent["reds"] += 1
                    went_red = True
            record = {
                "probe": name, "ok": ok, "reason": reason,
                "status": ent["status"], "latency_secs": elapsed,
                "trace_id": trace_id, "detail": detail,
            }
            consecutive = ent["consecutive_failures"]
            description = ent["description"]
        if went_red:
            # Red TRANSITION only (the recorder also rate-limits per
            # rule): one bundle per outage, carrying the failing run's
            # trace id so the flight-recorder timeline and the
            # probe_seconds exemplars resolve to the same trace.
            self._capture_incident(name, reason, trace_id, consecutive,
                                   description, now)
        return record

    def run_pending(self, now: Optional[float] = None) -> List[dict]:
        """Run every probe whose interval elapsed; returns their
        result records (deterministic tick for tests/drills)."""
        if now is None:
            now = self._clock()
        with self._lock:
            due = [name for name, ent in self._probes.items()
                   if now >= ent["next_due"]]
        return [self.run_once(name, now=now) for name in due]

    def start(self, poll_secs: float = 0.25) -> None:
        """Background mode: tick ``run_pending`` on a daemon thread."""
        with self._lock:
            if self._thread is not None:
                return
            self._stop.clear()

            def loop():
                while not self._stop.wait(poll_secs):
                    try:
                        self.run_pending()
                    except Exception:
                        logger.exception("probe tick failed")

            self._thread = threading.Thread(
                target=loop, name="probe-scheduler", daemon=True
            )
            self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
        if thread is not None:
            self._stop.set()
            thread.join(timeout=timeout)

    # ---- incident capture ----------------------------------------------

    def _capture_incident(self, name: str, reason: str, trace_id: str,
                          consecutive: int, description: str,
                          now: float) -> None:
        rec = self._incidents
        if rec is None:
            return
        # Same alert-state shape the SLO engine hands the recorder;
        # series names the exemplar-linked family so the bundle's
        # exemplars.json resolves the probe's trace id.
        state = {
            "rule": f"probe-{name}",
            "state": "firing",
            "since": now,
            "kind": "probe",
            "series": "edl_tpu_probe_seconds",
            "labels": {"probe": name},
            "probe": name,
            "reason": reason,
            "trace_id": trace_id,
            "value": float(consecutive),
            "description": description or (
                f"black-box probe {name} red ({reason})"
            ),
        }
        try:
            rec.capture(state, now=now)
        except Exception:
            logger.exception("probe %s incident capture failed", name)

    # ---- gang-scheduler tenancy ----------------------------------------

    def note_registered(self) -> None:
        with self._lock:
            self._tenant["registered"] = True
            self._tenant["state"] = "submitted"

    def note_preempted(self, job_id=None, entry=None) -> None:
        """Wired as the tenant's ``preempt_cb``: probing continues —
        an observer that stops observing under pressure is useless —
        but the eviction is recorded and rendered."""
        with self._lock:
            self._tenant["preemptions"] += 1
            self._tenant["state"] = "preempted"
            self._tenant["last_event_ts"] = self._clock()

    def note_resumed(self, job_id=None, entry=None) -> None:
        with self._lock:
            self._tenant["resumes"] += 1
            self._tenant["state"] = "running"
            self._tenant["last_event_ts"] = self._clock()

    # ---- rendering ------------------------------------------------------

    def render(self) -> dict:
        """The ``/probes`` endpoint body."""
        with self._lock:
            probes = {}
            for name, ent in self._probes.items():
                probes[name] = {
                    key: ent[key] for key in (
                        "status", "attempts", "failures",
                        "consecutive_failures", "reds",
                        "interval_secs", "unhealthy_after",
                        "last_run_ts", "last_ok_ts", "last_failure_ts",
                        "last_reason", "last_error",
                        "last_latency_secs", "last_trace_id",
                        "description",
                    )
                }
            return {
                "job": self._job,
                "purpose": "canary",
                "canary_id_base": CANARY_ID_BASE,
                "canary_id_span": CANARY_ID_SPAN,
                "tenant": dict(self._tenant),
                "probes": probes,
            }

    def healthz(self) -> dict:
        """Aggregated outside-in verdict: ok while no probe is red.
        Probes that never ran ("init") do not fail the verdict — a
        just-started master must not report unhealthy before the first
        probe interval elapses."""
        with self._lock:
            statuses = {
                name: ent["status"] for name, ent in self._probes.items()
            }
        red = sorted(n for n, s in statuses.items() if s == "red")
        ok = not red
        return {
            "ok": ok,
            "status": "ok" if ok else "degraded",
            "red": red,
            "probes": statuses,
        }


# ---------------------------------------------------------------------------
# Transport helpers + the five shipped probe factories. Every factory
# takes injectable callables (or addresses it builds repo-standard
# clients over), so the master wiring, the chaos drill, and the fast
# tests share one probe body each.
# ---------------------------------------------------------------------------


def _probe_guard(fn):
    """Run ``fn`` mapping transport errors onto the bounded reason
    vocabulary; ``ProbeFailure`` passes through untouched."""
    from elasticdl_tpu.comm.rpc import RpcError

    try:
        return fn()
    except ProbeFailure:
        raise
    except TimeoutError as exc:
        raise ProbeFailure("timeout", f"{exc}")
    except RpcError as exc:
        text = str(exc)
        low = text.lower()
        if "deadline" in low or "timeout" in low or "timed out" in low:
            raise ProbeFailure("timeout", text)
        raise ProbeFailure("rpc_error", text)
    except (ConnectionError, OSError) as exc:
        raise ProbeFailure("rpc_error", f"{type(exc).__name__}: {exc}")


class RowCanaryClient:
    """A remote-engine client pinned to the canary id range. Lazily
    connects (the fleet may come up after the prober) with a SHORT
    retry budget — a probe must fail fast and red the SLI, not ride a
    four-minute reconnect loop."""

    def __init__(self, addrs: str, table: Optional[str] = None,
                 retries: int = 2, backoff_secs: float = 0.2):
        self._addrs = addrs
        self._configured_table = table
        self._retries = int(retries)
        self._backoff = float(backoff_secs)
        self._engine = None
        self._table_name = None
        self._lock = threading.Lock()

    def _resolve(self):
        from elasticdl_tpu.embedding.row_service import (
            make_remote_engine,
        )

        with self._lock:
            if self._engine is None:
                engine = make_remote_engine(
                    self._addrs, {}, retries=self._retries,
                    backoff_secs=self._backoff,
                )
                if self._configured_table is not None:
                    name = self._configured_table
                    if name not in engine.tables:
                        raise ProbeFailure(
                            "rpc_error",
                            f"canary table {name!r} not served "
                            f"(fleet has {sorted(engine.tables)})",
                        )
                else:
                    name = sorted(engine.tables)[0]
                self._engine, self._table_name = engine, name
            return self._engine, self._table_name

    def reset(self):
        """Drop the cached engine (fresh bootstrap on next use)."""
        with self._lock:
            self._engine = None

    @property
    def table_name(self) -> Optional[str]:
        return self._table_name

    def dim(self) -> int:
        engine, name = self._resolve()
        return int(engine.tables[name].dim)

    def pull(self, ids) -> np.ndarray:
        def body():
            engine, name = self._resolve()
            return np.asarray(
                engine.tables[name].get(np.asarray(ids, np.int64)),
                np.float32,
            )

        return _probe_guard(body)

    def push(self, ids, grads) -> None:
        def body():
            engine, name = self._resolve()
            engine.optimizer.apply_gradients(
                engine.tables[name], np.asarray(ids, np.int64),
                np.asarray(grads, np.float32),
            )

        _probe_guard(body)

    def map_version(self) -> int:
        with self._lock:
            engine = self._engine
        if engine is None:
            return 0
        cmap = getattr(engine, "shard_map", None)
        try:
            return int(cmap.version) if cmap is not None else 0
        except AttributeError:
            return 0


def make_row_ryw_probe(client: RowCanaryClient, slot: int = 0,
                       eps: float = 1e-3,
                       expect_fn: Optional[Callable] = None):
    """Read-your-writes against the row tier: durable push, immediate
    pull, byte-equality. With ``expect_fn(before, grads) -> expected``
    (the deployment knows its optimizer rule) the pulled bytes must
    EQUAL the expected bytes; without it the pull must differ from the
    pre-push bytes (the write is visible). The push sign alternates so
    the canary row stays bounded forever."""
    state = {"sign": 1.0}

    def probe():
        ids = np.array([canary_id(slot)], np.int64)
        before = client.pull(ids)
        grads = np.full((1, before.shape[1]), state["sign"] * eps,
                        np.float32)
        state["sign"] = -state["sign"]
        client.push(ids, grads)        # durable-ack on a WAL'd fleet
        after = client.pull(ids)
        if expect_fn is not None:
            expected = np.asarray(expect_fn(before, grads), np.float32)
            if not np.array_equal(after, expected):
                raise ProbeFailure(
                    "mismatch",
                    "pull after durable push is not byte-equal to the "
                    "expected applied row",
                )
        elif np.array_equal(after, before):
            raise ProbeFailure(
                "stale",
                "read-your-writes violated: pull after durable push "
                "returned the pre-push bytes",
            )
        return {"table": client.table_name, "id": int(ids[0])}

    return probe


def make_reshard_convergence_probe(addrs: str,
                                   table: Optional[str] = None,
                                   slots=(0, 1, 2, 3),
                                   retries: int = 2,
                                   backoff_secs: float = 0.2):
    """A FRESH client every run — no cached shard map — bootstraps,
    adopts the newest installed map, and pulls canary ids across the
    whole fleet, riding any REDIRECT a live split throws at it. The
    probe's own ``probe_seconds`` observation IS the fresh-client
    convergence time."""
    ids = np.array([canary_id(s) for s in slots], np.int64)

    def probe():
        client = RowCanaryClient(addrs, table=table, retries=retries,
                                 backoff_secs=backoff_secs)
        rows = client.pull(ids)
        return {
            "rows": int(rows.shape[0]),
            "map_version": client.map_version(),
        }

    return probe


def fingerprint_predictions(tree) -> bytes:
    """Stable byte fingerprint of a prediction output tree (dict /
    list / array nests) — the freshness probe's change detector."""
    parts = []

    def walk(node):
        if isinstance(node, dict):
            for key in sorted(node):
                parts.append(str(key).encode())
                walk(node[key])
        elif isinstance(node, (list, tuple)):
            for item in node:
                walk(item)
        else:
            arr = np.asarray(node)
            parts.append(arr.dtype.str.encode())
            parts.append(arr.tobytes())

    walk(tree)
    return b"|".join(parts)


def make_router_predictor(router_addr: str, feature_key: str, ids,
                          timeout: float = 2.0):
    """Predict callable over the serving router's public HTTP surface
    (msgpack ``/v1/predict``), returning the predictions tree."""
    ids = np.asarray(ids, np.int64)

    def predict():
        import http.client

        from elasticdl_tpu.common import tensor_utils

        host, _, port = router_addr.rpartition(":")
        body = tensor_utils.dumps({"features": {feature_key: ids}})
        conn = http.client.HTTPConnection(host or "localhost",
                                          int(port), timeout=timeout)
        try:
            try:
                conn.request(
                    "POST", "/v1/predict", body=body,
                    headers={"Content-Type": "application/x-msgpack"},
                )
                resp = conn.getresponse()
                raw = resp.read()
            except (TimeoutError, OSError) as exc:
                raise ProbeFailure(
                    "timeout" if isinstance(exc, TimeoutError)
                    else "http_error",
                    f"router predict: {type(exc).__name__}: {exc}",
                )
            if resp.status != 200:
                raise ProbeFailure(
                    "http_error",
                    f"router /v1/predict -> HTTP {resp.status}",
                )
            return tensor_utils.loads(raw).get("predictions")
        finally:
            conn.close()

    return predict


def make_serving_freshness_probe(predict_fn, push_fn,
                                 deadline_secs: float = 5.0,
                                 poll_secs: float = 0.05):
    """Outside-in push-to-servable: snapshot the canary prediction,
    push a canary row grad, poll the router until the prediction
    CHANGES. ``push_fn(sign)`` pushes a bounded alternating-sign grad
    to the canary row; ``predict_fn()`` returns the predictions tree
    for the canary id."""
    state = {"sign": 1.0}

    def probe():
        base = fingerprint_predictions(predict_fn())
        push_fn(state["sign"])
        state["sign"] = -state["sign"]
        deadline = time.monotonic() + deadline_secs
        polls = 0
        while True:
            polls += 1
            if fingerprint_predictions(predict_fn()) != base:
                return {"polls": polls}
            if time.monotonic() >= deadline:
                raise ProbeFailure(
                    "stale",
                    f"canary write not servable within "
                    f"{deadline_secs}s ({polls} polls)",
                )
            time.sleep(poll_secs)

    return probe


def make_stream_appender(stream_dir: str,
                         partition: str = CANARY_STREAM_PARTITION,
                         slot: int = 0):
    """Append callable for the stream_watermark probe: writes a canary
    record (id inside the reserved range, fsync'd) and returns its
    offset."""
    import json as _json

    from elasticdl_tpu.data.stream import StreamWriter

    writer = StreamWriter(stream_dir)

    def append() -> int:
        payload = _json.dumps(
            {"id": canary_id(slot), "canary": True}
        ).encode()
        return writer.append(partition, payload, fsync=True)

    return append


def make_stream_watermark_probe(append_fn, watermark_fn,
                                deadline_secs: float = 10.0,
                                poll_secs: float = 0.05):
    """Append a canary stream record, then poll the committed
    watermark until it passes the record's offset. ``watermark_fn()``
    returns the canary partition's committed watermark (record count)
    or None while the partition is undiscovered."""

    def probe():
        offset = int(_probe_guard(append_fn))
        deadline = time.monotonic() + deadline_secs
        polls = 0
        while True:
            polls += 1
            wm = _probe_guard(watermark_fn)
            if wm is not None and int(wm) > offset:
                return {"offset": offset, "committed": int(wm),
                        "polls": polls}
            if time.monotonic() >= deadline:
                raise ProbeFailure(
                    "stale",
                    f"committed watermark did not pass offset "
                    f"{offset} within {deadline_secs}s "
                    f"(last {wm!r})",
                )
            time.sleep(poll_secs)

    return probe


def make_dispatch_roundtrip_probe(master_addr: str,
                                  worker_id: int = -1,
                                  resolve: bool = False,
                                  timeout: float = 2.0):
    """get_task / report_task_result against the master's dispatch
    plane. Leased tasks are handed straight back with the graceful
    ``preempted:`` reason (no retry budget burned, the task re-queues
    at the front) unless ``resolve=True`` — the drill mode, where the
    only job on the master is the canary stream and the probe doubles
    as its worker."""
    from elasticdl_tpu.comm.rpc import RpcStub
    from elasticdl_tpu.master.servicer import SERVICE_NAME

    holder: dict = {"stub": None}

    def probe():
        def body():
            stub = holder["stub"]
            if stub is None:
                stub = RpcStub(master_addr, SERVICE_NAME,
                               max_retries=0)
                holder["stub"] = stub
            try:
                resp = stub.call("get_task", timeout=timeout,
                                 worker_id=int(worker_id))
            except Exception:
                # Next run reconnects: a channel wedged by a master
                # kill must not fail every later probe run too.
                stub.reconnect()
                raise
            if resp.get("stale_master"):
                raise ProbeFailure(
                    "stale", "fenced master answered get_task"
                )
            task = resp.get("task") or {}
            detail = {"finished": bool(resp.get("finished")),
                      "resolved": False}
            task_id = int(task.get("task_id", -1))
            if task_id >= 0:
                fields = {"task_id": task_id,
                          "worker_id": int(worker_id)}
                job = resp.get("job")
                if job:
                    fields["job"] = job
                gen = resp.get("generation")
                if gen is not None:
                    fields["generation"] = gen
                if not resolve:
                    fields["err_reason"] = (
                        "preempted: canary probe hand-back"
                    )
                stub.call("report_task_result", timeout=timeout,
                          **fields)
                detail.update(task_id=task_id, resolved=bool(resolve))
            return detail

        return _probe_guard(body)

    return probe
