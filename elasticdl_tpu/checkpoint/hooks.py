"""Shared checkpoint wiring for executors and workers.

One implementation of "restore at init / save every N versions / final
save" so the Local and distributed paths cannot drift (reference spreads
this across ps/parameter_server.py:49-66 and ps/servicer.py:242-257).
"""

from typing import Optional

from elasticdl_tpu.checkpoint.saver import CheckpointSaver
from elasticdl_tpu.checkpoint.state_io import (
    named_leaves_from_state,
    restore_state_from_named_leaves,
)
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger(__name__)


def _has_orbax_versions(checkpoint_dir: str) -> bool:
    import os
    import re

    # Finalized versions only — orbax's in-progress
    # *.orbax-checkpoint-tmp-* dirs must not route restore here.
    pattern = re.compile(r"^orbax-\d+$")
    try:
        return any(
            pattern.match(name) for name in os.listdir(checkpoint_dir)
        )
    except OSError:
        return False


def has_valid_checkpoint(checkpoint_dir: str) -> bool:
    """Either backend has a restorable version here (used by the
    elastic-relaunch resume decision, worker/main.py)."""
    if not checkpoint_dir:
        return False
    if _has_orbax_versions(checkpoint_dir):
        return True
    try:
        return (
            CheckpointSaver(checkpoint_dir).get_valid_latest_version()
            is not None
        )
    except OSError:
        return False


def restore_from_dir(state, checkpoint_dir: str, required: bool = True,
                     host_tables=None):
    """Restore a TrainState's leaves from the latest valid version.

    Backend is detected from the directory contents: orbax version dirs
    (multi-host jobs write those — global arrays aren't addressable from
    one process) restore onto the state's current shardings; otherwise
    the native shard files restore via host numpy.

    ``required=False`` is the elastic-relaunch path: a replacement worker
    is pointed at the job's checkpoint dir, which legitimately has no
    valid version yet if the job died before the first checkpoint — start
    fresh instead of crash-looping the replacement pod.

    ``host_tables`` ({name: EmbeddingTable-like}): host-tier tables to
    refill from the checkpoint's embedding rows (native backend only).
    """
    if _has_orbax_versions(checkpoint_dir):
        if host_tables:
            # Symmetric with CheckpointHook: orbax checkpoints don't
            # carry host rows — silently continuing would lazy-reinit
            # every trained row.
            raise ValueError(
                "host_tables restore requires a native-backend "
                f"checkpoint; {checkpoint_dir} is orbax-backed"
            )
        from elasticdl_tpu.checkpoint.orbax_backend import (
            OrbaxSaver,
            restore_state,
        )

        try:
            state = restore_state(OrbaxSaver(checkpoint_dir), state)
        except FileNotFoundError:
            if required:
                raise
            logger.warning(
                "No valid orbax checkpoint under %s; starting fresh",
                checkpoint_dir,
            )
            return state
        logger.info(
            "Restored state at version %d from %s (orbax)",
            int(state.step), checkpoint_dir,
        )
        return state
    try:
        _, dense, embeddings = CheckpointSaver(checkpoint_dir).restore()
    except FileNotFoundError:
        if required:
            raise
        logger.warning(
            "No valid checkpoint under %s; starting fresh", checkpoint_dir
        )
        return state
    state = restore_state_from_named_leaves(state, dense)
    missing = [n for n in (host_tables or {}) if n not in embeddings]
    if missing:
        # Loud, like the orbax guard above: continuing would silently
        # lazy-reinit every trained row / optimizer slot.
        raise ValueError(
            f"checkpoint at {checkpoint_dir} (version {int(state.step)}) "
            f"carries no host-table payload for {sorted(missing)}; "
            "was it written without host_tables, or with a different "
            "row optimizer?"
        )
    for name, table in (host_tables or {}).items():
        ids, rows = embeddings[name].to_arrays()
        if ids.size:
            table.set(ids, rows)
        if getattr(table, "supports_dirty_rows", False):
            # The refill marked every restored row dirty; the on-disk
            # state it came from already holds them, so the next delta
            # must not re-ship the whole table.
            table.clear_dirty()
    logger.info(
        "Restored state at version %d from %s",
        int(state.step), checkpoint_dir,
    )
    return state


class CheckpointHook:
    """Periodic + final checkpoint writer. ``maybe_save`` is a no-op when
    no dir or no interval is configured; ``save_final`` always writes the
    current version when a dir is configured (so the last steps of a run
    are never lost to interval rounding)."""

    def __init__(
        self,
        checkpoint_dir: str = "",
        checkpoint_steps: int = 0,
        num_shards: int = 1,
        keep_max: int = 3,
        saver: Optional[CheckpointSaver] = None,
        async_save: bool = True,
        backend: str = "native",
        host_tables=None,
        delta_chain_max: int = 0,
    ):
        # host_tables ({name: EmbeddingTable-like}): host-tier rows are
        # saved alongside the state (native backend; the saver shards
        # rows by id % N like the reference Go checkpoint).
        if host_tables and backend == "orbax":
            raise ValueError(
                "host_tables checkpointing requires the native backend"
            )
        self._host_tables = host_tables or {}
        for view in self._host_tables.values():
            # Turn dirty tracking on now that a consumer drains it
            # (tables default OFF so jobs without checkpointing never
            # pay for the marked-ids set).
            enable = getattr(view, "enable_dirty_tracking", None)
            if enable is not None:
                enable()
        # "orbax": required for multi-host jobs (one process cannot
        # device_get a global array); writes coordinately and restores
        # onto any target sharding. Orbax manages its own async IO, so
        # the hook's async wrapper is bypassed there.
        self._orbax = None
        if backend == "orbax" and checkpoint_dir:
            from elasticdl_tpu.checkpoint.orbax_backend import OrbaxSaver

            self._orbax = OrbaxSaver(checkpoint_dir, keep_max=keep_max)
            saver = saver or self._orbax  # enables the save paths below
        if saver is None and checkpoint_dir:
            saver = CheckpointSaver(
                checkpoint_dir, num_shards=num_shards, keep_max=keep_max,
                delta_chain_max=delta_chain_max,
            )
        self.saver = saver
        self.checkpoint_steps = int(checkpoint_steps)
        self._last_saved = None
        # Async capture/write split: the device->host copy + host-table
        # capture stay on the caller's thread (they must observe a
        # consistent state), but serialization, checksumming, and disk
        # IO move to the bounded background CheckpointWriter — the
        # training step doesn't wait on storage, and a slow disk
        # backpressures (bounded queue) instead of piling up full host
        # model copies. A crash mid-write leaves a torn ``.tmp`` dir
        # the saver's validity scan never sees.
        from elasticdl_tpu.checkpoint.saver import ChainPlanner
        from elasticdl_tpu.checkpoint.writer import CheckpointWriter

        self._writer = CheckpointWriter(max_pending=1,
                                        sync=not async_save)
        # In-memory chain planning: disk lags the write queue, so
        # planning from it could fork the chain (see ChainPlanner).
        self._planner = ChainPlanner(delta_chain_max)
        from elasticdl_tpu.observability import default_registry

        self._m_stall = default_registry().histogram(
            "checkpoint_stall_seconds",
            "Step/push-path time spent capturing + enqueuing a "
            "checkpoint (the part the hot path actually waits on)",
            exemplars=True,
        )

    def flush(self):
        """Wait for in-flight async writes; raise a deferred failure
        (unless a newer write has since succeeded and superseded it)."""
        if self._orbax is not None:
            self._orbax.wait()
        self._writer.flush()

    @property
    def enabled(self) -> bool:
        return self.saver is not None

    def note_version(self, version: int):
        """Seed the save baseline after a checkpoint restore, so the
        interval-crossing rule doesn't count pre-restore steps and write
        a spurious (non-multiple) checkpoint on the first step."""
        if self._last_saved is None:
            self._last_saved = int(version)

    def maybe_save(self, state) -> bool:
        if (
            self.saver is None
            or not self.checkpoint_steps
            or state is None
        ):
            return False
        version = int(state.step)
        if version == 0 or version == self._last_saved:
            return False
        # Save on exact multiples (per-step callers) or whenever the
        # interval was crossed since the last save — fused task execution
        # advances the version several steps per call and may never land
        # exactly on a multiple.
        crossed = (
            version - (self._last_saved or 0) >= self.checkpoint_steps
        )
        if version % self.checkpoint_steps != 0 and not crossed:
            return False
        self._save(version, state)
        return True

    def save_final(self, state) -> bool:
        if self.saver is None or state is None:
            # Even with nothing new to write, surface deferred failures.
            self.flush()
            return False
        version = int(state.step)
        if self._last_saved == version:
            self.flush()
            return False
        self._save(version, state)
        self.flush()
        return True

    def _save(self, version: int, state):
        # CAPTURE on the caller's thread (consistent snapshot before
        # the step mutates/donates buffers and before further row
        # applies): start the device->host transfers async, capture
        # host tables (dirty rows only when a delta is planned), then
        # hand serialization + IO to the background writer. The time
        # spent HERE is the whole step-path checkpoint cost —
        # checkpoint_stall_seconds measures it.
        import jax
        import time as _time

        t0 = _time.monotonic()
        if self._orbax is not None:
            from elasticdl_tpu.checkpoint.orbax_backend import save_state

            save_state(self._orbax, state)
            self._last_saved = version
            self._m_stall.observe(_time.monotonic() - t0)
            return

        from elasticdl_tpu.checkpoint.state_io import start_host_transfer

        start_host_transfer(state)
        # Incremental plan: only when the saver supports chains AND
        # host tables exist (a dense-only delta saves nothing — the
        # dense leaves ARE the payload and ride in full either way).
        plan, base, prev = ("full", None, None)
        if self._host_tables and hasattr(self.saver, "save_delta"):
            plan, base, prev = self._planner.plan(version)
        from elasticdl_tpu.checkpoint.saver import (
            capture_tables,
            remark_dirty,
        )

        embeddings, dirty_ids = capture_tables(
            self._host_tables, delta=plan == "delta"
        )
        leaves = jax.device_get(named_leaves_from_state(state))
        # Only pass the kwarg when host tables exist — custom savers
        # (tests, adapters) need not grow the parameter otherwise.
        kwargs = {"embeddings": embeddings} if embeddings else {}

        def write():
            try:
                if plan == "delta":
                    if not self.saver.element_exists(prev):
                        from elasticdl_tpu.checkpoint.state_io import (
                            CorruptCheckpointError,
                        )

                        # The predecessor this delta was planned
                        # against failed ahead of us in the FIFO
                        # queue: writing would produce an
                        # unrestorable element whose success would
                        # also mask the predecessor's deferred error.
                        raise CorruptCheckpointError(
                            f"delta {version}: predecessor {prev} "
                            "never became durable; restarting chain"
                        )
                    self.saver.save_delta(
                        version, leaves, embeddings, base, prev
                    )
                else:
                    self.saver.save(version, leaves, **kwargs)
            except BaseException:
                # Drained dirty rows must re-enter the NEXT delta, and
                # the chain restarts from a fresh base (queued deltas
                # linking through the failure are unrestorable).
                remark_dirty(self._host_tables, dirty_ids)
                self._planner.reset()
                raise
            self._last_saved = version

        self._writer.submit(write, label=f"v{version}-{plan}")
        self._m_stall.observe(_time.monotonic() - t0)
