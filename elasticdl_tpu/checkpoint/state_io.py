"""TrainState ⇄ named-leaf dict conversion for checkpointing.

The reference checkpoints a Model protobuf keyed by variable name
(``ps/parameters.py:172``, ``pkg/ps/model.go:77``). The TPU TrainState is
an arbitrary pytree (params + batch_stats + optimizer state + step + rng),
so leaves are keyed by their tree path — stable across runs because the
structure is determined by the model definition — and restore fills a
freshly initialized state's leaves by path, which also revalidates
structure compatibility.
"""

import dataclasses
import zlib
from typing import Dict

import jax
import numpy as np


class CorruptCheckpointError(RuntimeError):
    """A checkpoint file exists but cannot be decoded into a valid
    shard payload (truncated write, bit rot, chaos injection). Restore
    treats the whole version as unusable and falls back to the
    previous retained version (saver.CheckpointSaver.restore)."""


# ---- shard-file framing --------------------------------------------------
#
# New shard files carry a magic + CRC32 header so torn writes and bit
# rot are caught by checksum before msgpack ever sees the bytes (the
# same discipline as the master journal's frames). Legacy files (raw
# msgpack, no magic) still load: msgpack map headers can never start
# with this magic, so sniffing is unambiguous.

SHARD_MAGIC = b"EDLC1"


def frame_shard_blob(blob: bytes) -> bytes:
    """``magic + u32le crc32(blob) + blob`` — the on-disk shard frame."""
    crc = zlib.crc32(blob) & 0xFFFFFFFF
    return SHARD_MAGIC + crc.to_bytes(4, "little") + blob


def unframe_shard_blob(data: bytes, path: str = "") -> bytes:
    """Strip and verify the frame; raw legacy blobs pass through.
    Raises CorruptCheckpointError on checksum mismatch or a frame too
    short to carry its header."""
    if not data.startswith(SHARD_MAGIC):
        return data  # legacy (pre-framing) shard file
    where = f" ({path})" if path else ""
    header = len(SHARD_MAGIC) + 4
    if len(data) < header:
        raise CorruptCheckpointError(
            f"framed shard shorter than its header{where}"
        )
    want = int.from_bytes(data[len(SHARD_MAGIC):header], "little")
    blob = data[header:]
    got = zlib.crc32(blob) & 0xFFFFFFFF
    if got != want:
        raise CorruptCheckpointError(
            f"shard crc32 mismatch (want {want:#010x}, got "
            f"{got:#010x}){where}"
        )
    return blob


def validate_shard_payload(payload, path: str = ""):
    """Structural check on one decoded shard file. msgpack happily
    decodes *some* corrupted byte streams into non-payload values
    (e.g. a leading ``\\x00`` becomes the int 0), so decode success
    alone is not integrity — the shape of the payload is."""
    where = f" ({path})" if path else ""
    if not isinstance(payload, dict):
        raise CorruptCheckpointError(
            f"shard payload is {type(payload).__name__}, not dict{where}"
        )
    meta = payload.get("meta")
    if not isinstance(meta, dict):
        raise CorruptCheckpointError(f"shard payload lacks meta{where}")
    for key in ("version", "shard", "num_shards"):
        if not isinstance(meta.get(key), int):
            raise CorruptCheckpointError(
                f"shard meta lacks int {key!r}{where}"
            )
    dense = payload.get("dense", {})
    if not isinstance(dense, dict):
        raise CorruptCheckpointError(f"shard dense is not a dict{where}")
    for name, arr in dense.items():
        if not isinstance(arr, np.ndarray):
            raise CorruptCheckpointError(
                f"dense leaf {name!r} decoded as "
                f"{type(arr).__name__}, not ndarray{where}"
            )
    if not isinstance(payload.get("embeddings", {}), dict):
        raise CorruptCheckpointError(
            f"shard embeddings is not a dict{where}"
        )
    return payload

# Non-pytree callables the state carries (struct.field(pytree_node=
# False)) — everything else a TrainState SUBCLASS adds (e.g.
# SparseTrainState's tables/slot_tables/table_steps) must checkpoint,
# so the field list is discovered from the dataclass, not hardcoded: a
# fixed list silently DROPPED subclass state from every checkpoint.


def _state_trees(state):
    if dataclasses.is_dataclass(state):
        for field in dataclasses.fields(state):
            if not field.metadata.get("pytree_node", True):
                continue  # apply_fn / tx: code, not state
            yield field.name, getattr(state, field.name)
        return
    # Duck-typed states (row-service checkpoint adapters, tests):
    # the classic TrainState surface.
    for name in ("step", "params", "batch_stats", "opt_state", "rng"):
        yield name, getattr(state, name)


def _leaf_name(prefix: str, path) -> str:
    return prefix + jax.tree_util.keystr(path)


def start_host_transfer(state):
    """Kick off the device→host copies for every checkpointable leaf
    WITHOUT blocking (jax arrays expose ``copy_to_host_async``). The
    subsequent ``named_leaves_from_state`` then mostly waits on
    transfers that already ran while the caller did other capture work
    — the async-checkpoint path's cheap first half."""
    for _prefix, tree in _state_trees(state):
        for leaf in jax.tree_util.tree_leaves(tree):
            start = getattr(leaf, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:
                    pass  # committed-elsewhere arrays still device_get


def named_leaves_from_state(state) -> Dict[str, np.ndarray]:
    """Flatten state into {path_name: host ndarray}."""
    out = {}
    for prefix, tree in _state_trees(state):
        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        for path, leaf in leaves:
            out[_leaf_name(prefix, path)] = np.asarray(leaf)
    return out


def restore_state_from_named_leaves(state, named: Dict[str, np.ndarray],
                                    strict: bool = True):
    """Fill ``state``'s leaves from the named dict.

    ``state`` supplies the tree structure (and the shardings of its
    leaves: jax re-places restored values to match via the caller's
    device_put). Missing names raise when ``strict`` (reference restore
    asserts variable presence, save_utils.py:230-247).
    """
    new_fields = {}
    for prefix, tree in _state_trees(state):
        paths, treedef = jax.tree_util.tree_flatten_with_path(tree)
        new_leaves = []
        for path, leaf in paths:
            name = _leaf_name(prefix, path)
            if name in named:
                value = np.asarray(named[name])
                if tuple(value.shape) != tuple(np.shape(leaf)):
                    raise ValueError(
                        f"Checkpoint leaf {name} shape {value.shape} != "
                        f"state shape {np.shape(leaf)}"
                    )
                new_leaves.append(value.astype(np.asarray(leaf).dtype))
            elif strict:
                raise KeyError(f"Checkpoint missing leaf {name}")
            else:
                new_leaves.append(leaf)
        new_fields[prefix] = jax.tree_util.tree_unflatten(
            treedef, new_leaves
        )
    return state.replace(**new_fields)
