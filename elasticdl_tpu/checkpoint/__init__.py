"""Sharded checkpoint/restore with cross-N repartitioning.

Counterpart of ``elasticdl/python/common/save_utils.py:70-271`` and the Go
PS checkpoint (``elasticdl/pkg/ps/checkpoint.go``).
"""

from elasticdl_tpu.checkpoint.hooks import CheckpointHook, restore_from_dir
from elasticdl_tpu.checkpoint.saver import CheckpointSaver
from elasticdl_tpu.checkpoint.state_io import (
    CorruptCheckpointError,
    named_leaves_from_state,
    restore_state_from_named_leaves,
)
from elasticdl_tpu.checkpoint.writer import CheckpointWriter

__all__ = [
    "CheckpointHook",
    "CheckpointSaver",
    "CheckpointWriter",
    "CorruptCheckpointError",
    "named_leaves_from_state",
    "restore_from_dir",
    "restore_state_from_named_leaves",
]
