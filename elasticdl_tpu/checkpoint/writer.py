"""Bounded background checkpoint writer.

The async capture/write split: the step/push path pays only a fast
in-memory capture (buffer clones under the caller's locks) plus an
enqueue here; serialization, checksumming, and file I/O run on ONE
background thread (per-shard file writes fan out inside the saver's
own pool). Ordering is FIFO, so versions publish in save order and the
"no version published until fully durable" rule composes with the
saver's tmp+rename+fsync publish.

Backpressure is the bounded queue: an interval save that finds it full
can skip (``block=False`` — its state is covered by the next
interval), while drain paths (``checkpoint_now``/``save_final``) block
for their turn and then ``flush()``. At most ``max_pending`` captured
snapshots exist at once, so slow storage bounds memory instead of
piling up host copies.

``sync=True`` runs jobs inline on the caller's thread (errors raise
immediately) — the chaos harness uses it for deterministic schedules,
and it is the pre-PR behavior for callers that want it.
"""

import threading
import time
from collections import deque
from typing import Callable, Optional

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import tracing

logger = get_logger(__name__)


class CheckpointWriter:
    def __init__(self, max_pending: int = 2, sync: bool = False,
                 metrics_registry=None):
        self._sync = bool(sync)
        self._max_pending = max(1, int(max_pending))
        self._cond = threading.Condition()
        self._queue = deque()  # (fn, label, enqueue_t)
        self._active = 0  # jobs popped but not finished
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # Deferred failure surfaced at flush(); a newer successful
        # write supersedes an older failure — the freshest durable
        # state is what restores.
        self._pending_error: Optional[BaseException] = None
        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self._m_depth = registry.gauge(
            "checkpoint_writer_queue_depth",
            "Captured checkpoints awaiting the background writer",
        )
        self._m_wait = registry.histogram(
            "checkpoint_writer_queue_seconds",
            "Capture-to-write-start latency in the writer queue",
        )

    @property
    def sync(self) -> bool:
        return self._sync

    def _pending(self) -> int:
        """In-flight captured snapshots: queued + actively writing.
        Caller holds the lock."""
        return len(self._queue) + self._active

    @property
    def busy(self) -> bool:
        """At capacity — a non-blocking submit would be refused.
        Interval savers check this BEFORE capturing, so a skipped
        interval doesn't drain dirty state it then has to put back."""
        if self._sync:
            return False
        with self._cond:
            return self._pending() >= self._max_pending

    def submit(self, fn: Callable[[], None], label: str = "ckpt",
               block: bool = True) -> bool:
        """Enqueue one write job. Returns False when ``block=False``
        and the queue is at capacity (the caller skips this interval
        and re-marks any drained dirty state). Sync mode runs inline
        and raises inline."""
        if self._sync:
            fn()
            return True
        with self._cond:
            if self._closed:
                raise RuntimeError("CheckpointWriter is closed")
            while self._pending() >= self._max_pending:
                if not block:
                    return False
                self._cond.wait()
            self._queue.append((fn, label, time.monotonic()))
            self._m_depth.set(float(len(self._queue)))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="ckpt-writer"
                )
                self._thread.start()
            self._cond.notify_all()
        return True

    def _run(self):
        # Ownership check instead of a shared retire flag: flush()
        # detaches the idle thread by nulling self._thread under the
        # lock; a submit racing in right after spawns a FRESH owner,
        # and this (dethroned) thread exits without stealing its jobs.
        me = threading.current_thread()
        while True:
            with self._cond:
                while (not self._queue and self._thread is me
                       and not self._closed):
                    self._cond.wait()
                if self._thread is not me or not self._queue:
                    return  # retired by flush(), or closed idle
                fn, label, t_enq = self._queue.popleft()
                self._active += 1
                self._m_depth.set(float(len(self._queue)))
                self._cond.notify_all()
            queue_wait = time.monotonic() - t_enq
            self._m_wait.observe(queue_wait)
            try:
                # Writer-queue span on this process's trace track: the
                # wall time a checkpoint spent queued + writing, off
                # the step path.
                with tracing.span("ckpt_write", label=label,
                                  queue_wait=round(queue_wait, 6)):
                    fn()
            except BaseException as exc:
                self._pending_error = exc
                logger.error(
                    "async checkpoint write (%s) failed: %s", label, exc
                )
            else:
                self._pending_error = None
            finally:
                with self._cond:
                    self._active -= 1
                    self._cond.notify_all()

    def flush(self):
        """Barrier: wait until every submitted write has landed, then
        raise any still-unsuperseded failure. After flush() returns
        cleanly, the newest submitted version is fully durable. The
        idle writer thread is RETIRED (a later submit spawns a fresh
        one) so flush-heavy callers — save_final, SIGTERM drains,
        short-lived test clusters — never leak parked threads."""
        if not self._sync:
            with self._cond:
                while self._queue or self._active:
                    self._cond.wait()
                thread, self._thread = self._thread, None
                self._cond.notify_all()
            if thread is not None:
                thread.join(timeout=30.0)
        if self._pending_error is not None:
            exc, self._pending_error = self._pending_error, None
            raise exc

    def close(self):
        """Flush, then refuse further submits."""
        try:
            self.flush()
        finally:
            with self._cond:
                self._closed = True
                self._cond.notify_all()
