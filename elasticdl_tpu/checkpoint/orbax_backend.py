"""Orbax-backed checkpointing for multi-host / sharded state.

The native saver (`checkpoint/saver.py`) device_gets leaves to host
numpy — fine single-process (it also gives the reference-parity
repartition semantics), but a multi-host global array is not fully
addressable from one process, so ``device_get`` fails there. Orbax
writes each process's shards coordinately (TensorStore/OCDBT under the
hood) and restores to ANY target sharding, which is exactly the
mesh-resize restore contract.

Same directory-per-version layout idea as the native saver, separate
namespace (``orbax-<version>``): the two backends never mix files.
"""

import os
import re
from typing import Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("orbax_backend")

_VERSION_RE = re.compile(r"^orbax-(\d+)$")


def _version_dir(base: str, version: int) -> str:
    return os.path.join(base, f"orbax-{version}")


class OrbaxSaver:
    """Minimal save/restore over orbax StandardCheckpointer, version-
    directory compatible with CheckpointHook's expectations (save,
    get_valid_latest_version, restore_tree)."""

    def __init__(self, checkpoint_dir: str, keep_max: int = 3):
        import orbax.checkpoint as ocp

        self.checkpoint_dir = os.path.abspath(checkpoint_dir)
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        self.keep_max = keep_max
        self._ckptr = ocp.StandardCheckpointer()

    def save(self, version: int, tree) -> str:
        # Orbax writes async; we only JOIN the previous write here
        # (single-in-flight backpressure, same policy as the native
        # hook's background writer) so the training thread doesn't wait
        # on storage. ``wait()`` (hook.flush / final save) joins fully.
        self._ckptr.wait_until_finished()
        path = _version_dir(self.checkpoint_dir, version)
        self._ckptr.save(path, tree, force=True)
        # GC over FINALIZED versions only (the in-flight one is not
        # listed yet, so it cannot be pruned nor make the count wrong).
        self._gc(self._list_versions())
        logger.info("Saving orbax checkpoint version %d (async)", version)
        return path

    def wait(self):
        self._ckptr.wait_until_finished()
        self._gc(self._list_versions())

    def _list_versions(self):
        out = []
        for name in os.listdir(self.checkpoint_dir):
            m = _VERSION_RE.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def versions(self):
        # Join in-flight writes so callers see a consistent listing.
        self._ckptr.wait_until_finished()
        return self._list_versions()

    def get_valid_latest_version(self) -> Optional[int]:
        versions = self.versions()
        return versions[-1] if versions else None

    def restore_tree(self, abstract_tree, version: Optional[int] = None):
        """Restore onto ``abstract_tree``'s shapes/dtypes/shardings —
        jax.eval_shape output with shardings attached restores straight
        onto a (possibly different) mesh layout."""
        self._ckptr.wait_until_finished()
        if version is None:
            version = self.get_valid_latest_version()
            if version is None:
                raise FileNotFoundError(
                    f"No orbax checkpoint under {self.checkpoint_dir}"
                )
        path = _version_dir(self.checkpoint_dir, version)
        return self._ckptr.restore(path, abstract_tree)

    def _gc(self, versions):
        if self.keep_max and len(versions) > self.keep_max:
            import shutil

            for version in versions[: -self.keep_max]:
                shutil.rmtree(
                    _version_dir(self.checkpoint_dir, version),
                    ignore_errors=True,
                )


def save_state(saver: OrbaxSaver, state) -> str:
    """Save a TrainState's array leaves (apply_fn/tx are static).

    Fields come from the dataclass via ``state_io._state_trees`` — the
    same discovery the native backend uses — so TrainState SUBCLASS
    state (SparseTrainState's tables/slot_tables/table_steps) rides the
    checkpoint instead of silently dropping out of a hardcoded list.
    Each field stores as its leaves list (optax states and custom
    pytrees aren't orbax-serializable as structure; the restore side
    unflattens against the live state's treedef).
    """
    import jax

    from elasticdl_tpu.checkpoint.state_io import _state_trees

    tree = {
        name: jax.tree.leaves(field_tree)
        for name, field_tree in _state_trees(state)
    }
    return saver.save(int(state.step), tree)


def restore_state(saver: OrbaxSaver, state,
                  version: Optional[int] = None):
    """Restore onto ``state``'s structure AND placement: the abstract
    target carries each leaf's current sharding, so a checkpoint saved
    on one mesh restores re-placed onto another (mesh-resize path)."""
    import jax

    from elasticdl_tpu.checkpoint.state_io import _state_trees

    def abstract(tree):
        return jax.tree.map(
            lambda leaf: jax.ShapeDtypeStruct(
                getattr(leaf, "shape", ()),
                getattr(leaf, "dtype", None),
                sharding=getattr(leaf, "sharding", None),
            ),
            tree,
        )

    fields = list(_state_trees(state))
    target = {
        name: jax.tree.leaves(field_tree) for name, field_tree in fields
    }
    try:
        restored = saver.restore_tree(abstract(target), version=version)
    except FileNotFoundError:
        raise
    except Exception:
        # Legacy layout (pre field-discovery): step/params/batch_stats/
        # rng stored as native structures, opt_state as a leaves list.
        # Only the classic five fields exist there — a state carrying
        # MORE (SparseTrainState tables) must not silently restore
        # partially.
        classic = ("step", "params", "batch_stats", "opt_state", "rng")
        extra = [name for name, _ in fields if name not in classic]
        if extra:
            raise ValueError(
                f"orbax checkpoint predates the field-discovery layout "
                f"and carries no state for {extra}; restoring would "
                "silently reinitialize that state"
            )
        legacy_target = {
            "step": state.step,
            "params": state.params,
            "batch_stats": state.batch_stats,
            "opt_state": jax.tree.leaves(state.opt_state),
            "rng": state.rng,
        }
        restored = saver.restore_tree(
            abstract(legacy_target), version=version
        )
        return state.replace(
            step=restored["step"],
            params=restored["params"],
            batch_stats=restored["batch_stats"],
            opt_state=jax.tree.unflatten(
                jax.tree.structure(state.opt_state),
                restored["opt_state"],
            ),
            rng=restored["rng"],
        )
    new_fields = {
        name: jax.tree.unflatten(
            jax.tree.structure(field_tree), restored[name]
        )
        for name, field_tree in fields
    }
    return state.replace(**new_fields)
