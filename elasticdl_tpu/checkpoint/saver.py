"""Checkpoint layout, GC, validity, and repartitioning restore.

Layout parity with the reference (``common/save_utils.py:101-118``,
``pkg/ps/checkpoint.go:122-127``):

    {dir}/version-{v}/variables-{i}-of-{N}.ckpt

Each shard file is msgpack of

    {"meta": {"version": v, "shard": i, "num_shards": N},
     "dense": {leaf_name: ndarray},           # by string_to_id(name) % N
     "embeddings": {table: IndexedSlices}}    # rows by id % N

Restore reads *all* shard files of a version, so loading onto a different
shard count (the reference's repartition restore, save_utils.py:206-259)
is the natural path, with the same hash functions guaranteeing stable
placement. A version is valid iff the file count equals every file's
recorded ``num_shards`` ("slowest-PS-wins" validity, save_utils.py:154-167).
"""

import os
import re
import shutil
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.common.hash_utils import int_to_id, string_to_id
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.checkpoint.state_io import (
    CorruptCheckpointError,
    validate_shard_payload,
)
from elasticdl_tpu.embedding.table import EmbeddingTable

logger = get_logger(__name__)

# ---- chaos seam (chaos/interceptors.py installs) -----------------------
# _post_save_hook(checkpoint_dir, version, vdir): after a version dir is
#   published (fault plans corrupt files here); _post_restore_hook(
#   checkpoint_dir, version): after a successful restore (the version-
#   monotonicity invariant checker observes restores here).
_post_save_hook: Optional[Callable] = None
_post_restore_hook: Optional[Callable] = None


def set_chaos_hooks(post_save: Optional[Callable] = None,
                    post_restore: Optional[Callable] = None):
    global _post_save_hook, _post_restore_hook
    _post_save_hook = post_save
    _post_restore_hook = post_restore

_VERSION_RE = re.compile(r"^version-(\d+)$")
_SHARD_RE = re.compile(r"^variables-(\d+)-of-(\d+)\.ckpt$")


def _version_dir(checkpoint_dir: str, version: int) -> str:
    return os.path.join(checkpoint_dir, f"version-{version}")


class CheckpointSaver:
    """Save/restore named dense leaves + host embedding tables."""

    def __init__(
        self,
        checkpoint_dir: str,
        num_shards: int = 1,
        keep_max: int = 3,
    ):
        if not checkpoint_dir:
            raise ValueError("checkpoint_dir must be non-empty")
        self.checkpoint_dir = checkpoint_dir
        self.num_shards = max(1, int(num_shards))
        self.keep_max = int(keep_max)
        os.makedirs(checkpoint_dir, exist_ok=True)

    # ---- save ----------------------------------------------------------

    def save(
        self,
        version: int,
        dense: Dict[str, np.ndarray],
        embeddings: Optional[Dict[str, EmbeddingTable]] = None,
    ) -> str:
        """Write all shards of one version, then GC old versions."""
        from elasticdl_tpu.observability import default_registry

        registry = default_registry()
        save_t0 = time.monotonic()
        bytes_written = 0
        vdir = _version_dir(self.checkpoint_dir, version)
        tmp = vdir + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        n = self.num_shards
        # Materialize each table once; per-shard masks are vectorized
        # (int_to_id is id % n for non-negative row ids).
        table_arrays = {
            tname: table.to_arrays()
            for tname, table in (embeddings or {}).items()
        }
        table_shard_of = {
            tname: ids % n for tname, (ids, _rows) in table_arrays.items()
        }
        for shard in range(n):
            payload = {
                "meta": {
                    "version": int(version),
                    "shard": shard,
                    "num_shards": n,
                },
                "dense": {
                    name: np.asarray(arr)
                    for name, arr in dense.items()
                    if string_to_id(name, n) == shard
                },
                "embeddings": {},
            }
            for tname, (ids, rows) in table_arrays.items():
                keep = table_shard_of[tname] == shard
                payload["embeddings"][tname] = tensor_utils.IndexedSlices(
                    values=rows[keep], ids=ids[keep]
                )
            path = os.path.join(tmp, f"variables-{shard}-of-{n}.ckpt")
            blob = tensor_utils.dumps(payload)
            bytes_written += len(blob)
            with open(path, "wb") as f:
                f.write(blob)
        # Atomic-ish publish: the version dir appears only when complete.
        if os.path.exists(vdir):
            shutil.rmtree(vdir)
        os.rename(tmp, vdir)
        logger.info("Saved checkpoint version %s (%s shards)", version, n)
        if _post_save_hook is not None:
            _post_save_hook(self.checkpoint_dir, int(version), vdir)
        registry.histogram(
            "checkpoint_save_seconds", "Checkpoint save duration",
        ).observe(time.monotonic() - save_t0)
        registry.counter(
            "checkpoint_saved_bytes_total", "Checkpoint payload bytes",
        ).inc(bytes_written)
        registry.counter(
            "checkpoint_saves_total", "Checkpoint versions written",
        ).inc()
        self.gc()
        return vdir

    # ---- enumerate / validate -----------------------------------------

    def list_versions(self):
        out = []
        if not os.path.isdir(self.checkpoint_dir):
            return out
        for entry in os.listdir(self.checkpoint_dir):
            m = _VERSION_RE.match(entry)
            if m and os.path.isdir(
                os.path.join(self.checkpoint_dir, entry)
            ):
                out.append(int(m.group(1)))
        return sorted(out)

    def is_valid_version(self, version: int) -> bool:
        """Valid iff shard file count matches the recorded num_shards
        (save_utils.py:154-167)."""
        vdir = _version_dir(self.checkpoint_dir, version)
        if not os.path.isdir(vdir):
            return False
        shards = [f for f in os.listdir(vdir) if _SHARD_RE.match(f)]
        if not shards:
            return False
        counts = {int(_SHARD_RE.match(f).group(2)) for f in shards}
        return len(counts) == 1 and counts.pop() == len(shards)

    def get_valid_latest_version(self) -> Optional[int]:
        for version in reversed(self.list_versions()):
            if self.is_valid_version(version):
                return version
        return None

    # ---- restore -------------------------------------------------------

    def restore(
        self, version: Optional[int] = None
    ) -> Tuple[int, Dict[str, np.ndarray], Dict[str, EmbeddingTable]]:
        """Read every shard of a version and merge — shard-count agnostic
        (repartition restore, save_utils.py:206-259).

        With no explicit ``version``, a version whose shard files fail
        to decode (truncated/corrupted write — the shard-count validity
        check cannot see inside files) is skipped with a warning and
        the previous retained version restores instead: a replacement
        worker must resume from the freshest *readable* state, not
        crash-loop on a torn file. An explicit ``version`` raises
        ``CorruptCheckpointError`` — the caller asked for that one."""
        if version is not None:
            return self._restore_version(version)
        candidates = [
            v for v in reversed(self.list_versions())
            if self.is_valid_version(v)
        ]
        if not candidates:
            raise FileNotFoundError(
                f"No valid checkpoint under {self.checkpoint_dir}"
            )
        from elasticdl_tpu.observability import default_registry

        for i, v in enumerate(candidates):
            try:
                return self._restore_version(v)
            except CorruptCheckpointError as exc:
                default_registry().counter(
                    "checkpoint_corrupt_versions_total",
                    "Checkpoint versions skipped at restore because a "
                    "shard file failed to decode",
                ).inc()
                older = len(candidates) - i - 1
                logger.error(
                    "Checkpoint version %d is corrupt (%s); falling "
                    "back to %s older version(s)", v, exc, older,
                )
        raise FileNotFoundError(
            f"Every retained checkpoint version under "
            f"{self.checkpoint_dir} is corrupt "
            f"(tried {candidates})"
        )

    def _restore_version(
        self, version: int
    ) -> Tuple[int, Dict[str, np.ndarray], Dict[str, EmbeddingTable]]:
        vdir = _version_dir(self.checkpoint_dir, version)
        if not self.is_valid_version(version):
            raise FileNotFoundError(f"Invalid checkpoint version {vdir}")
        dense: Dict[str, np.ndarray] = {}
        embeddings: Dict[str, EmbeddingTable] = {}
        for fname in sorted(os.listdir(vdir)):
            if not _SHARD_RE.match(fname):
                continue
            path = os.path.join(vdir, fname)
            try:
                with open(path, "rb") as f:
                    payload = tensor_utils.loads(f.read())
            except Exception as exc:
                # msgpack raises assorted types on truncated/garbled
                # bytes; all mean the same thing here.
                raise CorruptCheckpointError(
                    f"cannot decode {path}: "
                    f"{type(exc).__name__}: {exc}"
                ) from exc
            validate_shard_payload(payload, path)
            dense.update(payload.get("dense", {}))
            for tname, slices in payload.get("embeddings", {}).items():
                # An empty (0, D) slice still carries the row dim; a shard
                # that happens to own zero rows of a table must not pin the
                # table to dim 0 (all its rows may live in later shards).
                dim = (
                    slices.values.shape[1]
                    if slices.values.ndim == 2 else 0
                )
                table = embeddings.get(tname)
                if table is not None and (
                    table.num_rows == 0 and slices.ids.size
                    and slices.values.dtype != table.dtype
                ):
                    # A row-less placeholder from an earlier empty shard
                    # must not pin the dtype either.
                    table = None
                if table is None or (table.dim == 0 and dim):
                    # Preserve the saved dtype: step counters serialize
                    # as float64 rows (exact ints past 2^24) and must
                    # not round through a float32 default on restore.
                    dtype = (
                        slices.values.dtype
                        if slices.ids.size else np.float32
                    )
                    table = EmbeddingTable(tname, dim, dtype=dtype)
                    embeddings[tname] = table
                if slices.ids.size:
                    table.set(slices.ids, slices.values)
        if _post_restore_hook is not None:
            _post_restore_hook(self.checkpoint_dir, int(version))
        return int(version), dense, embeddings

    # ---- GC ------------------------------------------------------------

    def gc(self):
        """Keep the newest ``keep_max`` valid versions
        (save_utils.py:188-204)."""
        if self.keep_max <= 0:
            return
        versions = self.list_versions()
        for version in versions[: -self.keep_max]:
            shutil.rmtree(
                _version_dir(self.checkpoint_dir, version),
                ignore_errors=True,
            )
