"""Checkpoint layout, GC, validity, delta chains, and repartitioning.

Layout parity with the reference (``common/save_utils.py:101-118``,
``pkg/ps/checkpoint.go:122-127``), extended with incremental deltas:

    {dir}/version-{v}/variables-{i}-of-{N}.ckpt     # full base
    {dir}/delta-{v}/chain.json                      # {version, base, prev}
    {dir}/delta-{v}/rows-{i}-of-{N}.ckpt            # dirty rows only

Each shard file is a CRC32-framed msgpack blob
(``state_io.frame_shard_blob``) of

    {"meta": {"version": v, "shard": i, "num_shards": N, ...},
     "dense": {leaf_name: ndarray},           # by string_to_id(name) % N
     "embeddings": {table: IndexedSlices}}    # rows by id % N

A **full base** carries every dense leaf and every materialized row.
A **delta** carries every dense leaf (dense state has no sparsity to
exploit) but only the embedding rows dirtied since the previous
element; ``chain.json`` names its base and predecessor so restore can
replay ``base → delta → delta → …`` in order. A bounded chain length
(``delta_chain_max``) forces compaction into a fresh base.

Restore reads *all* shard files of each element, so loading onto a
different shard count (the reference's repartition restore,
save_utils.py:206-259) works across a whole chain. A dir is valid iff
the file count equals every file's recorded ``num_shards``
("slowest-PS-wins" validity, save_utils.py:154-167); a torn delta
truncates the chain to its longest intact prefix, extending the
corrupt-version fallback semantics to chains.
"""

import json
import os
import re
import shutil
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.common.hash_utils import string_to_id
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.checkpoint.state_io import (
    CorruptCheckpointError,
    frame_shard_blob,
    unframe_shard_blob,
    validate_shard_payload,
)
from elasticdl_tpu.embedding.table import EmbeddingTable

logger = get_logger(__name__)

# ---- chaos seam (chaos/interceptors.py installs) -----------------------
# _post_save_hook(checkpoint_dir, version, vdir): after a version dir is
#   published (fault plans corrupt files here); _post_restore_hook(
#   checkpoint_dir, version): after a successful restore (the version-
#   monotonicity invariant checker observes restores here);
# _fsync_hook("checkpoint"): ahead of each shard file's fsync inside
#   _publish_dir — a fault plan's ``fsync_stall`` sleeps here (slow
#   checkpoint disk stretches the save, never tears it: publish stays
#   behind the tmp-dir rename).
_post_save_hook: Optional[Callable] = None
_post_restore_hook: Optional[Callable] = None
_fsync_hook: Optional[Callable] = None


def set_chaos_hooks(post_save: Optional[Callable] = None,
                    post_restore: Optional[Callable] = None,
                    fsync: Optional[Callable] = None):
    global _post_save_hook, _post_restore_hook, _fsync_hook
    _post_save_hook = post_save
    _post_restore_hook = post_restore
    _fsync_hook = fsync

_VERSION_RE = re.compile(r"^version-(\d+)$")
_DELTA_RE = re.compile(r"^delta-(\d+)$")
_SHARD_RE = re.compile(r"^variables-(\d+)-of-(\d+)\.ckpt$")
_DELTA_SHARD_RE = re.compile(r"^rows-(\d+)-of-(\d+)\.ckpt$")
CHAIN_FILE = "chain.json"


def _version_dir(checkpoint_dir: str, version: int) -> str:
    return os.path.join(checkpoint_dir, f"version-{version}")


def _delta_dir(checkpoint_dir: str, version: int) -> str:
    return os.path.join(checkpoint_dir, f"delta-{version}")


def _table_arrays(embeddings) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
    """Normalize {name: table-like | (ids, rows)} to plain arrays —
    the boundary between capture (caller's thread, under the caller's
    locks) and the write pipeline (possibly a background thread)."""
    out = {}
    for name, table in (embeddings or {}).items():
        if isinstance(table, tuple):
            ids, rows = table
        else:
            ids, rows = table.to_arrays()
        out[name] = (np.asarray(ids, np.int64), np.asarray(rows))
    return out


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class CheckpointSaver:
    """Save/restore named dense leaves + host embedding tables.

    ``delta_chain_max`` > 0 enables incremental saves via
    ``plan_next``/``save_delta``: up to that many deltas ride one base
    before a save compacts into a fresh full base. 0 keeps the classic
    full-snapshot-only behavior (and still *restores* chains written
    by other configurations)."""

    def __init__(
        self,
        checkpoint_dir: str,
        num_shards: int = 1,
        keep_max: int = 3,
        delta_chain_max: int = 0,
        io_workers: int = 0,
    ):
        if not checkpoint_dir:
            raise ValueError("checkpoint_dir must be non-empty")
        self.checkpoint_dir = checkpoint_dir
        self.num_shards = max(1, int(num_shards))
        self.keep_max = int(keep_max)
        self.delta_chain_max = max(0, int(delta_chain_max))
        # Per-shard parallel serialize+write: shard files of one
        # version are independent, so slow storage amortizes across
        # them. 0 = auto.
        self._io_workers = int(io_workers) or min(4, self.num_shards)
        self._io_pool = None
        # Caller-supplied meta of the newest element the last restore
        # applied (tip wins along a chain): how the row service's
        # shard map rides the checkpoint (row_service._restore_latest).
        self.last_restored_meta: dict = {}
        os.makedirs(checkpoint_dir, exist_ok=True)

    # ---- write pipeline ------------------------------------------------

    def _pool(self):
        if self._io_pool is None and self._io_workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._io_pool = ThreadPoolExecutor(
                max_workers=self._io_workers,
                thread_name_prefix="ckpt-shard",
            )
        return self._io_pool

    def _build_payloads(self, version: int, dense: Dict[str, np.ndarray],
                        table_arrays, file_prefix: str,
                        extra_meta: Optional[dict] = None) -> Dict[str, dict]:
        n = self.num_shards
        # Per-shard masks are vectorized (int_to_id is id % n for
        # non-negative row ids).
        shard_of = {
            tname: ids % n for tname, (ids, _rows) in table_arrays.items()
        }
        payloads = {}
        for shard in range(n):
            # Caller meta first: the structural keys (version/shard/
            # num_shards) are load-bearing for restore and must win.
            meta = dict(extra_meta or {})
            meta.update({
                "version": int(version),
                "shard": shard,
                "num_shards": n,
            })
            payload = {
                "meta": meta,
                "dense": {
                    name: np.asarray(arr)
                    for name, arr in dense.items()
                    if string_to_id(name, n) == shard
                },
                "embeddings": {},
            }
            for tname, (ids, rows) in table_arrays.items():
                keep = shard_of[tname] == shard
                payload["embeddings"][tname] = tensor_utils.IndexedSlices(
                    values=rows[keep], ids=ids[keep]
                )
            payloads[f"{file_prefix}-{shard}-of-{n}.ckpt"] = payload
        return payloads

    def _publish_dir(self, final_dir: str, payloads: Dict[str, dict],
                     chain_info: Optional[dict] = None) -> int:
        """Serialize + write + fsync every shard file into a tmp dir
        (shards in parallel), then rename into place and fsync the
        parent: **no version is published until fully durable**, so a
        crash at any point leaves either the previous state or a
        ``.tmp`` dir the validity scan never sees."""
        tmp = final_dir + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)

        def write_one(item) -> int:
            fname, payload = item
            blob = frame_shard_blob(tensor_utils.dumps(payload))
            path = os.path.join(tmp, fname)
            with open(path, "wb") as f:
                f.write(blob)
                f.flush()
                hook = _fsync_hook
                if hook is not None:
                    hook("checkpoint")
                os.fsync(f.fileno())
            return len(blob)

        pool = self._pool()
        items = sorted(payloads.items())
        if pool is not None and len(items) > 1:
            bytes_written = sum(pool.map(write_one, items))
        else:
            bytes_written = sum(write_one(item) for item in items)
        if chain_info is not None:
            chain_path = os.path.join(tmp, CHAIN_FILE)
            with open(chain_path, "w") as f:
                json.dump(chain_info, f)
                f.flush()
                os.fsync(f.fileno())
            bytes_written += os.path.getsize(chain_path)
        # The tmp dir's own entries must be durable BEFORE the rename:
        # fsyncing only the files and the parent leaves a window where
        # the published dir survives a power loss with entries missing.
        _fsync_dir(tmp)
        if os.path.exists(final_dir):
            shutil.rmtree(final_dir)
        os.rename(tmp, final_dir)
        _fsync_dir(os.path.dirname(final_dir) or ".")
        return bytes_written

    def _record_save(self, version: int, vdir: str, kind: str,
                     bytes_written: int, t0: float):
        from elasticdl_tpu.observability import default_registry

        registry = default_registry()
        registry.histogram(
            "checkpoint_save_seconds", "Checkpoint save duration",
        ).observe(time.monotonic() - t0)
        registry.counter(
            "checkpoint_saved_bytes_total", "Checkpoint payload bytes",
        ).inc(bytes_written)
        registry.counter(
            "checkpoint_bytes_written_total",
            "Checkpoint bytes written per element kind",
            ["kind"],
        ).labels(kind).inc(bytes_written)
        registry.counter(
            "checkpoint_saves_total", "Checkpoint versions written",
        ).inc()
        chains = self.chains()
        registry.gauge(
            "checkpoint_delta_chain_length",
            "Deltas riding the newest checkpoint base",
        ).set(float(len(chains[-1]["deltas"]) if chains else 0))
        if _post_save_hook is not None:
            _post_save_hook(self.checkpoint_dir, int(version), vdir)
        self.gc(chains=chains)

    # ---- save ----------------------------------------------------------

    def save(
        self,
        version: int,
        dense: Dict[str, np.ndarray],
        embeddings=None,
        meta: Optional[dict] = None,
    ) -> str:
        """Write all shards of one FULL version, then GC old chains.
        ``embeddings`` maps table name to a table-like (``to_arrays``)
        or a pre-captured ``(ids, rows)`` tuple. ``meta`` rides every
        shard file's meta dict and surfaces on restore via
        ``last_restored_meta`` (reserved keys version/shard/num_shards
        win)."""
        t0 = time.monotonic()
        vdir = _version_dir(self.checkpoint_dir, version)
        payloads = self._build_payloads(
            version, dense, _table_arrays(embeddings), "variables",
            extra_meta=meta,
        )
        bytes_written = self._publish_dir(vdir, payloads)
        logger.info(
            "Saved checkpoint version %s (%s shards)",
            version, self.num_shards,
        )
        self._record_save(version, vdir, "full", bytes_written, t0)
        return vdir

    def save_delta(
        self,
        version: int,
        dense: Dict[str, np.ndarray],
        embeddings,
        base_version: int,
        prev_version: int,
        meta: Optional[dict] = None,
    ) -> str:
        """Write one DELTA element against ``base_version`` whose
        predecessor in the chain is ``prev_version`` (the base itself
        for the first delta). ``embeddings`` carries only the dirty
        rows; dense leaves ride in full (dense state has no sparsity
        to exploit — every leaf changes every step). ``meta`` as in
        ``save`` (chain keys win)."""
        t0 = time.monotonic()
        chain_info = {
            "version": int(version),
            "base": int(base_version),
            "prev": int(prev_version),
            "num_shards": self.num_shards,
        }
        vdir = _delta_dir(self.checkpoint_dir, version)
        extra = dict(meta or {})
        extra.update({"base": int(base_version),
                      "prev": int(prev_version)})
        payloads = self._build_payloads(
            version, dense, _table_arrays(embeddings), "rows",
            extra_meta=extra,
        )
        bytes_written = self._publish_dir(vdir, payloads, chain_info)
        logger.info(
            "Saved delta checkpoint %s (base %s, prev %s)",
            version, base_version, prev_version,
        )
        self._record_save(version, vdir, "delta", bytes_written, t0)
        return vdir

    def plan_next(self) -> Tuple[str, Optional[int], Optional[int]]:
        """What the next save should write, from ON-DISK state:
        ``("full", None, None)`` or ``("delta", base, prev)``. Deltas
        require ``delta_chain_max`` > 0, an existing restorable chain,
        and headroom under the bound — a full chain compacts into a
        fresh base. Async callers must plan through a ``ChainPlanner``
        instead: disk lags the write queue, and planning from it can
        fork the chain."""
        if self.delta_chain_max <= 0:
            return ("full", None, None)
        chains = self.chains()
        if not chains:
            return ("full", None, None)
        tip_chain = chains[-1]
        if len(tip_chain["deltas"]) >= self.delta_chain_max:
            return ("full", None, None)
        return ("delta", tip_chain["base"], tip_chain["tip"])

    # ---- enumerate / validate -----------------------------------------

    def _scan(self, pattern) -> List[int]:
        out = []
        if not os.path.isdir(self.checkpoint_dir):
            return out
        for entry in os.listdir(self.checkpoint_dir):
            m = pattern.match(entry)
            if m and os.path.isdir(
                os.path.join(self.checkpoint_dir, entry)
            ):
                out.append(int(m.group(1)))
        return sorted(out)

    def list_versions(self):
        """Full-base versions only (the classic listing)."""
        return self._scan(_VERSION_RE)

    def list_deltas(self):
        return self._scan(_DELTA_RE)

    @staticmethod
    def _dir_valid(vdir: str, shard_re) -> bool:
        if not os.path.isdir(vdir):
            return False
        shards = [f for f in os.listdir(vdir) if shard_re.match(f)]
        if not shards:
            return False
        counts = {int(shard_re.match(f).group(2)) for f in shards}
        return len(counts) == 1 and counts.pop() == len(shards)

    def is_valid_version(self, version: int) -> bool:
        """Valid iff shard file count matches the recorded num_shards
        (save_utils.py:154-167)."""
        return self._dir_valid(
            _version_dir(self.checkpoint_dir, version), _SHARD_RE
        )

    def is_valid_delta(self, version: int) -> bool:
        return self._dir_valid(
            _delta_dir(self.checkpoint_dir, version), _DELTA_SHARD_RE
        )

    def element_exists(self, version: int) -> bool:
        """A durable element (base or delta) for ``version`` is on
        disk. Async delta writers check their PREDECESSOR with this
        before writing: the writer is FIFO, so by the time a delta
        executes, its planned prev either landed or failed — and a
        delta written over a failed prev would be unrestorable while
        its drained dirty rows report durable."""
        return self.is_valid_version(version) or self.is_valid_delta(
            version
        )

    def delta_chain_info(self, version: int) -> Optional[dict]:
        """The delta's ``chain.json`` ({version, base, prev,
        num_shards}); None when unreadable/inconsistent."""
        path = os.path.join(
            _delta_dir(self.checkpoint_dir, version), CHAIN_FILE
        )
        try:
            with open(path) as f:
                info = json.load(f)
        except (OSError, ValueError):
            return None
        try:
            if int(info["version"]) != int(version):
                return None
            int(info["base"]), int(info["prev"])
        except (KeyError, TypeError, ValueError):
            return None
        return info

    def chains(self) -> List[dict]:
        """Restorable units, sorted by LINEAGE (base version)
        ascending: ``[{"base": b, "deltas": [d1, ...], "tip":
        newest}]``. A chain's deltas are the longest prefix whose
        ``prev`` links resolve (base → d1 → d2 → …) through valid
        delta dirs — exactly what restore can replay.

        Base order, not tip order: on a healthy disk they agree
        (versions are monotonic and deltas only ride the newest base),
        they disagree only when an older base's chain extends PAST a
        newer base — which can only be a dead pre-crash timeline (a
        restarted writer truncated its restore and opened a fresh
        base; the service then re-ran those versions with new data).
        Ranking that stale chain's numerically-newer tip above the
        fresh base would make restore() return pre-crash rows and
        gc() reclaim the good base under ``keep_max``."""
        bases = [
            v for v in self.list_versions() if self.is_valid_version(v)
        ]
        by_base: Dict[int, List[dict]] = {}
        for d in self.list_deltas():
            if not self.is_valid_delta(d):
                continue
            info = self.delta_chain_info(d)
            if info is None:
                continue
            by_base.setdefault(int(info["base"]), []).append(info)
        out = []
        for base in bases:
            deltas = []
            prev = base
            for info in sorted(
                by_base.get(base, []), key=lambda i: int(i["version"])
            ):
                v = int(info["version"])
                if v <= prev or int(info["prev"]) != prev:
                    break  # gap or fork: chain ends at the last link
                deltas.append(v)
                prev = v
            out.append({
                "base": base,
                "deltas": deltas,
                "tip": deltas[-1] if deltas else base,
            })
        out.sort(key=lambda c: c["base"])
        return out

    def get_valid_latest_version(self) -> Optional[int]:
        """Newest restorable version — the tip of the newest chain
        (== the newest valid base when no deltas exist)."""
        chains = self.chains()
        return chains[-1]["tip"] if chains else None

    # ---- restore -------------------------------------------------------

    def restore(
        self, version: Optional[int] = None
    ) -> Tuple[int, Dict[str, np.ndarray], Dict[str, EmbeddingTable]]:
        """Restore the newest readable state, replaying ``base +
        deltas`` in order — shard-count agnostic per element
        (repartition restore, save_utils.py:206-259), so an N-shard
        base plus M-shard deltas merge fine.

        With no explicit ``version``: a corrupt BASE skips the whole
        chain (older chains restore instead); a corrupt/torn DELTA
        truncates to the longest intact prefix — a replacement worker
        must resume from the freshest *readable* state, not crash-loop
        on a torn file. An explicit ``version`` raises
        ``CorruptCheckpointError`` — the caller asked for that one."""
        if version is not None:
            return self._restore_exact(version)
        chains = self.chains()
        if not chains:
            raise FileNotFoundError(
                f"No valid checkpoint under {self.checkpoint_dir}"
            )
        from elasticdl_tpu.observability import default_registry

        for i, chain in enumerate(reversed(chains)):
            try:
                return self._restore_chain(
                    chain["base"], chain["deltas"], allow_prefix=True
                )
            except CorruptCheckpointError as exc:
                default_registry().counter(
                    "checkpoint_corrupt_versions_total",
                    "Checkpoint versions skipped at restore because a "
                    "shard file failed to decode",
                ).inc()
                older = len(chains) - i - 1
                logger.error(
                    "Checkpoint base %d is corrupt (%s); falling "
                    "back to %s older chain(s)",
                    chain["base"], exc, older,
                )
        raise FileNotFoundError(
            f"Every retained checkpoint chain under "
            f"{self.checkpoint_dir} is corrupt "
            f"(tried bases {[c['base'] for c in chains]})"
        )

    def _restore_exact(self, version: int):
        if self.is_valid_version(version):
            return self._restore_chain(version, [], allow_prefix=False)
        for chain in self.chains():
            if version in chain["deltas"]:
                idx = chain["deltas"].index(version)
                return self._restore_chain(
                    chain["base"], chain["deltas"][: idx + 1],
                    allow_prefix=False,
                )
        raise FileNotFoundError(
            f"Invalid checkpoint version "
            f"{_version_dir(self.checkpoint_dir, version)}"
        )

    def _restore_chain(self, base: int, deltas: List[int],
                       allow_prefix: bool):
        from elasticdl_tpu.observability import default_registry

        dense: Dict[str, np.ndarray] = {}
        embeddings: Dict[str, EmbeddingTable] = {}
        self.last_restored_meta = {}
        # The base raises on corruption (nothing to fall back on within
        # this chain); the caller skips to an older chain.
        self._load_dir(
            _version_dir(self.checkpoint_dir, base), _SHARD_RE,
            dense, embeddings,
        )
        version = int(base)
        for d in deltas:
            try:
                self._load_dir(
                    _delta_dir(self.checkpoint_dir, d),
                    _DELTA_SHARD_RE, dense, embeddings,
                )
            except CorruptCheckpointError as exc:
                if not allow_prefix:
                    raise
                default_registry().counter(
                    "checkpoint_corrupt_versions_total",
                    "Checkpoint versions skipped at restore because a "
                    "shard file failed to decode",
                ).inc()
                logger.error(
                    "Delta %d is torn (%s); restoring the intact "
                    "chain prefix at version %d", d, exc, version,
                )
                break
            version = int(d)
        if _post_restore_hook is not None:
            _post_restore_hook(self.checkpoint_dir, version)
        return version, dense, embeddings

    def _load_dir(self, vdir: str, shard_re,
                  dense: Dict[str, np.ndarray],
                  embeddings: Dict[str, EmbeddingTable]):
        """Merge every shard file of one element into the accumulators
        (delta rows OVERRIDE earlier chain elements' rows; dense
        leaves replace wholesale)."""
        if not self._dir_valid(vdir, shard_re):
            raise FileNotFoundError(f"Invalid checkpoint element {vdir}")
        for fname in sorted(os.listdir(vdir)):
            if not shard_re.match(fname):
                continue
            path = os.path.join(vdir, fname)
            try:
                with open(path, "rb") as f:
                    payload = tensor_utils.loads(
                        unframe_shard_blob(f.read(), path)
                    )
            except CorruptCheckpointError:
                raise
            except Exception as exc:
                # msgpack raises assorted types on truncated/garbled
                # bytes; all mean the same thing here.
                raise CorruptCheckpointError(
                    f"cannot decode {path}: "
                    f"{type(exc).__name__}: {exc}"
                ) from exc
            validate_shard_payload(payload, path)
            # Tip-wins along a chain: each loaded element overwrites,
            # so after a chain restore this holds the newest element's
            # caller meta (e.g. the row service's shard map).
            self.last_restored_meta = dict(payload.get("meta") or {})
            dense.update(payload.get("dense", {}))
            for tname, slices in payload.get("embeddings", {}).items():
                # An empty (0, D) slice still carries the row dim; a shard
                # that happens to own zero rows of a table must not pin the
                # table to dim 0 (all its rows may live in later shards).
                dim = (
                    slices.values.shape[1]
                    if slices.values.ndim == 2 else 0
                )
                table = embeddings.get(tname)
                if table is not None and (
                    table.num_rows == 0 and slices.ids.size
                    and slices.values.dtype != table.dtype
                ):
                    # A row-less placeholder from an earlier empty shard
                    # must not pin the dtype either.
                    table = None
                if table is None or (table.dim == 0 and dim):
                    # Preserve the saved dtype: step counters serialize
                    # as float64 rows (exact ints past 2^24) and must
                    # not round through a float32 default on restore.
                    dtype = (
                        slices.values.dtype
                        if slices.ids.size else np.float32
                    )
                    fresh = EmbeddingTable(tname, dim, dtype=dtype)
                    if table is not None and table.num_rows:
                        prev_ids, prev_rows = table.to_arrays()
                        fresh.set(prev_ids, prev_rows)
                    table = fresh
                    embeddings[tname] = table
                if slices.ids.size:
                    table.set(slices.ids, slices.values)

    # ---- GC ------------------------------------------------------------

    def gc(self, chains: Optional[List[dict]] = None):
        """Keep the newest ``keep_max`` restorable CHAINS — a base and
        the deltas riding it live and die together, so ``keep_max``
        can never delete a base whose deltas are still the newest
        restorable state (save_utils.py:188-204, extended). Orphaned
        deltas (base gone / linkage broken) are unrestorable garbage
        and are reclaimed too. ``chains`` lets a caller that just
        computed them (the per-save path) skip a second dir scan."""
        if self.keep_max <= 0:
            return
        if chains is None:
            chains = self.chains()
        kept = chains[-self.keep_max:]
        keep_dirs = set()
        for chain in kept:
            keep_dirs.add(_version_dir(self.checkpoint_dir,
                                       chain["base"]))
            for d in chain["deltas"]:
                keep_dirs.add(_delta_dir(self.checkpoint_dir, d))
        for entry in os.listdir(self.checkpoint_dir):
            path = os.path.join(self.checkpoint_dir, entry)
            if entry.endswith(".tmp") and (
                _VERSION_RE.match(entry[:-4])
                or _DELTA_RE.match(entry[:-4])
            ):
                # Stale partial publish: saves to one dir are
                # serialized through one writer and gc runs on that
                # same thread after each publish, so any tmp still
                # present lost its rename (crash/ENOSPC) — and
                # versions are monotonic, so it never gets one.
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                continue
            if not (_VERSION_RE.match(entry) or _DELTA_RE.match(entry)):
                continue
            if os.path.isdir(path) and path not in keep_dirs:
                shutil.rmtree(path, ignore_errors=True)


def capture_tables(tables, delta: bool):
    """Capture ``{name: (ids, rows)}`` for one save from table-like
    views (the caller's views self-lock). A ``delta`` capture DRAINS
    each tracked view's dirty set and returns the drained ids so a
    failed write can ``remark_dirty`` them; untracked views (seq
    maps, step counters — tiny by construction) ride every delta in
    full. A full capture also drains tracked views (discarding the
    ids): the base holds everything, and undrained dirt would make
    the first delta after it re-ship the whole table."""
    captured, dirty_ids = {}, {}
    for name, view in tables.items():
        tracked = getattr(view, "supports_dirty_rows", False)
        if delta and tracked:
            ids, rows = view.dirty_arrays()
            dirty_ids[name] = ids
        elif tracked and hasattr(view, "capture_arrays"):
            # Self-locking views (the hook's _LockedTable): snapshot
            # + dirty-drain must be ONE lock acquisition — a row
            # mutated between separate to_arrays()/clear_dirty()
            # calls would lose its dirty mark without riding the
            # snapshot, and never ride any later delta either.
            ids, rows = view.capture_arrays()
        else:
            ids, rows = view.to_arrays()
            if tracked:
                view.clear_dirty()
        captured[name] = (ids, rows)
    return captured, dirty_ids


def remark_dirty(tables, dirty_ids):
    """Put drained dirty ids back after a failed/refused write — or
    they silently vanish from every future delta."""
    for name, ids in dirty_ids.items():
        view = tables.get(name)
        if view is not None and len(ids):
            view.mark_dirty(ids)


class ChainPlanner:
    """In-memory delta-chain planner for (possibly async) savers.

    ``CheckpointSaver.plan_next`` reads the DISK, which lags a bounded
    write queue: planning save N+1 from disk while save N is still
    queued forks the chain — two deltas naming the same ``prev``, and
    the chain walk drops everything past the fork, silently losing the
    second delta's rows from every restore. The planner instead tracks
    the chain the queued writes will produce, updated optimistically
    at capture time (writes land FIFO, so disk converges).

    Starts conservative (``None`` → next save is a full base): a fresh
    process cannot know whether queued writes from a predecessor
    landed, and one compaction per restart is cheap hygiene. A write
    FAILURE calls ``reset()`` so the next save compacts into a fresh
    base, healing any queued deltas that linked through the failure.
    """

    def __init__(self, delta_chain_max: int):
        self._max = max(0, int(delta_chain_max))
        self._chain: Optional[dict] = None

    def plan(self, version: int) -> Tuple[str, Optional[int],
                                          Optional[int]]:
        """Decide full-vs-delta for ``version`` and advance the
        tracked chain as if the write will succeed."""
        version = int(version)
        chain = self._chain
        if (
            self._max <= 0
            or chain is None
            or chain["len"] >= self._max
            or version <= chain["tip"]
        ):
            self._chain = {"base": version, "len": 0, "tip": version}
            return ("full", None, None)
        base, prev = chain["base"], chain["tip"]
        chain["len"] += 1
        chain["tip"] = version
        return ("delta", base, prev)

    def reset(self):
        self._chain = None
