"""The `Embedding` layer (in-HBM tier).

Counterpart of ``elasticdl.layers.Embedding``
(``elasticdl/python/elasticdl/layers/embedding.py:7-150``) and
``SparseEmbedding`` (``keras/layers/sparse_embedding.py:7-71``). The
reference splits these: the EDL layer owns *no* weights and delegates
lookup to the parameter server; SparseEmbedding owns weights locally. On
TPU there is one layer that always owns its table as a flax param — the
distribution question ("is this table sharded?") is answered by the
auto-partition pass (partition.py) annotating the param's sharding, not by
swapping layer classes (the ModelHandler clone-rewrite becomes a no-op).

Input forms:
- int ids of any shape -> embeddings with a trailing ``dim`` axis
  (dense-input path, layer.call:104),
- `RaggedIds` + ``combiner`` -> ``(batch, dim)`` reduced rows
  (sparse-input path, _sparse_input_call:111).
"""

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from elasticdl_tpu.embedding.combiner import RaggedIds, combine

# Keras Embedding default init == RandomUniform(-0.05, 0.05); the reference
# Go PS lazy row init uses the same range (pkg/common/embedding_table.go:36-44).
EMBEDDING_INIT_SCALE = 0.05

# Param name the auto-partition pass matches on (partition.py).
EMBEDDING_PARAM_NAME = "embedding"


def embedding_init(key, shape, dtype=jnp.float32):
    return jax.random.uniform(
        key, shape, dtype, -EMBEDDING_INIT_SCALE, EMBEDDING_INIT_SCALE
    )


class Embedding(nn.Module):
    """Embedding lookup with optional ragged-input combiner.

    ``input_dim``  — vocabulary size (rows),
    ``output_dim`` — embedding dimension,
    ``combiner``   — sum | mean | sqrtn, required for RaggedIds input.
    """

    input_dim: int
    output_dim: int
    combiner: Optional[str] = None
    param_dtype: jnp.dtype = jnp.float32
    # Table initializer override ((key, shape, dtype) -> array); None =
    # the Keras/reference uniform(-0.05, 0.05). Used by the
    # feature-column surface's ``embedding_column(initializer=...)``.
    initializer: Optional[callable] = None
    # Pallas row-streaming lookup for the ragged path: None = auto,
    # which takes XLA — round-3 device-time measurement overturned the
    # round-2 wall-clock kernel tiers (ops/pallas_embedding
    # use_pallas_lookup, dispatch note there). True pins the kernel
    # (single-device only: under a sharded mesh it would force GSPMD
    # to materialize the full table per shard — use
    # lookup_combine_sharded for an explicit per-shard kernel);
    # False pins XLA.
    pallas: Optional[bool] = None

    def _use_pallas(self, table, ids):
        from elasticdl_tpu.ops.pallas_embedding import use_pallas_lookup

        if self.pallas is not None:
            return self.pallas
        return (
            jax.default_backend() == "tpu"
            and jax.device_count() == 1
            and use_pallas_lookup(table.shape[1], ids.shape[1])
        )

    @nn.compact
    def __call__(self, ids):
        table = self.param(
            EMBEDDING_PARAM_NAME,
            self.initializer or embedding_init,
            (self.input_dim, self.output_dim),
            self.param_dtype,
        )
        if isinstance(ids, RaggedIds):
            if self.combiner is None:
                raise ValueError(
                    "RaggedIds input requires a combiner "
                    "(reference embedding.py:111-133)"
                )
            if self._use_pallas(table, ids.ids):
                from elasticdl_tpu.ops.pallas_embedding import (
                    lookup_combine,
                )

                return lookup_combine(
                    table, ids.ids, ids.weights, self.combiner,
                    force_pallas=True,
                    # An explicit pallas=True pin on a non-TPU backend
                    # (CPU tests) runs the interpreter.
                    interpret=jax.default_backend() != "tpu",
                )
            rows = jnp.take(table, ids.ids, axis=0)
            return combine(rows, ids.weights, self.combiner)
        return jnp.take(table, jnp.asarray(ids), axis=0)
