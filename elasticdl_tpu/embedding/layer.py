"""The `Embedding` layer (in-HBM tier).

Counterpart of ``elasticdl.layers.Embedding``
(``elasticdl/python/elasticdl/layers/embedding.py:7-150``) and
``SparseEmbedding`` (``keras/layers/sparse_embedding.py:7-71``). The
reference splits these: the EDL layer owns *no* weights and delegates
lookup to the parameter server; SparseEmbedding owns weights locally. On
TPU there is one layer that always owns its table as a flax param — the
distribution question ("is this table sharded?") is answered by the
auto-partition pass (partition.py) annotating the param's sharding, not by
swapping layer classes (the ModelHandler clone-rewrite becomes a no-op).

Input forms:
- int ids of any shape -> embeddings with a trailing ``dim`` axis
  (dense-input path, layer.call:104),
- `RaggedIds` + ``combiner`` -> ``(batch, dim)`` reduced rows
  (sparse-input path, _sparse_input_call:111).
"""

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from elasticdl_tpu.embedding.combiner import RaggedIds, combine

# Keras Embedding default init == RandomUniform(-0.05, 0.05); the reference
# Go PS lazy row init uses the same range (pkg/common/embedding_table.go:36-44).
EMBEDDING_INIT_SCALE = 0.05

# Param name the auto-partition pass matches on (partition.py).
EMBEDDING_PARAM_NAME = "embedding"


def embedding_init(key, shape, dtype=jnp.float32):
    return jax.random.uniform(
        key, shape, dtype, -EMBEDDING_INIT_SCALE, EMBEDDING_INIT_SCALE
    )


class Embedding(nn.Module):
    """Embedding lookup with optional ragged-input combiner.

    ``input_dim``  — vocabulary size (rows),
    ``output_dim`` — embedding dimension,
    ``combiner``   — sum | mean | sqrtn, required for RaggedIds input.
    """

    input_dim: int
    output_dim: int
    combiner: Optional[str] = None
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, ids):
        table = self.param(
            EMBEDDING_PARAM_NAME,
            embedding_init,
            (self.input_dim, self.output_dim),
            self.param_dtype,
        )
        if isinstance(ids, RaggedIds):
            if self.combiner is None:
                raise ValueError(
                    "RaggedIds input requires a combiner "
                    "(reference embedding.py:111-133)"
                )
            rows = jnp.take(table, ids.ids, axis=0)
            return combine(rows, ids.weights, self.combiner)
        return jnp.take(table, jnp.asarray(ids), axis=0)
