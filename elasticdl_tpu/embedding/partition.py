"""Auto-partition pass: which tables get sharded over the mesh.

Counterpart of the reference ModelHandler's rewrite
(``elasticdl/python/common/model_handler.py:85-89``, ``:222-232``): Keras
embeddings bigger than 2MB are swapped for PS-backed EDL embeddings. Here
no layer is swapped — the pass walks the param pytree and assigns a
``PartitionSpec`` per leaf: embedding tables over the threshold are
row-sharded over the data axis (rows live once across the mesh, the
gather/scatter ride ICI), everything else is replicated.

MeshRunner consumes the resulting spec tree for param/optimizer-state
placement, which also co-shards optimizer slot rows with their table
(reference slot co-location, ps/parameters.py:156).
"""

from typing import Callable, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from elasticdl_tpu.embedding.layer import EMBEDDING_PARAM_NAME

# model_handler.py:85-89 threshold parity.
DEFAULT_PARTITION_THRESHOLD_BYTES = 2 * 1024 * 1024


def _leaf_nbytes(leaf) -> int:
    shape = getattr(leaf, "shape", ())
    dtype = np.dtype(getattr(leaf, "dtype", np.float32))
    return int(np.prod(shape, dtype=np.int64)) * dtype.itemsize


def embedding_partition_rule(
    threshold_bytes: int = DEFAULT_PARTITION_THRESHOLD_BYTES,
    axis: str = "dp",
    axis_size: Optional[int] = None,
) -> Callable:
    """Build a ``(path, leaf) -> PartitionSpec`` rule.

    A leaf is a shardable table iff its param name is the Embedding layer's
    table param, it is 2-D, its row count divides the mesh axis, and it
    exceeds the size threshold.
    """

    def rule(path, leaf):
        names = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
        shape = getattr(leaf, "shape", ())
        if (
            names
            and names[-1] == EMBEDDING_PARAM_NAME
            and len(shape) == 2
            and _leaf_nbytes(leaf) > threshold_bytes
            and (axis_size is None or shape[0] % axis_size == 0)
        ):
            return P(axis, None)
        return P()

    return rule


def tree_partition_specs(params, rule) -> "jax.tree_util.PyTreeDef":
    """Map the rule over a param pytree -> pytree of PartitionSpecs."""
    return jax.tree_util.tree_map_with_path(rule, params)


def tree_shardings(params, mesh: Mesh, rule):
    """Same, but as NamedShardings for device_put/jit."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, rule(path, leaf)), params
    )
