"""Host-side lazy embedding table (host tier).

Counterpart of the reference PS tables (``elasticdl/python/ps/
embedding_table.py:10-124``, ``elasticdl/pkg/common/embedding_table.go``):
a dict id -> 1-D row, materialized on first get with a deterministic
initializer, plus constant-initialized slot-table variants for optimizer
state. On TPU this tier backs tables too large for HBM (rows are pulled
into the device batch and scattered back by the sparse engine) and is the
unit the checkpoint repartitioner works over; the default in-HBM path does
not use it.

Rows initialize deterministically from (table name, id) so a re-created
shard produces identical values — the reference instead relied on the PS
pod surviving; we cannot (SURVEY.md §7 stage 5).
"""

from typing import Dict, Iterable

import numpy as np

from elasticdl_tpu.embedding.layer import EMBEDDING_INIT_SCALE


def get_slot_table_name(table_name: str, slot_name: str) -> str:
    """Reference naming: ps/embedding_table.py:122."""
    return f"{table_name}-{slot_name}"


def _row_seed(name: str, row_id: int) -> int:
    import zlib

    return (zlib.crc32(name.encode("utf-8")) * 2654435761 + int(row_id)) % (
        2**32
    )


def supports_dirty_rows(table) -> bool:
    """Whether a table-like view can serve incremental (delta)
    checkpoints: it tracks the rows touched since the last drain.
    Checkpoint adapters (seq maps, step counters) report False and are
    captured in full inside every delta — they are tiny by
    construction."""
    return bool(getattr(table, "supports_dirty_rows", False))


class EmbeddingTable:
    """Lazy id->row store with deterministic per-row init.

    Tracks **dirty rows** — ids materialized or written since the last
    ``dirty_arrays`` drain — so incremental (delta) checkpoints move
    only the working set instead of the whole table. Reads of existing
    rows stay free: only a first materialization or a ``set`` marks.
    Tracking is OFF until a checkpoint consumer enables it
    (``configure_checkpoint``/``CheckpointHook``): without a drain,
    the marked-ids set would grow to every touched row for nothing.
    """

    def __init__(
        self,
        name: str,
        dim: int,
        initializer: str = "uniform",
        is_slot: bool = False,
        slot_init_value: float = 0.0,
        dtype=np.float32,
    ):
        self.name = name
        self.dim = int(dim)
        self.initializer = initializer
        self.is_slot = is_slot
        self.slot_init_value = float(slot_init_value)
        self.dtype = np.dtype(dtype)
        self.vectors: Dict[int, np.ndarray] = {}
        self._dirty: set = set()
        self._track_dirty = False

    def _init_row(self, row_id: int) -> np.ndarray:
        if self.is_slot or self.initializer == "zeros":
            return np.full((self.dim,), self.slot_init_value, self.dtype)
        rng = np.random.RandomState(_row_seed(self.name, row_id))
        if self.initializer == "normal":
            return rng.normal(0.0, 0.05, self.dim).astype(self.dtype)
        return rng.uniform(
            -EMBEDDING_INIT_SCALE, EMBEDDING_INIT_SCALE, self.dim
        ).astype(self.dtype)

    def get(self, ids: Iterable[int]) -> np.ndarray:
        """Batch lookup; lazily initializes unseen rows
        (ps/embedding_table.py:51-62)."""
        ids = list(ids)
        out = np.empty((len(ids), self.dim), self.dtype)
        for i, row_id in enumerate(ids):
            row = self.vectors.get(int(row_id))
            if row is None:
                row = self._init_row(int(row_id))
                self.vectors[int(row_id)] = row
                # Materialization dirties: a lazily created row must
                # ride the next delta so restore-from-chain conserves
                # it (row-conservation invariant) without re-reading.
                if self._track_dirty:
                    self._dirty.add(int(row_id))
            out[i] = row
        return out

    def set(self, ids: Iterable[int], values: np.ndarray) -> None:
        values = np.asarray(values, self.dtype)
        for i, row_id in enumerate(ids):
            self.vectors[int(row_id)] = values[i].copy()
            if self._track_dirty:
                self._dirty.add(int(row_id))

    def erase(self, ids) -> int:
        """Drop rows (tiered-store demotion, storage/tiered.py);
        absent ids are ignored. Returns the number actually erased.
        Erased ids leave the dirty set — their bytes are gone, and a
        later dirty drain re-reading them through get() would
        resurrect them as fresh lazy inits."""
        erased = 0
        for row_id in ids:
            if self.vectors.pop(int(row_id), None) is not None:
                erased += 1
            self._dirty.discard(int(row_id))
        return erased

    def contains(self, ids) -> np.ndarray:
        """Bool membership mask, without materializing anything."""
        return np.array(
            [int(i) in self.vectors for i in ids], bool
        )

    def all_ids(self) -> np.ndarray:
        """Every materialized id, sorted — enumeration without row
        bytes (live-migration range scans, shard-map erase sweeps)."""
        return np.array(sorted(self.vectors), np.int64)

    def peek(self, ids) -> np.ndarray:
        """Read EXISTING rows without materializing or dirtying —
        what a live migration streams (absent ids raise KeyError: the
        caller enumerated them, so absence is a logic error)."""
        out = np.empty((len(list(ids)), self.dim), self.dtype)
        for i, row_id in enumerate(ids):
            out[i] = self.vectors[int(row_id)]
        return out

    @property
    def num_rows(self) -> int:
        return len(self.vectors)

    # ---- dirty-row tracking (incremental checkpoints) -----------------

    @property
    def supports_dirty_rows(self) -> bool:
        """True once a checkpoint consumer enabled tracking — the
        delta-capture predicate. Reporting capability instead of
        enablement would make delta captures silently empty."""
        return self._track_dirty

    def enable_dirty_tracking(self) -> None:
        self._track_dirty = True

    @property
    def dirty_count(self) -> int:
        return len(self._dirty)

    def dirty_arrays(self):
        """(ids, rows) of rows touched since the last drain, sorted by
        id, and CLEAR the dirty set — the delta-checkpoint capture
        unit. On a later write failure the caller re-marks via
        ``mark_dirty`` so the rows re-enter the next delta."""
        if not self._dirty:
            return (np.zeros((0,), np.int64),
                    np.zeros((0, self.dim), self.dtype))
        ids = np.array(sorted(self._dirty), np.int64)
        self._dirty.clear()
        rows = np.stack([self.vectors[int(i)] for i in ids])
        return ids, rows

    def mark_dirty(self, ids) -> None:
        if self._track_dirty:
            self._dirty.update(int(i) for i in np.asarray(ids).ravel())

    def clear_dirty(self) -> None:
        """Forget tracked dirt — called after a restore refill, whose
        rows already match the on-disk state they came from."""
        self._dirty.clear()

    def to_arrays(self):
        """(ids, rows) sorted by id — checkpoint serialization unit."""
        if not self.vectors:
            return (np.zeros((0,), np.int64),
                    np.zeros((0, self.dim), self.dtype))
        ids = np.array(sorted(self.vectors), np.int64)
        rows = np.stack([self.vectors[int(i)] for i in ids])
        return ids, rows

    @classmethod
    def from_arrays(cls, name, ids, rows, **kwargs):
        table = cls(name, rows.shape[1] if rows.ndim == 2 else 0, **kwargs)
        for row_id, row in zip(ids, rows):
            table.vectors[int(row_id)] = np.asarray(row, table.dtype)
        return table

    def debug_info(self) -> str:
        size = self.num_rows * self.dim * self.dtype.itemsize
        return (
            f"EmbeddingTable {self.name}: rows={self.num_rows} "
            f"dim={self.dim} bytes={size}"
        )
