"""Explicit, versioned row-placement: the shard map.

Until PR 12 the row plane's topology was frozen at launch: clients
hashed ``id % N`` over the ``--row_service_addr`` list and nothing
could move a row without a checkpoint-restore repartition (PR 10).
This module makes placement an explicit, *versioned* object — the
shape Elastic Model Aggregation (arxiv 2204.03211) argues the
parameter-service tier needs, and the same slot-map design Redis
Cluster / HBase use for live resharding:

- the id space folds into ``NUM_BUCKETS`` **buckets** (``id %
  NUM_BUCKETS`` — dense vocab ids spread uniformly, so contiguous
  bucket ranges balance load);
- a ``ShardMap`` assigns disjoint bucket **ranges** covering the whole
  bucket space to shards (index into its ``shards`` address list), and
  carries a **monotonic version**: every topology change (range moved,
  shard added, replica set updated) is a new map with a bumped
  version;
- **hot-row read replicas** ride the same map: ``replicas[table][id]``
  lists extra shards that serve *reads* for that id (writes stay
  single-home; the home pushes async refreshes — row_service.py).

Movement algebra is pure (``move_range``/``move_shard``/``add_shard``/
``with_replicas`` return new maps); the *protocol* that makes a move
safe — copy, catch-up, fence, cutover — lives in
``master/row_reshard.py`` (the authority) and ``row_service.py`` (the
shards). Servers enforce the map: a pull/push for buckets a shard does
not own under its installed map returns a retryable REDIRECT carrying
the newer map, which is how stale clients (and clients that predate a
split) converge without any out-of-band channel.
"""

import json
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# The id space folds into this many buckets (id % NUM_BUCKETS). A
# power of two with plenty of headroom: the finest possible split is
# one bucket, so 8192 buckets support far more shards than the row
# plane will see while keeping the owner lookup table 16KB.
NUM_BUCKETS = 8192


def bucket_of(ids) -> np.ndarray:
    """Bucket index per id (vectorized). Non-negative for the int64
    row ids this repo uses everywhere (numpy mod follows the divisor's
    sign, so even a stray negative id lands in [0, NUM_BUCKETS))."""
    return np.asarray(ids, np.int64) % NUM_BUCKETS


class ShardMapError(ValueError):
    pass


def _normalize(ranges: Sequence[Tuple[int, int, int]]):
    """Sort by lo and coalesce adjacent ranges owned by one shard —
    the canonical form equality/serialization use."""
    out: List[Tuple[int, int, int]] = []
    for lo, hi, shard in sorted(
        (int(l), int(h), int(s)) for l, h, s in ranges
    ):
        if out and out[-1][2] == shard and out[-1][1] == lo:
            out[-1] = (out[-1][0], hi, shard)
        else:
            out.append((lo, hi, shard))
    return out


class ShardMap:
    """One immutable placement epoch: bucket ranges → shards, plus the
    hot-row replica sets. Mutators return NEW maps with ``version + 1``
    — the monotonic version is the fencing token every server and
    client compares."""

    def __init__(self, version: int, shards: Sequence[str],
                 ranges: Sequence[Tuple[int, int, int]],
                 replicas: Optional[Dict[str, Dict[int, Tuple[int, ...]]]]
                 = None):
        self.version = int(version)
        self.shards = [str(a) for a in shards]
        self.ranges = _normalize(ranges)
        self.replicas = {
            str(t): {int(i): tuple(int(s) for s in reps)
                     for i, reps in per.items()}
            for t, per in (replicas or {}).items()
        }
        self._owner: Optional[np.ndarray] = None
        self.validate()

    # ---- construction / validation ------------------------------------

    @classmethod
    def bootstrap(cls, shards: Sequence[str]) -> "ShardMap":
        """Version-1 map: the bucket space split into N even contiguous
        ranges (shard s owns [s*B/N, (s+1)*B/N)). Dense vocab ids
        spread uniformly over buckets, so even ranges balance load."""
        shards = list(shards)
        n = len(shards)
        if not n:
            raise ShardMapError("bootstrap needs at least one shard")
        bounds = [round(s * NUM_BUCKETS / n) for s in range(n + 1)]
        return cls(
            1, shards,
            [(bounds[s], bounds[s + 1], s) for s in range(n)
             if bounds[s] < bounds[s + 1]],
        )

    def validate(self):
        if self.version < 1:
            raise ShardMapError(f"version must be >= 1: {self.version}")
        if not self.shards:
            raise ShardMapError("shard map has no shards")
        cursor = 0
        for lo, hi, shard in self.ranges:
            if lo != cursor:
                raise ShardMapError(
                    f"ranges must cover [0, {NUM_BUCKETS}) without "
                    f"gaps/overlap: expected lo={cursor}, got {lo}"
                )
            if hi <= lo:
                raise ShardMapError(f"empty/inverted range ({lo}, {hi})")
            if not 0 <= shard < len(self.shards):
                raise ShardMapError(
                    f"range ({lo}, {hi}) names shard {shard} of "
                    f"{len(self.shards)}"
                )
            cursor = hi
        if cursor != NUM_BUCKETS:
            raise ShardMapError(
                f"ranges cover [0, {cursor}), need [0, {NUM_BUCKETS})"
            )
        for table, per in self.replicas.items():
            for i, reps in per.items():
                for s in reps:
                    if not 0 <= s < len(self.shards):
                        raise ShardMapError(
                            f"replica set for {table}:{i} names shard "
                            f"{s} of {len(self.shards)}"
                        )

    # ---- lookup --------------------------------------------------------

    @property
    def owner_table(self) -> np.ndarray:
        """int32[NUM_BUCKETS] bucket → shard lookup (built lazily, the
        map is immutable)."""
        if self._owner is None:
            owner = np.empty(NUM_BUCKETS, np.int32)
            for lo, hi, shard in self.ranges:
                owner[lo:hi] = shard
            self._owner = owner
        return self._owner

    def home_of_ids(self, ids) -> np.ndarray:
        """Home shard index per id (vectorized)."""
        return self.owner_table[bucket_of(ids)]

    def owns(self, shard: int, ids) -> np.ndarray:
        return self.home_of_ids(ids) == int(shard)

    def ranges_of(self, shard: int) -> List[Tuple[int, int]]:
        return [(lo, hi) for lo, hi, s in self.ranges
                if s == int(shard)]

    def buckets_owned(self, shard: int) -> int:
        return sum(hi - lo for lo, hi in self.ranges_of(shard))

    def replica_targets(self, table: str, row_id: int) -> Tuple[int, ...]:
        per = self.replicas.get(table)
        if not per:
            return ()
        return per.get(int(row_id), ())

    # ---- movement algebra (pure; version + 1) --------------------------

    def _bump(self, ranges=None, shards=None, replicas=None) -> "ShardMap":
        return ShardMap(
            self.version + 1,
            self.shards if shards is None else shards,
            self.ranges if ranges is None else ranges,
            self.replicas if replicas is None else replicas,
        )

    def move_range(self, lo: int, hi: int, target: int) -> "ShardMap":
        """Reassign buckets [lo, hi) to ``target``. The migration
        protocol calls this at CUTOVER — after the bytes moved."""
        lo, hi, target = int(lo), int(hi), int(target)
        if not 0 <= lo < hi <= NUM_BUCKETS:
            raise ShardMapError(f"bad range ({lo}, {hi})")
        if not 0 <= target < len(self.shards):
            raise ShardMapError(f"unknown target shard {target}")
        out = []
        for rlo, rhi, shard in self.ranges:
            left = (rlo, min(rhi, lo), shard)
            right = (max(rlo, hi), rhi, shard)
            for piece in (left, right):
                if piece[1] > piece[0]:
                    out.append(piece)
        out.append((lo, hi, target))
        return self._bump(ranges=out)

    def move_shard(self, source: int, target: int) -> "ShardMap":
        """Reassign EVERY bucket of ``source`` to ``target`` (merge:
        the source keeps its slot in ``shards`` but owns nothing — a
        drained shard can be retired by ops once clients converge)."""
        source, target = int(source), int(target)
        out = [(lo, hi, target if s == source else s)
               for lo, hi, s in self.ranges]
        return self._bump(ranges=out)

    def add_shard(self, addr: str) -> "ShardMap":
        """Append a (initially empty) shard — the split target."""
        if addr in self.shards:
            raise ShardMapError(f"shard {addr} already in the map")
        return self._bump(shards=self.shards + [str(addr)])

    def retire_shard(self, shard: int) -> "ShardMap":
        """Drop a drained shard's slot from the address list (the
        compaction step after a ``merge``): it must own zero buckets
        and appear in no replica set. Every shard index above it
        shifts down one — the authority re-distributes the new epoch
        with each server's new ``shard_id``, and stale clients
        converge through the usual version fencing."""
        shard = int(shard)
        if not 0 <= shard < len(self.shards):
            raise ShardMapError(f"unknown shard {shard}")
        if len(self.shards) < 2:
            raise ShardMapError("cannot retire the last shard")
        if self.buckets_owned(shard):
            raise ShardMapError(
                f"shard {shard} still owns "
                f"{self.buckets_owned(shard)} bucket(s); merge it "
                "away first"
            )
        for table, per in self.replicas.items():
            for i, reps in per.items():
                if shard in reps:
                    raise ShardMapError(
                        f"shard {shard} still replicates "
                        f"{table}:{i}; refresh replicas first"
                    )
        shards = [a for s, a in enumerate(self.shards) if s != shard]
        ranges = [
            (lo, hi, s - 1 if s > shard else s)
            for lo, hi, s in self.ranges
        ]
        replicas = {
            table: {
                i: tuple(s - 1 if s > shard else s for s in reps)
                for i, reps in per.items()
            }
            for table, per in self.replicas.items()
        }
        return self._bump(ranges=ranges, shards=shards,
                          replicas=replicas)

    def split_plan(self, shard: int) -> Tuple[int, int]:
        """The upper half of ``shard``'s largest range — what a split
        migrates away. Raises when the shard owns a single bucket
        (nothing left to split)."""
        ranges = self.ranges_of(shard)
        if not ranges:
            raise ShardMapError(f"shard {shard} owns no buckets")
        lo, hi = max(ranges, key=lambda r: r[1] - r[0])
        if hi - lo < 2:
            raise ShardMapError(
                f"shard {shard}'s largest range ({lo}, {hi}) cannot "
                "split further"
            )
        mid = (lo + hi) // 2
        return mid, hi

    def with_replicas(
        self, replicas: Dict[str, Dict[int, Tuple[int, ...]]]
    ) -> "ShardMap":
        """Replace the hot-row replica assignment wholesale (the
        authority recomputes it from the shards' hot sets)."""
        return self._bump(replicas=replicas)

    # ---- serialization -------------------------------------------------

    def to_json(self) -> dict:
        """Plain-container form (msgpack/json safe; replica dicts as
        pair lists — json objects cannot key on ints)."""
        return {
            "version": self.version,
            "num_buckets": NUM_BUCKETS,
            "shards": list(self.shards),
            "ranges": [list(r) for r in self.ranges],
            "replicas": {
                table: [[i, list(reps)]
                        for i, reps in sorted(per.items())]
                for table, per in sorted(self.replicas.items())
            },
        }

    @classmethod
    def from_json(cls, blob: dict) -> "ShardMap":
        if int(blob.get("num_buckets", NUM_BUCKETS)) != NUM_BUCKETS:
            raise ShardMapError(
                f"map was built over {blob.get('num_buckets')} buckets, "
                f"this build uses {NUM_BUCKETS}"
            )
        return cls(
            blob["version"], blob["shards"],
            [tuple(r) for r in blob["ranges"]],
            {
                table: {int(i): tuple(reps) for i, reps in pairs}
                for table, pairs in (blob.get("replicas") or {}).items()
            },
        )

    def __eq__(self, other):
        return (isinstance(other, ShardMap)
                and self.to_json() == other.to_json())

    def __repr__(self):
        return (
            f"ShardMap(v{self.version}, {len(self.shards)} shards, "
            f"{len(self.ranges)} ranges, "
            f"{sum(len(p) for p in self.replicas.values())} replicated "
            "ids)"
        )


class ClientShardMap:
    """Thread-safe monotonic holder of the newest map a client has
    seen. ``update`` from a REDIRECT payload only ever moves forward —
    two pool threads racing redirects from different shards cannot
    regress the routing epoch."""

    def __init__(self, shard_map: ShardMap):
        self._lock = threading.Lock()
        self._map = shard_map

    def get(self) -> ShardMap:
        with self._lock:
            return self._map

    @property
    def version(self) -> int:
        return self.get().version

    def update(self, map_json: dict) -> bool:
        """Adopt ``map_json`` if it is newer; returns whether the
        routing epoch advanced."""
        fresh = ShardMap.from_json(map_json)
        with self._lock:
            if fresh.version <= self._map.version:
                return False
            self._map = fresh
            return True


def dump_map(shard_map: ShardMap) -> str:
    return json.dumps(shard_map.to_json(), indent=2, sort_keys=True)
