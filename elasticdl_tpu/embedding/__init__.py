"""Sparse embedding engine (TPU-native).

The reference implements sparse embeddings as an external key-value store on
parameter-server pods with lazy row init and a worker-side delegate that
captures gradients (``elasticdl/python/ps/embedding_table.py``,
``elasticdl/python/elasticdl/embedding_delegate.py``). On TPU the table is a
dense ``(vocab, dim)`` array living in HBM, row-sharded over the device mesh,
and gradients flow through the gather inside the jit-compiled step — no RPC
plane, no delegate.

Two tiers:

- **In-HBM tier** (`layer.Embedding`): the table is a flax param; the
  auto-partition pass (`partition.py`, counterpart of the reference
  ModelHandler's 2MB rewrite) row-shards big tables over the mesh.
- **Host tier** (`table.EmbeddingTable`): a lazy, dict-backed row store
  mirroring the reference PS table semantics, used for >HBM tables and for
  checkpoint repartitioning. `host_engine.HostEmbeddingEngine` trains it
  end to end: per-batch dedup + bucket-padded row blocks on device,
  gradients w.r.t. the block scattered back through the row optimizers,
  double-buffered row prefetch.
"""

from elasticdl_tpu.embedding.combiner import RaggedIds, combine
from elasticdl_tpu.embedding.layer import Embedding
from elasticdl_tpu.embedding.partition import (
    DEFAULT_PARTITION_THRESHOLD_BYTES,
    embedding_partition_rule,
    tree_partition_specs,
)
from elasticdl_tpu.embedding.optimizer import (
    HostOptimizerWrapper,
    RowOptimizer,
    init_slot_tables,
    make_row_optimizer,
    sparse_apply,
    unique_pad,
)
from elasticdl_tpu.embedding.host_engine import (
    HostEmbedding,
    HostEmbeddingEngine,
    HostStepRunner,
    PreparedBatch,
    build_host_eval_step,
    build_host_train_step,
    host_rows_template,
)
from elasticdl_tpu.embedding.row_service import (
    HostRowService,
    make_remote_engine,
)
from elasticdl_tpu.embedding.table import EmbeddingTable, get_slot_table_name

__all__ = [
    "HostEmbedding",
    "HostEmbeddingEngine",
    "HostRowService",
    "make_remote_engine",
    "HostStepRunner",
    "PreparedBatch",
    "build_host_eval_step",
    "build_host_train_step",
    "host_rows_template",
    "HostOptimizerWrapper",
    "RowOptimizer",
    "init_slot_tables",
    "make_row_optimizer",
    "sparse_apply",
    "unique_pad",
    "RaggedIds",
    "combine",
    "Embedding",
    "EmbeddingTable",
    "get_slot_table_name",
    "DEFAULT_PARTITION_THRESHOLD_BYTES",
    "embedding_partition_rule",
    "tree_partition_specs",
]
