"""Device-tier sparse embedding training: the PS hot path, in HBM.

The reference trains big embedding tables on parameter-server pods —
pull rows, compute, push row grads, C++ kernels apply them
(``pkg/ps/server.go:162-192``, ``pkg/kernel/capi/kernel_api.cc:6-96``).
The TPU-native shape when the table FITS in HBM (the v5e has 16 GB —
a 4M x 256 f32 table is 4 GB): keep the table next to the model and
make the whole step one XLA program, with the sparse structure
preserved —

- **forward** reads the table through the measured Pallas row-streaming
  lookup (``ops/pallas_embedding.lookup_combine`` auto-dispatch: each
  touched row leaves HBM exactly once; the table never enters autodiff,
  so no dense (V, D) gradient ever exists),
- **backward** produces row gradients for only the batch's unique ids
  (linear-transpose of the combiner — exact, no hand math),
- **update** scatters through the in-place Pallas row kernels
  (``embedding/optimizer.sparse_apply``: one HBM read+write per touched
  row, slots included — the C++ kernel family this replaces).

``tables/slots`` ride a ``SparseTrainState`` (a ``TrainState`` with
extra pytree fields), so jit/donation/checkpoint treat them like any
other state leaf. Models read per-batch embeddings through the
``SparseEmbed`` module (collection ``sparse_emb``), mirroring the host
tier's ``HostEmbedding``/``host_rows`` contract.
"""

from dataclasses import dataclass
from typing import Any, Callable, Dict, FrozenSet, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from flax import struct
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.core.train_state import TrainState
from elasticdl_tpu.embedding.combiner import COMBINERS, RaggedIds, combine
from elasticdl_tpu.embedding.optimizer import (
    RowOptimizer,
    init_slot_tables,
    pack_table,
    sparse_apply,
    sparse_apply_packed,
)
from elasticdl_tpu.embedding.partition import (
    DEFAULT_PARTITION_THRESHOLD_BYTES,
)

SPARSE_EMB_COLLECTION = "sparse_emb"


@dataclass(frozen=True)
class TableSpec:
    """One device-resident sparse table: ``feature_key`` names the
    batch feature carrying its RaggedIds (or dense (B, L) ids)."""

    name: str
    vocab: int
    dim: int
    combiner: str = "sum"
    feature_key: str = "ids"

    def __post_init__(self):
        if self.combiner not in COMBINERS:
            raise ValueError(f"combiner must be one of {COMBINERS}")


class SparseEmbed(nn.Module):
    """Read the runner-computed (B, dim) combined embedding for one
    table (collection ``sparse_emb``). The model never touches the
    (V, D) table — the sparse step owns lookup and update."""

    table_name: str
    output_dim: int

    @nn.compact
    def __call__(self):
        return self.variable(
            SPARSE_EMB_COLLECTION,
            self.table_name,
            lambda: jnp.zeros((1, self.output_dim), jnp.float32),
        ).value


class SparseTrainState(TrainState):
    """TrainState + the sparse plane: {table: (V, D)} main tables,
    their slot tables, and per-table apply counters (Adam bias
    correction — reference kernel_api.cc:52-55 step semantics)."""

    tables: Dict[str, jnp.ndarray] = struct.field(default_factory=dict)
    slot_tables: Dict[str, Dict[str, jnp.ndarray]] = struct.field(
        default_factory=dict
    )
    table_steps: Dict[str, jnp.ndarray] = struct.field(
        default_factory=dict
    )


def _ragged(ids) -> RaggedIds:
    if isinstance(ids, RaggedIds):
        return ids
    ids = jnp.asarray(ids)
    return RaggedIds(
        ids=ids.astype(jnp.int32),
        weights=jnp.ones(ids.shape, jnp.float32),
    )


def _unique_pad_jit(ids_flat: jnp.ndarray, vocab: int):
    """In-jit static-shape dedup: (uids, inverse) with uids padded to
    ``ids_flat.size`` by the out-of-range sentinel ``vocab`` (the pad
    contract every Pallas row kernel skips on)."""
    uids, inverse = jnp.unique(
        ids_flat, return_inverse=True, size=ids_flat.size,
        fill_value=vocab,
    )
    return uids.astype(jnp.int32), inverse.astype(jnp.int32)


def _row_grads(d_emb, uids, inverse, ragged, combiner):
    """Exact row gradients via linear transpose of the combiner (it is
    linear in the rows): (B, dim) cotangent -> (U, dim) row grads,
    scatter-add over duplicate ids included. XLA's native strength —
    the lookup kernel's VJP design note (ops/pallas_embedding.py)."""
    n_unique = uids.shape[0]
    dim = d_emb.shape[-1]
    inv = inverse.reshape(ragged.ids.shape)

    def lookup(rows):
        return combine(
            jnp.take(rows, inv, axis=0), ragged.weights, combiner
        )

    transpose = jax.linear_transpose(
        lookup, jax.ShapeDtypeStruct((n_unique, dim), jnp.float32)
    )
    (rows_ct,) = transpose(d_emb.astype(jnp.float32))
    return rows_ct


def sparse_apply_sharded(opt: RowOptimizer, table, slot_tables, unique_ids,
                         row_grads, step, mesh, axis: str,
                         use_pallas: str = "auto",
                         interpret: bool = False):
    """``sparse_apply`` over a ROW-SHARDED ``(V, D)`` table: each device
    owns rows [idx*V/n, (idx+1)*V/n) and applies only the updates whose
    (globally unique) id lands in its range — the TPU-native analogue of
    the reference's id%N scatter to parameter-server pods
    (``worker/worker.py:570-580``, ``common/hash_utils.py:4-49``), with
    contiguous row ranges instead of modulo so each shard stays one
    dense slice (the placement ``checkpoint/saver.py`` repartitions).

    Ids out of the local range (including the global pad sentinel
    ``vocab``) map to the LOCAL pad sentinel ``shard_rows``, which
    ``sparse_apply`` drops (XLA path ``mode="drop"``; kernels skip) —
    so pads and remote ids cost nothing locally. ``unique_ids`` must be
    globally deduplicated (``_unique_pad_jit``): each real id then
    updates exactly one shard exactly once. Slot tables co-shard with
    their main table; ``step`` is the replicated apply counter."""
    num_shards = mesh.shape[axis]
    vocab = table.shape[0]
    if vocab % num_shards:
        raise ValueError(
            f"vocab {vocab} not divisible by mesh axis {axis!r} size "
            f"{num_shards}; pad the table"
        )
    shard_rows = vocab // num_shards

    def per_shard(tbl, slots, uids, grads, step_):
        lo = (jax.lax.axis_index(axis) * shard_rows).astype(jnp.int32)
        local = uids.astype(jnp.int32) - lo
        in_range = (local >= 0) & (local < shard_rows)
        local = jnp.where(in_range, local, shard_rows)
        return sparse_apply(
            opt, tbl, slots, local, grads, step_,
            use_pallas=use_pallas, interpret=interpret,
        )

    # check_vma=False for the same reason as lookup_combine_sharded:
    # the forced-kernel path's pallas_call outputs carry no varying-mesh
    # annotation; the out_specs make the row sharding explicit.
    return jax.shard_map(
        per_shard, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(None), P(None, None),
                  P()),
        out_specs=(P(axis, None), P(axis, None)), check_vma=False,
    )(table, slot_tables, jnp.asarray(unique_ids),
      jnp.asarray(row_grads), jnp.asarray(step))


def build_sparse_train_step(
    loss_fn: Callable,
    specs: Tuple[TableSpec, ...],
    row_opt: RowOptimizer,
    template,
    use_pallas: str = "auto",
    interpret: bool = False,
    mesh=None,
    axis: str = "dp",
    sharded_tables: FrozenSet[str] = frozenset(),
    packed_slots: bool = False,
) -> Callable:
    """Build ``(SparseTrainState, batch) -> (state, metrics)`` — one
    jittable program covering lookup, model fwd/bwd, dense apply, and
    the sparse row-kernel apply. ``template`` is the model's
    ``sparse_emb`` collection structure (``sparse_template``).
    Composable with ``lax.scan`` for the fused multi-step task path
    (``build_sparse_multi_step``).

    With ``mesh``, tables named in ``sharded_tables`` are row-sharded
    over ``axis``: lookup goes through
    ``lookup_combine_sharded``'s shard_map path and the row update
    through ``sparse_apply_sharded`` — same math, partitioned by row
    range, so the dp-N trajectory equals dp-1 exactly (dryrun case 5).
    Everything else (dedup, model fwd/bwd, dense apply) stays in the
    global view and GSPMD partitions it over the batch sharding.

    ``packed_slots``: slot tables live INSIDE the main table rows
    ((V, D*(1+n_slots)), optimizer.pack_table) so the apply is one
    gather + one scatter instead of (1 + n_slots) of each — the
    measured scatter-latency win (optimizer.sparse_apply_packed).
    Single-mesh only; forward narrows gathered rows to the first D
    columns."""
    from elasticdl_tpu.core.step import _call_loss
    from elasticdl_tpu.embedding.host_engine import _nest_rows
    from elasticdl_tpu.ops.pallas_embedding import (
        lookup_combine,
        lookup_combine_sharded,
    )
    if sharded_tables and mesh is None:
        raise ValueError("sharded_tables requires a mesh")
    if packed_slots and (mesh is not None or sharded_tables):
        raise ValueError(
            "packed_slots is single-mesh only (the row-sharded path "
            "keeps split tables)"
        )
    if packed_slots and use_pallas in ("always", "fused"):
        raise ValueError(
            "packed_slots uses the XLA gather/scatter path; the Pallas "
            "row kernels (serial and fused) operate on split tables"
        )

    def train_step(state: SparseTrainState, batch):
        state, rng = state.next_rng()
        features = batch["features"]

        embs, lookups = {}, {}
        for spec in specs:
            ragged = _ragged(features[spec.feature_key])
            table = state.tables[spec.name]
            # Forward from the LIVE table (Pallas auto-dispatch); the
            # table is not differentiated — row grads come from the
            # combiner transpose below.
            if packed_slots:
                # Gather the packed rows, narrow to the live first-D
                # columns, combine — the slot columns ride the same
                # (coalesced, cheap) gather; see sparse_apply_packed.
                rows = jnp.take(
                    jax.lax.stop_gradient(table), ragged.ids, axis=0
                )[..., :spec.dim]
                embs[spec.name] = combine(
                    rows, ragged.weights, spec.combiner
                )
            elif spec.name in sharded_tables:
                embs[spec.name] = lookup_combine_sharded(
                    jax.lax.stop_gradient(table), ragged.ids,
                    ragged.weights, spec.combiner, mesh, axis,
                    interpret=interpret,
                    force_pallas=(use_pallas == "always"),
                    force_xla=(use_pallas == "never"),
                )
            else:
                embs[spec.name] = lookup_combine(
                    jax.lax.stop_gradient(table), ragged.ids,
                    ragged.weights, spec.combiner,
                    interpret=interpret,
                    force_pallas=(use_pallas == "always"),
                    force_xla=(use_pallas == "never"),
                )
            uids, inverse = _unique_pad_jit(
                jnp.ravel(ragged.ids), spec.vocab
            )
            lookups[spec.name] = (ragged, uids, inverse)

        def compute_loss(params, embs):
            variables = {
                "params": params,
                SPARSE_EMB_COLLECTION: _nest_rows(template, embs),
            }
            preds = state.apply_fn(
                variables, batch["features"], training=True,
                rngs={"dropout": rng} if rng is not None else None,
                mutable=False,
            )
            return _call_loss(
                loss_fn, batch["labels"], preds, batch["mask"]
            )

        grad_fn = jax.value_and_grad(compute_loss, argnums=(0, 1))
        loss, (param_grads, emb_grads) = grad_fn(state.params, embs)

        new_tables = dict(state.tables)
        new_slots = dict(state.slot_tables)
        new_steps = dict(state.table_steps)
        for spec in specs:
            ragged, uids, inverse = lookups[spec.name]
            rows_ct = _row_grads(
                emb_grads[spec.name], uids, inverse, ragged,
                spec.combiner,
            )
            step_count = state.table_steps[spec.name] + 1
            if packed_slots:
                table = sparse_apply_packed(
                    row_opt, state.tables[spec.name], uids, rows_ct,
                    step_count, spec.dim,
                )
                slots = state.slot_tables[spec.name]  # {} — in-row
            elif spec.name in sharded_tables:
                table, slots = sparse_apply_sharded(
                    row_opt, state.tables[spec.name],
                    state.slot_tables[spec.name], uids, rows_ct,
                    step_count, mesh, axis, use_pallas=use_pallas,
                    interpret=interpret,
                )
            else:
                table, slots = sparse_apply(
                    row_opt, state.tables[spec.name],
                    state.slot_tables[spec.name], uids, rows_ct,
                    step=step_count, use_pallas=use_pallas,
                    interpret=interpret,
                )
            new_tables[spec.name] = table
            new_slots[spec.name] = slots
            new_steps[spec.name] = step_count

        state = state.apply_gradients(
            grads=param_grads, tables=new_tables,
            slot_tables=new_slots, table_steps=new_steps,
        )
        return state, {"loss": loss}

    return train_step


def build_sparse_multi_step(loss_fn, specs, row_opt, template,
                            use_pallas: str = "auto",
                            interpret: bool = False,
                            unroll: int = 1,
                            mesh=None, axis: str = "dp",
                            sharded_tables: FrozenSet[str] = frozenset(),
                            state_shardings=None,
                            packed_slots: bool = False) -> Callable:
    """T fused sparse steps per XLA program (the task-granular mode —
    core/step.build_multi_step for the sparse plane)."""
    step = build_sparse_train_step(
        loss_fn, specs, row_opt, template, use_pallas=use_pallas,
        interpret=interpret, mesh=mesh, axis=axis,
        sharded_tables=sharded_tables, packed_slots=packed_slots,
    )

    def multi_step(state, batches):
        def body(state, batch):
            return step(state, batch)

        num_steps = jax.tree.leaves(batches)[0].shape[0]
        return jax.lax.scan(
            body, state, batches, unroll=max(1, min(unroll, num_steps))
        )

    kwargs = {}
    if state_shardings is not None:
        kwargs = dict(
            in_shardings=(state_shardings, None),
            out_shardings=(state_shardings, None),
        )
    return jax.jit(multi_step, donate_argnums=(0,), **kwargs)


def init_sparse_state(
    model, tx, example_batch, specs: Tuple[TableSpec, ...],
    row_opt: RowOptimizer, seed: int = 0,
    table_dtype=jnp.float32, packed_slots: bool = False,
) -> Tuple[SparseTrainState, Any]:
    """Trace the model (zero embeddings in the collection), attach
    deterministic tables + zero slots; returns ``(state, template)``
    where template is the model's sparse_emb collection structure
    (pass to ``build_sparse_train_step``). Table init is seeded
    uniform, so elastic relaunches reproduce. With ``packed_slots``
    each table leaf is the (V, D*(1+n_slots)) packed store (identical
    main-table values — slots concatenate onto the same seeded init)
    and ``slot_tables`` entries are empty."""
    from elasticdl_tpu.embedding.host_engine import _iter_leaves

    rng = jax.random.PRNGKey(seed)
    variables = model.init(
        {"params": rng, "dropout": rng}, example_batch["features"],
        training=False,
    )
    template = variables.get(SPARSE_EMB_COLLECTION, {})
    names = [k for k, _ in _iter_leaves(template)]
    missing = {s.name for s in specs} - set(names)
    if missing:
        raise ValueError(
            f"model declares no SparseEmbed for tables {missing}"
        )

    tables = {}
    slot_tables = {}
    table_steps = {}
    for i, spec in enumerate(specs):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), i)
        scale = 1.0 / np.sqrt(spec.dim)
        main = jax.random.uniform(
            key, (spec.vocab, spec.dim), table_dtype, -scale, scale
        )
        slots = init_slot_tables(
            row_opt, spec.vocab, spec.dim, table_dtype
        )
        if packed_slots:
            tables[spec.name] = pack_table(main, slots, row_opt)
            slot_tables[spec.name] = {}
        else:
            tables[spec.name] = main
            slot_tables[spec.name] = slots
        table_steps[spec.name] = jnp.zeros((), jnp.int32)

    state = SparseTrainState(
        step=jnp.zeros((), jnp.int32),
        apply_fn=model.apply,
        params=variables["params"],
        batch_stats={},
        tx=tx,
        opt_state=tx.init(variables["params"]),
        rng=jax.random.PRNGKey(seed),
        tables=tables,
        slot_tables=slot_tables,
        table_steps=table_steps,
    )
    return state, template


class DeviceSparseRunner:
    """Worker-compatible step runner (init_state/train_step/eval_step +
    train_multi_step) for device-tier sparse models — the deployment
    adapter the host tier has in HostStepRunner.

    With ``mesh``, every TableSpec table over ``partition_threshold_bytes``
    whose vocab divides the ``axis`` size is ROW-SHARDED over the mesh
    (+its slot tables, co-sharded — reference slot co-location,
    ``ps/parameters.py:156``); the batch shards over the same ``axis``
    (data parallel), dense params replicate, and the step is jitted with
    explicit in/out shardings. This is the multi-chip form of the
    reference's N-parameter-server sparse plane
    (``docs/designs/parameter_server.md`` "Model Parameter Partition"):
    row ranges instead of id%N, XLA collectives over ICI instead of
    gRPC pull/push."""

    def __init__(self, specs: Tuple[TableSpec, ...],
                 row_opt: RowOptimizer, use_pallas: str = "auto",
                 interpret: Optional[bool] = None,
                 mesh=None, axis: str = "dp",
                 partition_threshold_bytes: int =
                 DEFAULT_PARTITION_THRESHOLD_BYTES,
                 packed_slots: bool = False):
        # packed_slots: slots live inside the table rows so the apply
        # is one gather + one scatter (optimizer.sparse_apply_packed —
        # the measured single-chip scatter-latency win). Single-mesh
        # only; checkpoints are layout-specific (a packed checkpoint
        # does not restore into a split-table runner or vice versa —
        # same class of opt-in as resnet50's s2d stem).
        if packed_slots and mesh is not None:
            raise ValueError(
                "packed_slots is single-mesh only (row-sharded tables "
                "keep the split layout)"
            )
        if packed_slots and use_pallas in ("always", "fused"):
            raise ValueError(
                "packed_slots uses the XLA gather/scatter path; "
                f"use_pallas={use_pallas!r} pins split-table kernels"
            )
        self.packed_slots = bool(packed_slots)
        self.specs = tuple(specs)
        self.row_opt = row_opt
        self.use_pallas = use_pallas
        # interpret=None: auto — real kernels on TPU, interpreter off
        # TPU (CPU tests) only when a kernel path is forced.
        if interpret is None:
            interpret = (
                use_pallas in ("always", "fused")
                and jax.default_backend() != "tpu"
            )
        self.interpret = interpret
        self.mesh = mesh
        self.axis = axis
        self.partition_threshold_bytes = int(partition_threshold_bytes)
        self.sharded_tables = self._sharded_tables_for(mesh)
        self._template = None
        self._state_shardings = None
        self._batch_shardings = None
        self._abstract_batch = None

    def _sharded_tables_for(self, mesh) -> frozenset:
        """Which tables row-shard on ``mesh``: vocab divides the axis
        and the table clears the size threshold. Re-derived on resize —
        a table that divided dp4 may not divide dp3."""
        if mesh is None:
            return frozenset()
        n = mesh.shape[self.axis]
        return frozenset(
            s.name for s in self.specs
            if s.vocab % n == 0
            and s.vocab * s.dim * 4 > self.partition_threshold_bytes
        )

    def _table_sharding(self, name):
        spec = P(self.axis, None) if name in self.sharded_tables else P()
        return NamedSharding(self.mesh, spec)

    def state_shardings(self, state):
        """Pytree of NamedShardings for a (possibly abstract)
        SparseTrainState: sharded tables/slots on P(axis, None),
        everything else replicated."""
        rep = NamedSharding(self.mesh, P())
        sh = jax.tree.map(lambda _: rep, state)
        return sh.replace(
            tables={
                k: self._table_sharding(k) for k in state.tables
            },
            slot_tables={
                k: jax.tree.map(
                    lambda _, s=self._table_sharding(k): s, v
                )
                for k, v in state.slot_tables.items()
            },
        )

    def init_state(self, model, tx, batch, seed: int = 0):
        if self.mesh is None:
            state, self._template = init_sparse_state(
                model, tx, batch, self.specs, self.row_opt, seed=seed,
                packed_slots=self.packed_slots,
            )
            return state

        # Build under jit with explicit out_shardings so a table sized
        # for the whole mesh never materializes on one device
        # (MeshRunner.init_state's pattern).
        def make_state():
            state, template = init_sparse_state(
                model, tx, batch, self.specs, self.row_opt, seed=seed
            )
            return state, template

        abstract_state, abstract_template = jax.eval_shape(make_state)
        shardings = self.state_shardings(abstract_state)
        self._state_shardings = shardings
        rep = NamedSharding(self.mesh, P())
        state, template = jax.jit(
            make_state,
            out_shardings=(
                shardings,
                jax.tree.map(lambda _: rep, abstract_template),
            ),
        )()
        self._template = template
        self._batch_shardings = self._batch_shardings_for(batch)
        # Shape-only copy of the example batch so resize() can rebuild
        # the batch shardings against the new mesh.
        self._abstract_batch = jax.eval_shape(lambda b: b, batch)
        return state

    def _batch_shardings_for(self, batch):
        return jax.tree.map(
            lambda leaf: NamedSharding(
                self.mesh,
                P(self.axis) if np.ndim(leaf) >= 1 else P(),
            ),
            batch,
        )

    def place_state(self, state):
        """Re-place restored host arrays with the runner's shardings
        (checkpoint restore would otherwise land a mesh-sized table on
        one device) — MeshRunner.place_state's contract."""
        if self.mesh is None:
            return state
        shardings = self._state_shardings or self.state_shardings(state)
        return jax.device_put(state, shardings)

    def resize(self, new_mesh, state=None):
        """Checkpointless live reshard onto ``new_mesh``
        (MeshRunner.resize's contract, sparse edition): every
        row-sharded table's per-device row range changes — dp4 → dp2
        doubles each shard — and the co-sharded slot tables move with
        it, with no disk round trip. Compiled steps baked the old
        shardings and must be rebuilt by the caller."""
        from elasticdl_tpu.parallel import reshard as reshard_lib

        self.mesh = new_mesh
        self.sharded_tables = self._sharded_tables_for(new_mesh)
        self._state_shardings = None
        if self._abstract_batch is not None:
            self._batch_shardings = self._batch_shardings_for(
                self._abstract_batch
            )
        if state is None:
            return None

        def shardings_fn(abstract):
            self._state_shardings = self.state_shardings(abstract)
            return self._state_shardings

        return reshard_lib.live_reshard(state, shardings_fn)

    def _jit_step(self, step):
        if self.mesh is None:
            return jax.jit(step, donate_argnums=(0,))
        return jax.jit(
            step, donate_argnums=(0,),
            in_shardings=(self._state_shardings,
                          self._batch_shardings),
            out_shardings=(self._state_shardings, None),
        )

    def train_step(self, loss_fn):
        step = build_sparse_train_step(
            loss_fn, self.specs, self.row_opt, self._template,
            use_pallas=self.use_pallas, interpret=self.interpret,
            mesh=self.mesh, axis=self.axis,
            sharded_tables=self.sharded_tables,
            packed_slots=self.packed_slots,
        )
        return self._jit_step(step)

    def train_multi_step(self, loss_fn):
        return build_sparse_multi_step(
            loss_fn, self.specs, self.row_opt, self._template,
            use_pallas=self.use_pallas, interpret=self.interpret,
            mesh=self.mesh, axis=self.axis,
            sharded_tables=self.sharded_tables,
            state_shardings=self._state_shardings,
            packed_slots=self.packed_slots,
        )

    def eval_step(self):
        from elasticdl_tpu.embedding.host_engine import _nest_rows
        from elasticdl_tpu.ops.pallas_embedding import (
            lookup_combine,
            lookup_combine_sharded,
        )

        specs = self.specs
        template = self._template

        def step(state, batch):
            embs = {}
            for spec in specs:
                ragged = _ragged(batch["features"][spec.feature_key])
                if self.packed_slots:
                    rows = jnp.take(
                        state.tables[spec.name], ragged.ids, axis=0
                    )[..., :spec.dim]
                    embs[spec.name] = combine(
                        rows, ragged.weights, spec.combiner
                    )
                elif spec.name in self.sharded_tables:
                    embs[spec.name] = lookup_combine_sharded(
                        state.tables[spec.name], ragged.ids,
                        ragged.weights, spec.combiner, self.mesh,
                        self.axis, interpret=self.interpret,
                        force_pallas=(self.use_pallas == "always"),
                        force_xla=(self.use_pallas == "never"),
                    )
                else:
                    embs[spec.name] = lookup_combine(
                        state.tables[spec.name], ragged.ids,
                        ragged.weights, spec.combiner,
                        interpret=self.interpret,
                        force_pallas=(self.use_pallas == "always"),
                        force_xla=(self.use_pallas == "never"),
                    )
            variables = {
                "params": state.params,
                SPARSE_EMB_COLLECTION: _nest_rows(template, embs),
            }
            return state.apply_fn(
                variables, batch["features"], training=False,
                mutable=False,
            )

        return jax.jit(step)
