"""Host-tier embedding training engine (>HBM tables, end to end).

SURVEY.md §7 stage 6 "hard part #2": dynamic-shape id batches vs XLA
static shapes. The reference trains huge tables by keeping rows on
parameter-server pods and shipping row batches over gRPC
(``worker/worker.py:362-391`` pull, ``:570-580`` scatter,
``ps/optimizer_wrapper.py:143`` lookup-apply-writeback). Here the same
capability is mesh-native:

- the table lives in host RAM (`EmbeddingTable` or the C++
  `NativeEmbeddingTable` via `make_host_table`),
- per batch, ids are deduplicated host-side and their rows pulled into a
  device array whose leading dim is **bucket-padded** (next power of two)
  so the jit step compiles once per bucket, not once per batch,
- the model reads those rows through the ``host_rows`` flax collection
  (`HostEmbedding` layer) and indexes them with the batch's inverse map,
- the step function differentiates w.r.t. the row block; the engine
  scatters the row gradients back through a row optimizer
  (`HostOptimizerWrapper` / native); slot tables and step counters ride
  the checkpoint via `HostStepRunner.host_tables`,
- `prepared_batches` double-buffers for engine-driven loops: rows for
  batch N+1 are pulled on a background thread while batch N trains.
  (`HostStepRunner` — the Worker adapter — prepares synchronously
  inside each step, since the worker hands it one batch at a time.)

Scope: one engine = one process's tables. In-process multi-worker jobs
share a single runner (engine lock serializes host access); multi-
PROCESS jobs share rows through `embedding/row_service.py` — the
Pserver sparse role over RPC (`--row_service_addr`).
"""

import queue
import threading
import time
import weakref
from typing import Callable, Dict, Iterable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from elasticdl_tpu.embedding.combiner import RaggedIds, combine


class PreparedBatch(NamedTuple):
    """A batch whose host half is already done (rows pulled, ids
    inverse-mapped): what ``HostStepRunner.iter_prepared`` yields so
    pulls for batch N+1 can run while batch N's device step executes.

    ``device_rows``/``device_batch`` are filled by the pipeline's
    device-placement stage (``prepared_batches(place_rows=True)``):
    the row blocks and batch already ``jax.device_put`` while the
    previous batch steps, so the jit call consumes resident buffers
    instead of paying the host→device copy on the critical path. None
    (the default) means the step transfers them itself."""

    raw: dict       # the original batch (multihost dummies, init)
    batch: dict     # features with inverse maps substituted
    host_rows: dict
    uniques: dict
    device_rows: Optional[dict] = None
    device_batch: Optional[dict] = None

MIN_BUCKET = 8

# Collection name through which the engine hands the per-batch row block
# to the model.
HOST_ROWS_COLLECTION = "host_rows"


def bucket_size(n: int, min_bucket: int = MIN_BUCKET) -> int:
    """Next power of two >= n (>= min_bucket): bounds the number of
    distinct compiled shapes to O(log vocab) per table."""
    b = min_bucket
    while b < n:
        b *= 2
    return b


class HostEmbedding(nn.Module):
    """Embedding lookup over the engine-provided per-batch row block.

    Input is the batch's **inverse map** (positions -> slots in the row
    block), produced by ``HostEmbeddingEngine.prepare_batch`` — not raw
    ids. Supports the same dense / RaggedIds+combiner forms as the
    in-HBM `Embedding` layer.
    """

    table_name: str
    output_dim: int
    combiner: Optional[str] = None

    @nn.compact
    def __call__(self, inverse):
        rows = self.variable(
            HOST_ROWS_COLLECTION,
            self.table_name,
            lambda: jnp.zeros((MIN_BUCKET, self.output_dim), jnp.float32),
        ).value
        if isinstance(inverse, RaggedIds):
            if self.combiner is None:
                raise ValueError("RaggedIds input requires a combiner")
            emb = jnp.take(rows, inverse.ids, axis=0)
            return combine(emb, inverse.weights, self.combiner)
        return jnp.take(rows, jnp.asarray(inverse), axis=0)


def host_rows_template(model, example_batch, seed: int = 0):
    """The model's ``host_rows`` collection structure (nested by module
    path, as flax scopes it). The engine speaks flat {table: rows}; the
    step nests/flattens against this template. Table names must be
    unique across the model."""
    variables = model.init(
        {"params": jax.random.PRNGKey(seed)},
        example_batch["features"], training=False,
    )
    template = variables.get(HOST_ROWS_COLLECTION, {})
    names = [k for k, _ in _iter_leaves(template)]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(
            f"host table names must be unique across the model: {dupes}"
        )
    return template


def _iter_leaves(node, out=None):
    out = [] if out is None else out
    for key, value in node.items():
        if isinstance(value, dict):
            _iter_leaves(value, out)
        else:
            out.append((key, value))
    return out


def _nest_rows(template, flat):
    """Flat {table: rows} -> the template's nested module-path shape."""
    return {
        key: (_nest_rows(value, flat) if isinstance(value, dict)
              else flat[key])
        for key, value in template.items()
    }


def build_host_train_step(loss_fn: Callable, rows_template) -> Callable:
    """Build ``(state, batch, host_rows) -> (state, row_grads, metrics)``.

    Same contract as core/step.build_train_step plus the host row block:
    ``host_rows`` (flat {table: (bucket, dim)}) enters as a
    differentiated argument; its gradients come back (flat) for the
    engine to scatter into the host store. ``rows_template`` comes from
    ``host_rows_template``. BatchNorm models are supported the same way
    as the core step (running stats frozen on padded batches).
    """
    from elasticdl_tpu.core.step import _call_loss

    def train_step(state, batch, host_rows):
        state, rng = state.next_rng()

        def compute_loss(params, host_rows):
            variables = {
                "params": params,
                HOST_ROWS_COLLECTION: _nest_rows(rows_template, host_rows),
            }
            has_batch_stats = bool(state.batch_stats)
            if has_batch_stats:
                variables["batch_stats"] = state.batch_stats
            mutable = ["batch_stats"] if has_batch_stats else False
            out = state.apply_fn(
                variables,
                batch["features"],
                training=True,
                rngs={"dropout": rng} if rng is not None else None,
                mutable=mutable,
            )
            if mutable:
                preds, updates = out
                new_stats = updates.get("batch_stats", state.batch_stats)
            else:
                preds, new_stats = out, state.batch_stats
            loss = _call_loss(loss_fn, batch["labels"], preds, batch["mask"])
            return loss, new_stats

        grad_fn = jax.value_and_grad(compute_loss, argnums=(0, 1),
                                     has_aux=True)
        (loss, new_stats), (param_grads, row_grads) = grad_fn(
            state.params, host_rows
        )
        if state.batch_stats:
            is_full = jnp.all(batch["mask"] > 0)
            new_stats = jax.tree.map(
                lambda new, old: jnp.where(is_full, new, old),
                new_stats, state.batch_stats,
            )
        state = state.apply_gradients(
            grads=param_grads, batch_stats=new_stats
        )
        return state, row_grads, {"loss": loss}

    return jax.jit(train_step, donate_argnums=(0,))


def build_host_eval_step(rows_template) -> Callable:
    """Build ``(state, batch, host_rows) -> predictions`` (host-tier
    counterpart of core/step.build_eval_step)."""

    def eval_step(state, batch, host_rows):
        variables = {
            "params": state.params,
            HOST_ROWS_COLLECTION: _nest_rows(rows_template, host_rows),
        }
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        return state.apply_fn(
            variables, batch["features"], training=False, mutable=False
        )

    return jax.jit(eval_step)


class HostEmbeddingEngine:
    """Pull/dedup/pad rows per batch; scatter row grads back after.

    ``tables``:   {name: EmbeddingTable-like} (host or native),
    ``optimizer``: a HostOptimizerWrapper-compatible object
                  (``apply_gradients(table, ids, grads)``),
    ``id_keys``:  {table_name: feature_key} — which feature carries the
                  raw ids for each table; prepare_batch replaces it with
                  the inverse map.
    """

    def __init__(self, tables: Dict, optimizer, id_keys: Dict[str, str],
                 metrics_registry=None, table_fanout: bool = True):
        # Serializes host-side table access: in-process multi-worker
        # jobs share ONE engine (threads), and neither the dict table
        # nor the C++ open-addressing row map (which rehashes on
        # growth) is safe under concurrent mutation. The device step
        # itself still runs outside the lock.
        #
        # Stores that are safe under concurrent IO — the RPC row
        # service, whose server serializes internally (the reference Go
        # PS served pulls concurrently with pushes by design,
        # ps/server.go) — declare ``concurrent_safe = True``; pulls and
        # pushes then skip the lock so a prefetching pull can be in
        # flight while the applier pushes the previous step's grads.
        self.lock = threading.RLock()
        self.concurrent_io = (
            all(getattr(t, "concurrent_safe", False)
                for t in tables.values())
            and getattr(optimizer, "concurrent_safe", False)
        )
        unknown = set(id_keys) - set(tables)
        if unknown:
            raise ValueError(f"id_keys reference unknown tables {unknown}")
        keys = list(id_keys.values())
        dupes = {k for k in keys if keys.count(k) > 1}
        if dupes:
            # Two tables sharing one feature would see the first table's
            # inverse map as the second's raw ids — silent corruption.
            raise ValueError(
                f"feature keys must be unique across tables: {dupes}"
            )
        self.tables = tables
        self.optimizer = optimizer
        self.id_keys = id_keys
        # table_fanout=False pins the serial per-table loop — the
        # pre-fan-out shape (benchmark baseline; also an escape hatch
        # if a store misdeclares concurrent_safe).
        self.table_fanout = bool(table_fanout)
        # Per-TABLE fan-out pool (lazy; only built for multi-table
        # engines over concurrent-safe stores): prepare_batch pulls and
        # apply_row_grads pushes fan out per table, so a DeepFM-style
        # batch pays max(table pull/push), not sum. Sized for one wave
        # of pulls AND one wave of pushes concurrently (the prefetch
        # thread prepares batch N+1 while the applier pushes batch N's
        # grads). This pool is DISTINCT from the sharded-client pool in
        # row_service.py on purpose — a table-level task there would
        # occupy a worker while waiting on its own shard sub-tasks
        # (nested submission deadlocks a shared bounded pool).
        self._table_pool = None
        self._table_pool_lock = threading.Lock()
        # Telemetry: lookup/update latency, row traffic, and the dedup
        # ("cache hit") ratio — total vs unique ids per batch. Rows
        # materialized is a pull-time gauge over the live tables.
        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self._m_lookup = registry.histogram(
            "embedding_lookup_seconds",
            "Host row pull + dedup + pad latency per batch",
        )
        # Phase split of the lookup monolith (matching dedup/row_pull/
        # pad child spans are emitted inside prepare_batch): the
        # critical-path report and dashboards can attribute INSIDE
        # prepare — "lookup is slow" becomes "the pull RPC is slow" or
        # "dedup is slow", which point at different fixes.
        self._m_dedup = registry.histogram(
            "embedding_dedup_seconds",
            "np.unique dedup latency per table per batch",
        )
        self._m_pull = registry.histogram(
            "embedding_row_pull_seconds",
            "Row fetch (store get / pull RPC) latency per table per "
            "batch",
        )
        self._m_pad = registry.histogram(
            "embedding_pad_seconds",
            "Bucket-pad + inverse-map assembly latency per table per "
            "batch",
        )
        self._m_device_put = registry.histogram(
            "embedding_device_put_seconds",
            "Device placement latency per prepared batch (the "
            "pipeline's jax.device_put stage)",
        )
        self._m_update = registry.histogram(
            "embedding_update_seconds",
            "Row-gradient scatter/apply latency per step",
        )
        self._m_ids = registry.counter(
            "embedding_lookup_ids_total",
            "Raw ids looked up (pre-dedup)",
        )
        self._m_unique = registry.counter(
            "embedding_lookup_unique_ids_total",
            "Unique rows actually pulled (1 - unique/raw = batch dedup "
            "hit rate)",
        )
        self._m_rows_updated = registry.counter(
            "embedding_rows_updated_total",
            "Rows receiving gradient updates",
        )
        # weakref: the registry is process-global and outlives engines;
        # a strong closure over self would pin the (larger-than-HBM)
        # host tables of every discarded engine for the process life.
        self_ref = weakref.ref(self)

        def _rows_materialized() -> float:
            engine = self_ref()
            if engine is None:
                return 0.0
            return sum(
                t.num_rows for t in engine.tables.values()
                if hasattr(t, "num_rows")
            )

        registry.gauge(
            "embedding_rows_materialized",
            "Rows resident across host tables (lazy-init high-water)",
        ).set_function(_rows_materialized)

    def prepare_batch(self, batch: dict) -> Tuple[dict, dict, dict]:
        """Host-side half of the step (runs off-thread under
        ``prepared_batches``): dedup ids, pull rows, bucket-pad.

        Returns (batch', host_rows, uniques):
        - batch' — ``batch`` with each id feature replaced by its int32
          inverse map into the row block,
        - host_rows — {table: (bucket, dim) float32}; rows[u:] are zero
          padding whose grads are dropped,
        - uniques — {table: (unique_ids, u)} for apply_row_grads.

        Tracing: each table emits ``dedup`` / ``row_pull`` / ``pad``
        phase spans. Called under an open span (the synchronous path,
        where prepare runs inside ``device_step``) they become its
        direct children, so the critical-path step breakdown names the
        pull; called from a pipeline thread (no ambient span) they nest
        under a fresh ``prepare_batch`` root — the span the overlap
        checker (tools/check_overlap.py) matches against concurrent
        device steps.
        """
        from elasticdl_tpu.observability import tracing

        t0 = time.monotonic()
        try:
            ctx = tracing.current_ctx()
            if ctx is not None:
                if self.concurrent_io:
                    return self._prepare_batch_locked(batch, ctx)
                with self.lock:
                    return self._prepare_batch_locked(batch, ctx)
            with tracing.span(
                "prepare_batch", tables=len(self.id_keys)
            ) as sp:
                ctx = sp.ctx()
                if self.concurrent_io:
                    return self._prepare_batch_locked(batch, ctx)
                with self.lock:
                    return self._prepare_batch_locked(batch, ctx)
        finally:
            self._m_lookup.observe(time.monotonic() - t0)

    def _get_table_pool(self):
        from concurrent.futures import ThreadPoolExecutor

        with self._table_pool_lock:
            if self._table_pool is None:
                self._table_pool = ThreadPoolExecutor(
                    max_workers=min(2 * len(self.id_keys), 16),
                    thread_name_prefix="table-fanout",
                )
                # Discarded engines (chaos relaunches build one per
                # replacement worker) must not leak their pool threads
                # for the process life; close() is explicit, the
                # finalizer covers engines that are simply dropped.
                weakref.finalize(
                    self, self._table_pool.shutdown, wait=False
                )
            return self._table_pool

    def close(self):
        """Shut down the per-table fan-out pool (idempotent). Engines
        are also finalizer-cleaned on GC; call this when discarding an
        engine deterministically (worker teardown, tests)."""
        with self._table_pool_lock:
            pool, self._table_pool = self._table_pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    def _prepare_table(self, table_name, ids, ctx):
        """One table's prepare: dedup → pull → pad, phase-timed. Pure
        per-table work (no shared mutable state beyond the thread-safe
        metrics/tables), so the fan-out path runs it on pool threads."""
        from elasticdl_tpu.observability import tracing

        ragged = isinstance(ids, RaggedIds)
        raw = np.asarray(ids.ids if ragged else ids)
        t0 = time.monotonic()
        with tracing.child_span("dedup", ctx, table=table_name):
            uniq, inverse = np.unique(raw, return_inverse=True)
        t1 = time.monotonic()
        self._m_dedup.observe(t1 - t0)
        u = len(uniq)
        self._m_ids.inc(raw.size)
        self._m_unique.inc(u)
        table = self.tables[table_name]
        with tracing.child_span("row_pull", ctx, table=table_name,
                                rows=u):
            pulled = table.get(uniq)
        t2 = time.monotonic()
        self._m_pull.observe(t2 - t1)
        with tracing.child_span("pad", ctx, table=table_name):
            rows = np.zeros((bucket_size(u), table.dim), np.float32)
            rows[:u] = pulled
            inv = inverse.reshape(raw.shape).astype(np.int32)
        self._m_pad.observe(time.monotonic() - t2)
        feature = (
            RaggedIds(ids=inv, weights=ids.weights) if ragged else inv
        )
        return feature, rows, (uniq, u)

    def _prepare_batch_locked(self, batch, ctx=None):
        if not isinstance(batch["features"], dict):
            raise TypeError(
                "host-tier batches need dict features (id_keys names the "
                "feature carrying each table's ids); got "
                f"{type(batch['features']).__name__}"
            )
        features = dict(batch["features"])
        host_rows, uniques = {}, {}
        items = list(self.id_keys.items())
        if len(items) > 1 and self.concurrent_io and self.table_fanout:
            # Parallel per-table fan-out: a multi-table batch pays
            # max(table pull), not sum. Only over concurrent-safe
            # stores (the RPC row plane) — a locked local store would
            # serialize the futures on self.lock anyway, and this
            # method already holds it then.
            pool = self._get_table_pool()
            futures = [
                (name, key,
                 pool.submit(self._prepare_table, name, features[key],
                             ctx))
                for name, key in items
            ]
            for name, key, future in futures:
                feature, rows, uniq_u = future.result()
                features[key] = feature
                host_rows[name] = rows
                uniques[name] = uniq_u
        else:
            for name, key in items:
                feature, rows, uniq_u = self._prepare_table(
                    name, features[key], ctx
                )
                features[key] = feature
                host_rows[name] = rows
                uniques[name] = uniq_u
        out = dict(batch)
        out["features"] = features
        return out, host_rows, uniques

    def place_on_device(self, prepared: PreparedBatch) -> PreparedBatch:
        """The pipeline's device-placement stage: ``jax.device_put``
        the row blocks and the batch for an upcoming step while the
        current one executes, so the jit call consumes already-resident
        buffers (``device_rows``/``device_batch``)."""
        from elasticdl_tpu.observability import tracing

        t0 = time.monotonic()
        with tracing.span("device_put", tables=len(prepared.host_rows)):
            device_rows = jax.device_put(prepared.host_rows)
            device_batch = jax.device_put(prepared.batch)
        self._m_device_put.observe(time.monotonic() - t0)
        return prepared._replace(
            device_rows=device_rows, device_batch=device_batch
        )

    def apply_row_grads(self, row_grads: dict, uniques: dict) -> None:
        """Scatter the step's row gradients into the host tables
        (lookup-apply-writeback, reference optimizer_wrapper.py:143)."""
        t0 = time.monotonic()
        try:
            if self.concurrent_io:
                self._apply_row_grads_inner(row_grads, uniques)
                return
            with self.lock:
                self._apply_row_grads_inner(row_grads, uniques)
        finally:
            self._m_update.observe(time.monotonic() - t0)

    def _apply_row_grads_inner(self, row_grads, uniques):
        items = list(uniques.items())
        if len(items) > 1 and self.concurrent_io and self.table_fanout:
            # Same max-not-sum fan-out as prepare: tables are disjoint
            # row spaces, so cross-table applies commute; per-table
            # FIFO is preserved because the (single) applier joins one
            # batch's futures before starting the next batch's.
            pool = self._get_table_pool()
            futures = [
                pool.submit(self._apply_table, name, uniq, u,
                            row_grads[name])
                for name, (uniq, u) in items
            ]
            for f in futures:
                f.result()
        else:
            for name, (uniq, u) in items:
                self._apply_table(name, uniq, u, row_grads[name])

    def _apply_table(self, table_name, uniq, u, grads):
        grads = np.asarray(grads)[:u]
        self._m_rows_updated.inc(u)
        self.optimizer.apply_gradients(
            self.tables[table_name], uniq, grads
        )

    def prepared_batches(self, batches: Iterable[dict], depth: int = 2,
                         place_rows: bool = False):
        """Double-buffered iterator of ``PreparedBatch``: rows for
        upcoming batches are pulled while the current batch trains
        (data/prefetch.py plays the same role for record decode).
        ``place_rows`` adds the device-placement stage: a second
        pipeline thread ``jax.device_put``s each prepared batch's row
        blocks (+batch) so the step consumes resident buffers.

        STALENESS WINDOW: a prefetched batch can read rows up to
        ``depth + 1`` apply_row_grads behind on ids it shares with
        in-flight batches — the reference async PS pull's
        relaxed-consistency window (async_sgd.md), widened by the
        prefetch depth. The device stage widens it by up to 2 more
        batches (its queue slot plus the transfer in flight): with
        ``place_rows`` the bound is ``depth + 3``. Shape unchanged —
        only the count of in-flight batches a shared id's pull may
        trail by.

        Returns a PrefetchIterator; ``close()`` it (or use as a context
        manager) when abandoning mid-stream — closing the last stage
        tears down the whole chain. (``HostStepRunner.iter_prepared``
        is a thin delegate — ONE pull-ahead implementation.)"""
        from elasticdl_tpu.data.prefetch import prefetch, staged

        prepared = prefetch(
            (PreparedBatch(b, *self.prepare_batch(b)) for b in batches),
            depth=depth,
        )
        if not place_rows:
            return prepared
        return staged(prepared, self.place_on_device, depth=1)


class HostStepRunner:
    """Step-runner adapter: drive host-tier models through the standard
    Worker/MiniCluster loop (worker.py accepts any runner exposing
    init_state/train_step/eval_step). prepare/apply happen inside the
    wrapped step so the worker's (state, batch) contract is unchanged —
    the role the reference worker's PS stubs played inline
    (worker.py:869-908), collapsed into the runner.

    Overlap (VERDICT r2 #7 — the reference's Go PS served pulls
    concurrently with training by design):

    - **Async apply**: the step dispatches the device program and hands
      (row_grads, uniques) to a single background applier thread; the
      device->host grad transfer and the lookup-apply-writeback (an RPC
      round trip for row-service engines) leave the critical path.
      Writes stay FIFO (one thread); reads that must see them —
      checkpoints via ``host_tables``, eval, init — flush first. The
      relaxed window (a pull may be one unapplied step behind on shared
      ids) is the reference async-PS consistency model (async_sgd.md).
    - **Pull-ahead**: ``iter_prepared`` wraps a batch stream so rows
      for upcoming batches are pulled on a prefetch thread while the
      current batch trains; the Worker task loop uses it when present.
    - **Device double-buffering**: a second pipeline stage
      ``jax.device_put``s batch N+1's row blocks while batch N steps,
      so the jit call consumes resident buffers (the host→device copy
      leaves the critical path too). Staleness-window math on
      ``prepared_batches``.
    """

    def __init__(self, engine: HostEmbeddingEngine,
                 async_apply: bool = True):
        self.engine = engine
        self._template = None
        self._model = None
        self._async_apply = async_apply
        self._apply_queue = None
        self._apply_thread = None
        self._apply_error = None

    # ---- async applier --------------------------------------------------

    def _applier_loop(self):
        while True:
            item = self._apply_queue.get()
            try:
                if item is None:
                    return
                row_grads, uniques = item
                try:
                    self.engine.apply_row_grads(
                        {k: np.asarray(v) for k, v in row_grads.items()},
                        uniques,
                    )
                except BaseException as exc:  # surfaced on next step/flush
                    self._apply_error = exc
            finally:
                self._apply_queue.task_done()

    def _enqueue_apply(self, row_grads, uniques):
        if self._apply_thread is None:
            # Bounded depth 2: the applier can fall at most one step
            # behind before the trainer blocks — keeps the staleness
            # window at the documented one step.
            self._apply_queue = queue.Queue(maxsize=2)
            self._apply_thread = threading.Thread(
                target=self._applier_loop, daemon=True,
                name="host-row-applier",
            )
            self._apply_thread.start()
        self._raise_pending()
        self._apply_queue.put((row_grads, uniques))

    def _raise_pending(self):
        if self._apply_error is not None:
            exc, self._apply_error = self._apply_error, None
            raise exc

    def flush(self):
        """Wait for every enqueued row apply to land (checkpoint/eval/
        init read barriers); re-raises applier failures."""
        if self._apply_queue is not None:
            self._apply_queue.join()
        self._raise_pending()

    @property
    def pull_ahead(self) -> bool:
        """Whether the Worker task loop should wrap batches in
        ``iter_prepared``: only under async apply — a synchronous
        runner (``async_apply=False``) promised exact semantics, and
        pull-ahead would reintroduce the stale-read window."""
        return self._async_apply

    def iter_prepared(self, batches: Iterable[dict], depth: int = 2,
                      place_rows: bool = True):
        """Pull-ahead iterator of ``PreparedBatch`` for the Worker task
        loop (delegates to the engine's prepared_batches — one
        implementation); ``close()`` it when abandoning mid-stream.
        ``depth`` is the pull-ahead queue (--host_prefetch_depth);
        ``place_rows`` (default on — this runner feeds a device step)
        adds the device double-buffering stage, widening the staleness
        window as documented on ``prepared_batches``."""
        return self.engine.prepared_batches(
            batches, depth=max(1, int(depth)), place_rows=place_rows
        )

    @property
    def host_tables(self) -> Dict:
        """Everything the checkpoint must carry: main tables PLUS the
        row optimizer's slot tables and per-table step counters (Adam
        bias correction must not restart at 1 after a relaunch). Pass
        to CheckpointHook(host_tables=...) / restore_from_dir. Views
        are lock-guarded so checkpoint snapshots don't race training
        threads sharing the engine. None for remote engines
        (embedding/row_service.py): the row SERVICE owns its rows'
        checkpointing, like the reference PS did."""
        if getattr(self.engine, "remote", False):
            return None
        return locked_checkpoint_tables(
            self.engine.tables, self.engine.optimizer, self.engine.lock,
            flush=self.flush,
        )

    def init_state(self, model, tx, batch, seed: int = 0):
        from elasticdl_tpu.core.train_state import init_train_state

        self.flush()
        prepared, _, _ = self.engine.prepare_batch(batch)
        self._template = host_rows_template(model, prepared, seed=seed)
        self._model = model
        return init_train_state(model, tx, prepared, seed=seed)

    def train_step(self, loss_fn: Callable) -> Callable:
        host_step = build_host_train_step(loss_fn, self._template)
        engine = self.engine

        def step(state, batch):
            if isinstance(batch, PreparedBatch):
                # Device-resident buffers when the pipeline's placement
                # stage ran: the jit call then pays no host→device copy.
                prepared = (
                    batch.device_batch if batch.device_batch is not None
                    else batch.batch
                )
                host_rows = (
                    batch.device_rows if batch.device_rows is not None
                    else batch.host_rows
                )
                uniques = batch.uniques
            else:
                prepared, host_rows, uniques = engine.prepare_batch(batch)
            state, row_grads, metrics = host_step(
                state, prepared, host_rows
            )
            if self._async_apply:
                # Device dispatch is async too: the applier thread
                # blocks on the grads transfer, not the caller.
                self._enqueue_apply(row_grads, uniques)
            else:
                engine.apply_row_grads(
                    {k: np.asarray(v) for k, v in row_grads.items()},
                    uniques,
                )
            return state, metrics

        return step

    def eval_step(self) -> Callable:
        host_eval = build_host_eval_step(self._template)
        engine = self.engine

        def step(state, batch):
            # Eval must see every trained row: drain pending applies.
            self.flush()
            if isinstance(batch, PreparedBatch):
                # A pull-ahead batch was prepared BEFORE the flush just
                # above — its row block may predate applies that were
                # still queued at pull time. Re-pull from the raw batch
                # so eval reads post-flush rows (eval bypasses
                # pull-ahead; exactness over overlap here).
                batch = batch.raw
            prepared, host_rows, _ = engine.prepare_batch(batch)
            return host_eval(state, prepared, host_rows)

        return step


def locked_checkpoint_tables(tables: Dict, optimizer, lock,
                             flush=None) -> Dict:
    """Everything a host-tier checkpoint must carry — main tables plus
    the optimizer's slot tables and step counters — each behind a
    lock-guarded view. Shared by HostStepRunner and HostRowService so
    the local and served checkpoint payloads cannot drift. ``flush``
    (the runner's async-apply drain) runs before any read so a snapshot
    never misses an in-flight row apply."""
    out = dict(tables)
    state_tables = getattr(optimizer, "state_tables", None)
    if state_tables is not None:
        out.update(state_tables(tables))
    return {
        name: _LockedTable(table, lock, flush)
        for name, table in out.items()
    }


class _LockedTable:
    """Lock-guarded view over a host table (or checkpoint adapter): the
    checkpoint hook snapshots and restore refills under the engine's
    lock, never racing training threads; reads drain the async applier
    first (``flush``). Dirty-row tracking (incremental checkpoints)
    passes through under the same lock when the wrapped table supports
    it."""

    def __init__(self, table, lock, flush=None):
        self._table = table
        self._lock = lock
        self._flush = flush

    def _drain(self):
        if self._flush is not None:
            self._flush()

    def to_arrays(self):
        self._drain()
        with self._lock:
            return self._table.to_arrays()

    @property
    def supports_dirty_rows(self) -> bool:
        return bool(getattr(self._table, "supports_dirty_rows", False))

    def dirty_arrays(self):
        self._drain()
        with self._lock:
            return self._table.dirty_arrays()

    def capture_arrays(self):
        """Full snapshot + dirty-drain under ONE lock acquisition
        (full-base capture): splitting them lets a write land between
        the two, excluded from the snapshot with its dirty mark
        wiped — the row would never ride any subsequent delta."""
        self._drain()
        with self._lock:
            ids, rows = self._table.to_arrays()
            if getattr(self._table, "supports_dirty_rows", False):
                self._table.clear_dirty()
            return ids, rows

    def mark_dirty(self, ids):
        with self._lock:
            self._table.mark_dirty(ids)

    def clear_dirty(self):
        with self._lock:
            self._table.clear_dirty()

    @property
    def dirty_count(self) -> int:
        with self._lock:
            return self._table.dirty_count

    def set(self, ids, values):
        self._drain()
        with self._lock:
            return self._table.set(ids, values)

    def get(self, ids):
        self._drain()
        with self._lock:
            return self._table.get(ids)

    @property
    def num_rows(self):
        self._drain()
        with self._lock:
            return self._table.num_rows

    def __getattr__(self, name):
        return getattr(self._table, name)
