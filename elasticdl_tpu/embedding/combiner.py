"""Ragged id batches and combiner reductions.

Counterpart of the reference's SparseTensor inputs + combiner handling
(``elasticdl/python/elasticdl/embedding_delegate.py:95-217``,
``safe_embedding_lookup_sparse`` re-implementation). XLA needs static
shapes, so a ragged batch of ids is stored padded to ``(batch, max_ids)``
with per-slot weights; weight 0 marks padding. Empty rows combine to the
zero vector (the reference's ``safe_`` default-row behavior).
"""

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np
from flax import struct

COMBINERS = ("sum", "mean", "sqrtn")


class RaggedIds(struct.PyTreeNode):
    """A padded ragged batch of embedding ids.

    ``ids``:     (batch, max_ids) int32, padded with 0,
    ``weights``: (batch, max_ids) float32, 0.0 on padded slots. For unweighted
    sparse input the weights are 1.0 on real slots (reference treats missing
    weights as 1, embedding_delegate.py:116-120).
    """

    ids: jnp.ndarray
    weights: jnp.ndarray

    @classmethod
    def from_lists(
        cls,
        id_lists: Sequence[Sequence[int]],
        weight_lists: Optional[Sequence[Sequence[float]]] = None,
        max_ids: Optional[int] = None,
    ) -> "RaggedIds":
        """Pad a list-of-lists (host-side, numpy) into a RaggedIds batch."""
        batch = len(id_lists)
        width = max_ids
        if width is None:
            width = max((len(r) for r in id_lists), default=1) or 1
        ids = np.zeros((batch, width), np.int32)
        weights = np.zeros((batch, width), np.float32)
        for i, row in enumerate(id_lists):
            row = list(row)
            if len(row) > width:
                raise ValueError(
                    f"row {i} has {len(row)} ids > max_ids={width}; "
                    "raise max_ids (silent truncation would drop features)"
                )
            n = len(row)
            ids[i, :n] = row
            if weight_lists is not None:
                weights[i, :n] = list(weight_lists[i])[:n]
            else:
                weights[i, :n] = 1.0
        return cls(ids=ids, weights=weights)

    @property
    def batch_size(self):
        return self.ids.shape[0]


def combine(embeddings, weights, combiner: str):
    """Reduce per-slot embeddings ``(batch, max_ids, dim)`` with weights
    ``(batch, max_ids)`` to ``(batch, dim)``.

    sum   = Σ w·e
    mean  = Σ w·e / Σ w
    sqrtn = Σ w·e / sqrt(Σ w²)
    (reference combiner semantics, embedding_delegate.py:189-217). Empty
    rows (all weights 0) produce zeros instead of NaN.
    """
    if combiner not in COMBINERS:
        raise ValueError(
            f"combiner must be one of {COMBINERS}, got {combiner!r}"
        )
    w = weights[..., None]
    summed = jnp.sum(embeddings * w, axis=-2)
    if combiner == "sum":
        return summed
    if combiner == "mean":
        denom = jnp.sum(weights, axis=-1, keepdims=True)
    else:  # sqrtn
        denom = jnp.sqrt(jnp.sum(weights * weights, axis=-1, keepdims=True))
    return jnp.where(denom > 0, summed / jnp.where(denom > 0, denom, 1.0), 0.0)
