"""Shared row service: the host tier served over RPC.

The one parameter-server role the mesh cannot absorb: several *worker
processes* training one >HBM embedding table need a shared row plane.
The reference serves it with the Pserver gRPC service
(``pull_embedding_vectors`` / ``push_gradients``,
``elasticdl/proto/elasticdl.proto:137-145``; Go impl
``pkg/ps/server.go:149,162``). Here the same contract rides the
framework's msgpack RPC (comm/rpc.py):

- **Server** (`HostRowService`): owns the tables (Python or C++ row
  store) and the row optimizer; applies pushed gradients under a lock
  (async-PS semantics — concurrent workers interleave, reference
  async_sgd.md); exposes `host_tables` so the server-side process
  checkpoints rows + optimizer slots exactly like a local engine.
- **Client** (`make_remote_engine`): a `HostEmbeddingEngine` whose
  tables pull rows over RPC and whose "optimizer" pushes gradients
  back. `HostStepRunner` works unchanged on top; its `host_tables` is
  None (the server owns checkpointing).

Worker-side dedup/bucketing still applies: each pull moves only the
batch's unique rows, mirroring the reference worker's dedup before
push (worker.py:487-599).
"""

import threading
from typing import Dict, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.comm.rpc import RpcServer, RpcStub
from elasticdl_tpu.embedding.host_engine import HostEmbeddingEngine

logger = get_logger("row_service")

SERVICE_NAME = "RowService"


class HostRowService:
    """Server side of the shared host tier."""

    def __init__(self, tables: Dict, optimizer):
        self._tables = tables
        self._optimizer = optimizer
        self._lock = threading.RLock()
        self._server: Optional[RpcServer] = None

    # ---- RPC handlers --------------------------------------------------

    def handlers(self):
        return {
            "table_info": self._table_info,
            "pull_rows": self._pull_rows,
            "push_row_grads": self._push_row_grads,
        }

    def _table_info(self, request: dict) -> dict:
        return {
            "tables": {
                name: {"dim": int(table.dim)}
                for name, table in self._tables.items()
            }
        }

    def _pull_rows(self, request: dict) -> dict:
        table = self._tables[request["table"]]
        with self._lock:
            rows = table.get(np.asarray(request["ids"], np.int64))
        return {"rows": np.asarray(rows, np.float32)}

    def _push_row_grads(self, request: dict) -> dict:
        table = self._tables[request["table"]]
        with self._lock:
            self._optimizer.apply_gradients(
                table,
                np.asarray(request["ids"], np.int64),
                np.asarray(request["grads"], np.float32),
            )
        return {}

    # ---- lifecycle / checkpoint ---------------------------------------

    def start(self, addr: str = "localhost:0") -> "HostRowService":
        self._server = RpcServer(
            addr, {SERVICE_NAME: self.handlers()}
        ).start()
        logger.info("Row service on port %d", self._server.port)
        return self

    @property
    def port(self) -> int:
        return self._server.port

    def stop(self, grace: Optional[float] = None):
        if self._server is not None:
            self._server.stop(grace)

    @property
    def host_tables(self) -> Dict:
        """Rows + optimizer slots + step counters, lock-guarded — pass
        to CheckpointHook/restore_from_dir in the SERVER process (the
        reference checkpoints on the PS for the same reason,
        ps/servicer.py:242-257)."""
        from elasticdl_tpu.embedding.host_engine import (
            locked_checkpoint_tables,
        )

        return locked_checkpoint_tables(
            self._tables, self._optimizer, self._lock
        )


class _RemoteTable:
    """Table-like view pulling rows over RPC (get-only: writes happen
    server-side via the optimizer push)."""

    def __init__(self, stub: RpcStub, name: str, dim: int):
        self._stub = stub
        self.name = name
        self.dim = dim

    def get(self, ids) -> np.ndarray:
        resp = self._stub.call(
            "pull_rows", table=self.name,
            ids=np.asarray(ids, np.int64),
        )
        return np.asarray(resp["rows"], np.float32)


class _RemoteOptimizer:
    """Optimizer-like view pushing row grads over RPC; the server
    applies them (reference push_gradients semantics)."""

    def __init__(self, stub: RpcStub):
        self._stub = stub

    def apply_gradients(self, table, ids, grads):
        self._stub.call(
            "push_row_grads", table=table.name,
            ids=np.asarray(ids, np.int64),
            grads=np.asarray(grads, np.float32),
        )
        return table


def make_remote_engine(
    addr: str, id_keys: Dict[str, str]
) -> HostEmbeddingEngine:
    """Client-side engine over a running `HostRowService`. Table names
    and dims come from the service itself."""
    stub = RpcStub(addr, SERVICE_NAME)
    info = stub.call("table_info")["tables"]
    tables = {
        name: _RemoteTable(stub, name, meta["dim"])
        for name, meta in info.items()
    }
    engine = HostEmbeddingEngine(
        tables, _RemoteOptimizer(stub), id_keys=id_keys
    )
    engine.remote = True  # server owns checkpointing (see HostStepRunner)
    return engine
