"""Shared row service: the host tier served over RPC.

The one parameter-server role the mesh cannot absorb: several *worker
processes* training one >HBM embedding table need a shared row plane.
The reference serves it with the Pserver gRPC service
(``pull_embedding_vectors`` / ``push_gradients``,
``elasticdl/proto/elasticdl.proto:137-145``; Go impl
``pkg/ps/server.go:149,162``). Here the same contract rides the
framework's msgpack RPC (comm/rpc.py):

- **Server** (`HostRowService`): owns the tables (Python or C++ row
  store) and the row optimizer; applies pushed gradients under a lock
  (async-PS semantics — concurrent workers interleave, reference
  async_sgd.md); exposes `host_tables` so the server-side process
  checkpoints rows + optimizer slots exactly like a local engine.
- **Client** (`make_remote_engine`): a `HostEmbeddingEngine` whose
  tables pull rows over RPC and whose "optimizer" pushes gradients
  back. `HostStepRunner` works unchanged on top; its `host_tables` is
  None (the server owns checkpointing).

Worker-side dedup/bucketing still applies: each pull moves only the
batch's unique rows, mirroring the reference worker's dedup before
push (worker.py:487-599).

**Live resharding + hot-row replicas (PR 12, docs/sparse_path.md
"Live resharding & hot-row replication"):** placement is no longer a
frozen ``id % N`` — it is a versioned ``ShardMap``
(embedding/shard_map.py) the client routes through and the server
*enforces*: a pull/push for buckets a shard does not own returns a
retryable REDIRECT carrying the newer map. Row ranges move between
live shards through a generation-fenced migration (``migrate_out`` /
``begin_ingest``/``ingest_rows``: bulk copy in chunks — hot rows from
the arena, cold rows via the tiered store's segment reads, never
promoted through the hot budget — then touched-set catch-up deltas,
then a brief write fence until the authority flips the map version).
Power-law read skew is attacked with **hot-row read replicas**: shards
track per-id pull frequency, the authority designates replica shards
for the hot set, the home pushes async refreshes after applied pushes,
and ``_ShardedTable.get`` fans hot-id reads across home + replicas
while writes stay single-home. The authority (shard-map controller +
split/merge/replication policy) lives in ``master/row_reshard.py``.
"""

import itertools
import threading
import time
from collections import Counter
from typing import Callable, Dict, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.comm import deadline as wl_deadline
from elasticdl_tpu.comm import overload as wl_overload
from elasticdl_tpu.comm.rpc import (
    EXPIRED_DETAIL,
    InvalidRequest,
    RpcError,
    RpcServer,
    RpcStub,
    decorrelated_jitter,
)
from elasticdl_tpu.embedding.host_engine import HostEmbeddingEngine
from elasticdl_tpu.embedding.shard_map import (
    ClientShardMap,
    ShardMap,
    bucket_of,
)
from elasticdl_tpu.embedding.table import get_slot_table_name
from elasticdl_tpu.observability import tracing
from elasticdl_tpu.observability import principal as wl_principal
from elasticdl_tpu.observability import usage as wl_usage

logger = get_logger("row_service")

SERVICE_NAME = "RowService"
SEQS_TABLE_NAME = "__row_service_seqs__"

# Rows per migration chunk: bounds how long the service lock is held
# per read and how large each ingest RPC is.
MIGRATE_CHUNK_ROWS = 2048
# Catch-up rounds before the source fences writes to the moving range
# and ships the final delta.
MIGRATE_CATCHUP_ROUNDS = 4
# A write fence expires on its own if the cutover never arrives (the
# authority died mid-protocol and will re-run the whole migration):
# better to re-accept writes — the re-run re-copies them — than to
# reject the range forever. The TTL must comfortably exceed the
# WORST-CASE final-delta + cutover-distribution time (the authority's
# RideOutTransport retries span ~64s against a flaky shard): a fence
# lapsing mid-protocol would let a push apply on the source after the
# final delta shipped — silently lost at the cutover erase.
FENCE_TTL_SECS = 300.0
# Hot-id pull tracking: bounded per-table counter (lossy: on overflow
# the tail halves away), only maintained once a shard map is installed.
HOT_TRACK_MAX_IDS = 4096


# ---- chaos seam (chaos/reshard_drill.py installs) ----------------------
#
# mid_migrate(service, migration_id, view_name, chunk_ids) runs after
# each migrated chunk lands on the target; raising simulates the
# source dying mid-copy.

_mid_migrate_hook: Optional[Callable] = None


def set_reshard_chaos_hooks(mid_migrate: Optional[Callable] = None):
    global _mid_migrate_hook
    _mid_migrate_hook = mid_migrate


class DirectTransport:
    """In-process transport to a ``HostRowService`` (tests/drills):
    the same ``.call`` surface as ``RpcStub`` without a socket."""

    def __init__(self, service: "HostRowService"):
        self._handlers = service.handlers()

    def call(self, method: str, timeout=None, **fields):
        return self._handlers[method](fields) or {}


def _all_ids(table) -> np.ndarray:
    """Every materialized row id of a table-like, WITHOUT reading row
    bytes where the store can avoid it (tiered tables enumerate from
    membership sets; the fallback pays a full to_arrays)."""
    fn = getattr(table, "all_ids", None)
    if fn is not None:
        return np.asarray(fn(), np.int64)
    return np.asarray(table.to_arrays()[0], np.int64)


def _peek_rows(table, ids: np.ndarray) -> np.ndarray:
    """Read rows for EXISTING ids without promotion/recency side
    effects where the store supports it (tiered tables serve cold ids
    straight from segment reads — a migrated cold range must not churn
    through the hot budget)."""
    fn = getattr(table, "peek", None)
    rows = fn(ids) if fn is not None else table.get(ids)
    return np.asarray(rows, np.float32)


def _client_key(client: str) -> int:
    """Stable 63-bit key for a client id string (dict/table row id)."""
    import hashlib

    return int.from_bytes(
        hashlib.blake2b(client.encode("utf-8"), digest_size=8).digest(),
        "big",
    ) >> 1


class _SeqTable:
    """Checkpoint adapter persisting the push-dedup map ({client key:
    last applied seq}) as a dim-1 table, closing the
    die-between-checkpoint-and-reply double-apply window: a relaunch
    restores the map with the rows it belongs to."""

    dim = 1

    def __init__(self, service: "HostRowService"):
        self._service = service

    def to_arrays(self):
        items = sorted(self._service._applied_seq.items())
        ids = np.array([k for k, _ in items], np.int64)
        rows = np.array(
            [[v] for _, v in items], np.float64
        ).reshape(-1, 1)
        return ids, rows

    def set(self, ids, values):
        values = np.asarray(values).reshape(len(list(ids)), -1)
        for key, row in zip(ids, values):
            self._service._applied_seq[int(key)] = int(round(float(row[0])))


class HostRowService:
    """Server side of the shared host tier.

    ``checkpoint_dir``/``checkpoint_steps``: save rows + optimizer
    state every N gradient pushes — the reference PS checkpoints inside
    ``push_gradients`` every checkpoint_steps versions
    (ps/servicer.py:242-257, pkg/ps/server.go:114-127); the push count
    is the service's version. At start the newest valid version is
    restored, so a relaunched service pod resumes lossless (reference
    PS relaunch + checkpoint-restore semantics).
    """

    def __init__(self, tables: Dict, optimizer, checkpoint_dir: str = "",
                 checkpoint_steps: int = 0, keep_max: int = 3,
                 metrics_registry=None,
                 push_durable_wait_secs: float = 60.0):
        self._tables = tables
        self._optimizer = optimizer
        # Ceiling on the durable-ack fsync wait (--push_durable_wait_secs);
        # a propagated request deadline SHRINKS it per-push (there is no
        # point fsync-waiting for a caller that stopped listening — the
        # record is already queued and will land regardless; only the
        # ack is abandoned).
        self._push_durable_wait_secs = float(push_durable_wait_secs)
        # Telemetry: served row traffic + handler latency (the row
        # plane's pressure gauges; scrape the serving process).
        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        # exemplars: slow pulls/pushes stamp their row_pull/row_push
        # span's trace id onto the observation (explicitly — the span
        # closes before the handler observes), so an SLO breach on
        # these histograms names concrete offending traces
        # (docs/observability.md "Continuous profiling & exemplars").
        self._m_pull = registry.histogram(
            "row_service_pull_seconds", "pull_rows handler latency",
            exemplars=True,
        )
        self._m_push = registry.histogram(
            "row_service_push_seconds", "push_row_grads handler latency",
            exemplars=True,
        )
        self._m_pulled = registry.counter(
            "row_service_pulled_rows_total", "Rows served to pulls",
        )
        self._m_pushed = registry.counter(
            "row_service_pushed_rows_total",
            "Row gradients applied from pushes",
        )
        self._m_dup = registry.counter(
            "row_service_duplicate_pushes_total",
            "Retried pushes dropped by (client, seq) dedup",
        )
        self._m_stall = registry.histogram(
            "checkpoint_stall_seconds",
            "Step/push-path time spent capturing + enqueuing a "
            "checkpoint (the part the hot path actually waits on)",
            exemplars=True,
        )
        # Reshard plane (docs/sparse_path.md "Live resharding"):
        self._m_map_version = registry.gauge(
            "row_shard_map_version",
            "Installed shard-map epoch (0 = static legacy topology)",
        )
        self._m_mig_rows = registry.counter(
            "row_migration_rows_total",
            "Rows streamed out by live range migrations",
        )
        self._m_mig_bytes = registry.counter(
            "row_migration_bytes_total",
            "Row bytes streamed out by live range migrations",
        )
        self._m_mig_secs = registry.counter(
            "row_migration_seconds_total",
            "Wall seconds spent inside migrate_out (copy + catch-up "
            "+ fence window)",
        )
        self._m_redirects = registry.counter(
            "row_redirects_total",
            "Pulls/pushes redirected because this shard does not own "
            "their buckets under the installed map",
        )
        self._m_replica_reads = registry.counter(
            "row_replica_reads_total",
            "Rows served from this shard's hot-row replica store",
        )
        self._m_durable_wait_timeouts = registry.counter(
            "row_push_durable_wait_timeouts_total",
            "Durable-ack fsync waits abandoned (wait ceiling or the "
            "propagated request deadline expired before the covering "
            "group commit landed; the record itself still commits)",
        )
        self._m_replica_stale = registry.histogram(
            "row_replica_staleness_seconds",
            "Replication lag observed at refresh receipt (home "
            "read-time to replica apply-time, wall clock)",
        )
        self._lock = threading.RLock()
        # ---- reshard state (all mutated under self._lock) ----
        self._shard_map: Optional[ShardMap] = None
        self._shard_id = 0
        # Outbound migration: {"id", "lo", "hi", "touched": {table:
        # set(ids)}} — the push handler records applied ids landing in
        # the moving range so catch-up ships exactly the delta (the
        # PR 10 dirty-tracking idea, scoped to the migration so the
        # checkpoint's own dirty sets are untouched).
        self._out_migration: Optional[dict] = None
        # Inbound migrations this shard agreed to ingest (generation
        # fence: ingest_rows for an unregistered id is rejected).
        self._ingests: Dict[str, dict] = {}
        # Write fences: [(lo, hi, monotonic deadline)] — pushes to a
        # fenced bucket get a retryable "fenced" verdict between the
        # final migration delta and the cutover map install.
        self._fences = []
        # Hot-id pull tracking (only once a map is installed). Its own
        # lock: the counting is advisory and must never serialize the
        # pull/push handlers on the service lock.
        self._hot_lock = threading.Lock()
        self._hot_counts: Dict[str, Counter] = {}
        self._hot_track_pulls = 0
        # Plain load counters for shard_stats (registry counters are
        # process-global; the authority needs THIS shard's numbers).
        self._stat_pulled_rows = 0
        self._stat_pushed_rows = 0
        # Hot-row replica store: {table: {id: [row, applied_at,
        # read_at]}} — rows this shard serves as a READ replica.
        self._replica_store: Dict[str, dict] = {}
        self._replica_queue = None
        self._replica_thread = None
        # Shard-to-shard transports (migration streaming, replica
        # refresh). Tests/drills inject an in-process factory.
        self.transport_factory: Optional[Callable] = None
        self._transports: Dict[str, object] = {}
        self._server: Optional[RpcServer] = None
        self._push_count = 0
        # Per-table monotonic update counter: bumped under the lock on
        # every APPLIED push (duplicates don't count — they changed
        # nothing). Serving-side hot-row caches poll this via the
        # ``table_versions`` RPC: an unchanged counter proves every
        # cached row is still current, a changed one invalidates the
        # table's cache entries. Not persisted: a restarted service
        # reports 0 again, and caches compare by != (not <), so the
        # reset reads as "changed" and flushes them — safe.
        self._table_versions: Dict[str, int] = {
            name: 0 for name in tables
        }
        # Wall-clock stamp of the last APPLIED push per table — the
        # ROADMAP's push-to-servable freshness signal: pulls return it,
        # and serving-side readers observe ``now - applied_at`` as
        # ``edl_tpu_row_freshness_seconds`` (how stale the rows a
        # prediction just used could be). Wall clock on purpose: the
        # reader is another process; monotonic clocks don't compare.
        self._applied_at: Dict[str, float] = {}
        self._checkpoint_steps = 0
        self._saver = None
        self._ckpt_writer = None
        self._ckpt_planner = None
        # Write-ahead push log (storage/pushlog.py): None until
        # configure_push_log. With it, every APPLIED push is framed
        # into the group-commit queue under the same lock that applied
        # it, so the log is a total order of this shard's applies and
        # a relaunch replays the tail through the normal apply path —
        # no acked write is ever lost (zero RPO in durable-ack mode).
        self._push_log = None
        # Serializes the busy-check/plan/capture/submit sequence:
        # concurrent push handlers at consecutive checkpoint versions
        # must not interleave inside the planner, or two deltas name
        # the same prev and the chain walk drops the second (its
        # drained rows would be silently unrestorable). An overlapping
        # interval trigger skips (non-blocking acquire), the drain
        # path waits — the old single-writer semaphore's discipline,
        # now at the trigger instead of the write.
        self._ckpt_trigger = threading.Lock()
        # Push dedup: {client key: last applied seq} — retried pushes
        # after an ambiguous failure must not double-apply. Persisted
        # with the checkpoint (see _SeqTable).
        self._applied_seq: Dict[int, int] = {}
        if checkpoint_dir:
            self.configure_checkpoint(
                checkpoint_dir, checkpoint_steps, keep_max
            )

    # ---- RPC handlers --------------------------------------------------

    def handlers(self):
        return {
            "table_info": self._table_info,
            "table_versions": self._table_versions_handler,
            "pull_rows": self._pull_rows,
            "push_row_grads": self._push_row_grads,
            "export_rows": self._export_rows,
            # Reshard plane:
            "get_shard_map": self._get_shard_map,
            "set_shard_map": self._set_shard_map,
            "shard_stats": self._shard_stats,
            "migrate_out": self._migrate_out,
            "begin_ingest": self._begin_ingest,
            "end_ingest": self._end_ingest,
            "ingest_rows": self._ingest_rows,
            "ingest_steps": self._ingest_steps,
            "pull_replica_rows": self._pull_replica_rows,
            "replica_refresh": self._replica_refresh,
        }

    def _table_info(self, request: dict) -> dict:
        return {
            "tables": {
                name: {"dim": int(table.dim)}
                for name, table in self._tables.items()
            }
        }

    def _table_versions_handler(self, request: dict) -> dict:
        """Monotonic per-table update counters — the serving cache's
        invalidation signal. One tiny fixed-size reply regardless of
        table size, so a cache can poll it far cheaper than re-pulling
        rows."""
        with self._lock:
            return {"versions": dict(self._table_versions),
                    "applied_at": dict(self._applied_at)}

    def table_version(self, table: str) -> int:
        """In-process accessor (tests / local tables)."""
        with self._lock:
            return self._table_versions[table]

    # ---- request validation (the malformed-grads guard) ----------------
    #
    # The native apply kernels (native/row_store.cc, the fused Pallas
    # path's host bookkeeping) trust the (n_ids, dim) shape they are
    # handed — a wrong-dim or wrong-count grad block read/written past
    # the arena segfaults the whole shard (observed while driving
    # PR 11). Validate every inbound block BEFORE it can reach an
    # apply; InvalidRequest surfaces as a clean INVALID_ARGUMENT to
    # the client instead of a dead process.

    def _validated_table(self, request: dict):
        name = request.get("table")
        table = self._tables.get(name) if isinstance(name, str) else None
        if table is None:
            raise InvalidRequest(
                f"unknown table {name!r} (serving "
                f"{sorted(self._tables)})"
            )
        return name, table

    @staticmethod
    def _validated_ids(request: dict) -> np.ndarray:
        raw = request.get("ids")
        if raw is None:
            raise InvalidRequest("ids missing")
        try:
            ids = np.asarray(raw, np.int64)
        except (ValueError, TypeError, OverflowError) as exc:
            raise InvalidRequest(f"ids not an int64 vector: {exc}")
        if ids.ndim != 1:
            raise InvalidRequest(
                f"ids must be 1-D, got shape {ids.shape}"
            )
        return ids

    @staticmethod
    def _validated_grads(request: dict, ids: np.ndarray, table,
                         table_name: str) -> np.ndarray:
        if np.unique(ids).size != ids.size:
            # The apply contract is one update per id; the Python
            # wrapper raises a plain ValueError here (read as a server
            # bug) and the native path would silently double-apply.
            raise InvalidRequest("ids must be unique per push")
        raw = request.get("grads")
        if raw is None:
            raise InvalidRequest("grads missing")
        try:
            grads = np.asarray(raw, np.float32)
        except (ValueError, TypeError) as exc:
            # Ragged nests / non-numeric payloads land here.
            raise InvalidRequest(f"grads not a float32 block: {exc}")
        if grads.ndim != 2:
            raise InvalidRequest(
                f"grads must be 2-D (n_ids, dim), got shape "
                f"{grads.shape}"
            )
        expected = (int(ids.size), int(table.dim))
        if tuple(grads.shape) != expected:
            raise InvalidRequest(
                f"grads shape {tuple(grads.shape)} != "
                f"(len(ids), dim) = {expected} for table "
                f"{table_name!r}"
            )
        return grads

    def _pull_rows(self, request: dict) -> dict:
        t0 = time.monotonic()
        who = wl_principal.current()
        table_name, table = self._validated_table(request)
        ids = self._validated_ids(request)
        # Ambient span: nests under the RPC server span (role
        # rowservice) so lock-wait + store time is attributable
        # separately from wire/serde time; free with no recorder.
        # Kept by name past its exit: the latency observation below
        # stamps the span's trace id as the histogram exemplar.
        tiered = hasattr(table, "prefault")
        pull_span = tracing.span("row_pull", table=table_name,
                                 rows=int(ids.size),
                                 **wl_principal.span_attrs(who))
        with pull_span:
            if tiered:
                # Fault this pull's cold rows with the DISK READ
                # outside the service lock: concurrent pushes wait on
                # in-memory bookkeeping only, and the host engine's
                # pull-ahead turns the fault into prefetch
                # (storage/tiered.py "Tiered storage").
                table.prefault(ids)
            # Explicit acquire/release (not ``with``) so hold time is
            # measured from acquisition, excluding contention wait —
            # the per-workload lock-hold meter answers "who OCCUPIES
            # the lock", not "who waits on it".
            self._lock.acquire()
            hold_t0 = time.monotonic()
            try:
                reject = self._reshard_reject_locked(ids)
                if reject is not None:
                    return reject
                rows = (table.get(ids, _defer_sweep=True) if tiered
                        else table.get(ids))
                applied_at = self._applied_at.get(request["table"], 0.0)
                self._stat_pulled_rows += int(ids.size)
                map_version = 0
                if self._shard_map is not None:
                    map_version = self._shard_map.version
            finally:
                self._lock.release()
                wl_usage.meter_lock_hold(
                    who, time.monotonic() - hold_t0
                )
            if tiered:
                # Budget sweep AFTER releasing the service lock: the
                # eviction's cold write stalls no handler but this one.
                table.maybe_sweep()
            if map_version:
                # Hot-id tracking feeds the authority's replica
                # designation; only maintained once a map is installed
                # (static topologies pay nothing) and OUTSIDE the
                # service lock (advisory stats must not serialize
                # handlers).
                self._track_hot(request["table"], ids)
        rows = np.asarray(rows, np.float32)
        self._m_pulled.inc(ids.size)
        wl_usage.meter_rows(who, "pull_rows", rows=int(ids.size),
                            nbytes=int(rows.nbytes))
        self._m_pull.observe(time.monotonic() - t0,
                             trace_id=pull_span.trace_id)
        # applied_at rides every pull so readers can observe row
        # freshness without an extra RPC (0.0 = never pushed).
        # map_version rides too: a replica-only epoch changes no
        # ownership, so REDIRECTs alone would never teach clients
        # about it — the piggybacked version lets them fetch the map
        # when it moves (0 = no map installed).
        return {"rows": rows,
                "applied_at": applied_at,
                "map_version": map_version}

    def _export_rows(self, request: dict) -> dict:
        """Dense rows ``lo+offset, lo+offset+stride, ... < hi`` for
        serving export WITHOUT inflating the live table: trained rows
        overlay a throwaway table's deterministic lazy init
        (serving/export.py materialization, server side).
        ``stride``/``offset`` let a sharded client pull only the rows
        this shard owns (id % N == shard) instead of the whole range."""
        table = self._tables[request["table"]]
        if "ids" in request:
            # Map-routed export (shard-map topologies): the client
            # asks each shard for exactly the ids it owns. Ownership
            # is enforced like pulls — a stale-epoch exporter gets a
            # REDIRECT, not silently lazy-initialized rows.
            want = np.asarray(request["ids"], np.int64)
            with self._lock:
                reject = self._reshard_reject_locked(want)
                if reject is not None:
                    return reject
                ids, rows = table.to_arrays()
            from elasticdl_tpu.serving.export import _clone_empty

            dense = np.asarray(_clone_empty(table).get(want))
            pos = {int(i): k for k, i in enumerate(want.tolist())}
            for i, row in zip(ids.tolist(), rows):
                at = pos.get(int(i))
                if at is not None:
                    dense[at] = row
            return {"rows": dense.astype(np.float32)}
        lo, hi = int(request["lo"]), int(request["hi"])
        stride = int(request.get("stride", 1))
        offset = int(request.get("offset", 0))
        want = np.arange(lo + offset, hi, stride)
        with self._lock:
            ids, rows = table.to_arrays()
        from elasticdl_tpu.serving.export import _clone_empty

        dense = np.asarray(_clone_empty(table).get(want))
        keep = (ids >= lo + offset) & (ids < hi)
        if stride != 1:
            keep &= (ids - lo - offset) % stride == 0
        dense[(ids[keep] - lo - offset) // stride] = rows[keep]
        return {"rows": dense.astype(np.float32)}

    def _push_row_grads(self, request: dict) -> dict:
        t0 = time.monotonic()
        who = wl_principal.current()
        table_name, table = self._validated_table(request)
        client = request.get("client", "")
        seq = int(request.get("seq", -1))
        ids = self._validated_ids(request)
        # Shape/dtype-gate the grad block BEFORE any lock or apply: a
        # malformed block must bounce as INVALID_ARGUMENT, never reach
        # the native kernels (segfault) or the Python apply (partial
        # mutation under the lock).
        grads = self._validated_grads(request, ids, table, table_name)
        prefault = getattr(table, "prefault_group", None)
        push_span = tracing.span("row_push", table=table_name,
                                 rows=int(ids.size),
                                 **wl_principal.span_attrs(who))
        with push_span:
            if prefault is not None:
                # Cold reads for evicted rows (and their optimizer
                # slots) OUTSIDE the service lock; a duplicate push
                # merely promotes rows it would have touched anyway.
                prefault(ids)
            duplicate = False
            wal_ticket = None
            # Explicit acquire/release for the same reason as
            # _pull_rows: the lock-hold meter must start at
            # acquisition, not enqueue.
            self._lock.acquire()
            hold_t0 = time.monotonic()
            try:
                # Ownership + fence checks BEFORE any mutation: a
                # redirected/fenced push applies nothing, so the
                # client's retry (against the new home, or after the
                # cutover) is the first and only apply.
                reject = self._reshard_reject_locked(ids)
                if reject is not None:
                    return reject
                if self._fence_hit_locked(ids):
                    return {"reshard": {"reason": "fenced"}}
                if client and seq >= 0:
                    key = _client_key(client)
                    if seq <= self._applied_seq.get(key, -1):
                        # Retried push whose first attempt DID apply
                        # before the reply was lost (at-most-once
                        # semantics). The duplicate ack still honors
                        # the durable-ack contract below: the FIRST
                        # attempt's WAL record may be queued unfsynced.
                        self._m_dup.inc()
                        duplicate = True
                if not duplicate:
                    self._optimizer.apply_gradients(table, ids, grads)
                    self._table_versions[table_name] += 1
                    self._applied_at[table_name] = time.time()
                    if client and seq >= 0:
                        # Record only AFTER apply succeeds: a failed
                        # apply must leave the seq unburned so the
                        # client's retry is not dropped as a duplicate
                        # (the gradient would be lost).
                        self._applied_seq[_client_key(client)] = seq
                    self._push_count += 1
                    version = self._push_count
                    self._stat_pushed_rows += int(ids.size)
                    if self._push_log is not None:
                        # Enqueue under the SAME lock that applied:
                        # log order == apply order == version order.
                        # The fsync wait (durable ack) happens after
                        # the lock is released.
                        wal_ticket = self._push_log.append(
                            version=version, client=client or "",
                            seq=seq, table=table_name, ids=ids,
                            grads=grads,
                            applied_at=self._applied_at[table_name],
                            map_version=(
                                self._shard_map.version
                                if self._shard_map is not None else 0
                            ),
                        )
                    mig = self._out_migration
                    if mig is not None:
                        # Applied writes landing in the moving range
                        # feed the catch-up delta — the migration's own
                        # dirty tracking (the checkpoint's sets stay
                        # untouched).
                        b = bucket_of(ids)
                        in_range = (b >= mig["lo"]) & (b < mig["hi"])
                        if in_range.any():
                            mig["touched"].setdefault(
                                request["table"], set()
                            ).update(ids[in_range].tolist())
                    refresh_ids = self._replicated_ids_locked(
                        request["table"], ids
                    )
            finally:
                self._lock.release()
                wl_usage.meter_lock_hold(
                    who, time.monotonic() - hold_t0
                )
            if duplicate:
                if (self._push_log is not None
                        and self._push_log.ack == "durable"):
                    # Ack the retry only once the original attempt's
                    # record is durable — a duplicate ack is still an
                    # ack, and zero RPO covers it too.
                    fsync_t0 = time.monotonic()
                    self._durable_wait(self._push_log.barrier)
                    wl_usage.meter_fsync_wait(
                        who, time.monotonic() - fsync_t0
                    )
                return {"duplicate": True}
            if wal_ticket is not None and self._push_log.ack == "durable":
                # Durable ack: the reply leaves only after the group
                # commit covering this record fsyncs. A failed commit
                # raises — the client must NOT treat this push as
                # durable (the shard's WAL disk is broken and the
                # error is loud by design).
                fsync_t0 = time.monotonic()
                self._durable_wait(
                    lambda budget: wal_ticket.wait(timeout=budget)
                )
                wl_usage.meter_fsync_wait(
                    who, time.monotonic() - fsync_t0
                )
            if refresh_ids is not None:
                # Async push-driven replica refresh: enqueue OUTSIDE
                # the lock; the refresher thread reads fresh rows and
                # fans them to the replica shards.
                self._queue_refresh(request["table"], refresh_ids)
            if prefault is not None:
                # Deferred half of the fused apply's budget sweep —
                # eviction's cold writes run with the lock released.
                table.maybe_sweep()
        self._m_pushed.inc(ids.size)
        wl_usage.meter_rows(who, "push_row_grads", rows=int(ids.size),
                            nbytes=int(grads.nbytes))
        self._m_push.observe(time.monotonic() - t0,
                             trace_id=push_span.trace_id)
        if (
            self._saver is not None and self._checkpoint_steps
            and version % self._checkpoint_steps == 0
        ):
            self._checkpoint(version)
        m = self._shard_map
        return {"map_version": m.version if m is not None else 0}

    def configure_push_durable_wait(self, secs: float) -> None:
        """Set the durable-ack fsync wait ceiling
        (``--push_durable_wait_secs``; the zoo factory builds the
        service before flags are applied, mirroring
        configure_checkpoint/configure_push_log)."""
        self._push_durable_wait_secs = float(secs)

    def _durable_wait(self, waiter: Callable[[float], None]) -> None:
        """Run one durable-ack fsync wait (``waiter(timeout_secs)``)
        under the configured ceiling, SHRUNK by the propagated request
        deadline when one is present: a caller that stopped listening
        gets its error now instead of holding a handler thread for the
        full ceiling (the record itself is already queued and commits
        regardless — only the ack is abandoned). A timed-out wait
        counts in ``row_push_durable_wait_timeouts_total`` and still
        raises: the client must never learn "durable" from a wait that
        did not observe the fsync."""
        from elasticdl_tpu.storage.pushlog import PushLogError

        budget = self._push_durable_wait_secs
        left = wl_deadline.remaining()
        if left is not None:
            budget = min(budget, max(left, 1e-3))
        try:
            waiter(budget)
        except PushLogError as exc:
            # Only the ran-out-of-time shape is a "timeout"; a commit
            # WRITE failure (broken WAL disk) is a different, louder
            # problem and must not hide in this counter.
            if "did not complete in time" in str(exc):
                self._m_durable_wait_timeouts.inc()
            raise

    # ---- live resharding: map enforcement ------------------------------

    def _reshard_reject_locked(self, ids: np.ndarray) -> Optional[dict]:
        """REDIRECT verdict for ids this shard does not own under the
        installed map (None = all owned, or no map installed — the
        static legacy topology never redirects). The carried map is
        how stale clients converge after a cutover."""
        m = self._shard_map
        if m is None:
            return None
        if m.owns(self._shard_id, ids).all():
            return None
        self._m_redirects.inc()
        return {"reshard": {"reason": "not_owner", "map": m.to_json()}}

    def _fence_hit_locked(self, ids: np.ndarray) -> bool:
        """Whether any id lands in a write-fenced bucket range (the
        window between a migration's final delta and its cutover).
        Expired fences lift themselves — an authority that died before
        the cutover re-runs the migration from scratch."""
        if not self._fences:
            return False
        now = time.monotonic()
        expired = [f for f in self._fences if f[2] <= now]
        if expired:
            # Loud: an expiring fence means a migration was abandoned
            # mid-protocol (or the cutover is pathologically slow) —
            # writes re-accepted here diverge from the target's copy
            # until the authority re-runs the move.
            for lo, hi, _dl in expired:
                logger.warning(
                    "write fence on buckets [%d, %d) EXPIRED before "
                    "cutover; accepting writes again (the abandoned "
                    "migration must re-run)", lo, hi,
                )
            self._fences = [f for f in self._fences if f[2] > now]
        if not self._fences:
            return False
        b = bucket_of(ids)
        return any(
            bool(((b >= lo) & (b < hi)).any())
            for lo, hi, _deadline in self._fences
        )

    def _track_hot(self, table: str, ids: np.ndarray):
        with self._hot_lock:
            counts = self._hot_counts.setdefault(table, Counter())
            counts.update(ids.tolist())
            self._hot_track_pulls += 1
            if (self._hot_track_pulls % 256 == 0
                    and len(counts) > HOT_TRACK_MAX_IDS):
                # Lossy decay: keep the head at half weight, drop the
                # tail — one-touch stranger ids must not grow the
                # counter without bound.
                self._hot_counts[table] = Counter({
                    i: n // 2
                    for i, n in counts.most_common(
                        HOT_TRACK_MAX_IDS // 2
                    )
                    if n > 1
                })

    def _replicated_ids_locked(self, table: str,
                               ids: np.ndarray) -> Optional[np.ndarray]:
        """The pushed ids whose replica sets need a refresh (None =
        replication not in play for this table)."""
        m = self._shard_map
        if m is None:
            return None
        per = m.replicas.get(table)
        if not per:
            return None
        hot = [i for i in ids.tolist() if i in per]
        return np.asarray(hot, np.int64) if hot else None

    # ---- live resharding: map install ----------------------------------

    def install_shard_map(self, shard_map: ShardMap, shard_id: int):
        """In-process map install (the RPC handler's body; drills and
        the authority's direct transport call this)."""
        return self._set_shard_map({
            "map": shard_map.to_json(), "shard_id": int(shard_id),
        })

    def _get_shard_map(self, request: dict) -> dict:
        with self._lock:
            m = self._shard_map
            return {
                "map": m.to_json() if m is not None else None,
                "shard_id": self._shard_id,
            }

    def _set_shard_map(self, request: dict) -> dict:
        """Install a newer map epoch (idempotent at the same version,
        stale versions rejected — the monotonic version IS the fence).
        On install this shard erases rows it no longer owns (they were
        migrated before the authority ever flipped the version) except
        rows inside a registered inbound migration (those arrive ahead
        of the ownership flip by design)."""
        fresh = ShardMap.from_json(request["map"])
        shard_id = int(request.get("shard_id", -1))
        with self._lock:
            cur = self._shard_map
            if cur is not None and fresh.version < cur.version:
                return {"accepted": False, "version": cur.version}
            if shard_id >= 0:
                self._shard_id = shard_id
            already = cur is not None and fresh.version == cur.version
            self._shard_map = fresh
            self._m_map_version.set(float(fresh.version))
            erased = 0
            if not already:
                # Fences on ranges we no longer own served their
                # purpose (the cutover landed); writes there now
                # redirect instead.
                self._fences = [
                    (lo, hi, dl) for lo, hi, dl in self._fences
                    if bool((fresh.owner_table[lo:hi]
                             == self._shard_id).any())
                ]
                erased = self._erase_unowned_locked()
                # Replica store: drop copies this shard no longer
                # replicates (topology moved on).
                for table, store in self._replica_store.items():
                    per = fresh.replicas.get(table, {})
                    for i in list(store):
                        if self._shard_id not in per.get(i, ()):
                            del store[i]
        if not already:
            self._warm_replicas()
        return {"accepted": True, "version": fresh.version,
                "erased_rows": erased}

    def _erase_unowned_locked(self) -> int:
        """Drop rows (and their optimizer slots) whose bucket this
        shard no longer owns — the cutover's single-homing guarantee.
        Buckets inside a registered inbound migration are exempt: the
        copy precedes the ownership flip."""
        m = self._shard_map
        if m is None:
            return 0
        exempt = [(g["lo"], g["hi"]) for g in self._ingests.values()]
        erased = 0
        for group in self._migration_views().values():
            for table in group.values():
                ids = _all_ids(table)
                if not ids.size:
                    continue
                b = bucket_of(ids)
                drop = m.home_of_ids(ids) != self._shard_id
                for lo, hi in exempt:
                    drop &= ~((b >= lo) & (b < hi))
                if drop.any():
                    erased += int(table.erase(ids[drop]))
        return erased

    # ---- live resharding: migration ------------------------------------

    def _migration_views(self) -> Dict[str, Dict[str, object]]:
        """{primary table: {view name: raw table}} — each primary with
        its optimizer slot tables (lockstep movement). Step counters
        and the push-dedup seq map stay per-shard: they are scalar
        bookkeeping of THIS process, not row state."""
        out = {}
        for name, table in self._tables.items():
            group = {name: table}
            for slot in getattr(self._optimizer.opt, "slot_names", ()):
                group[get_slot_table_name(name, slot)] = (
                    self._optimizer._slot_table(table, slot)
                )
            out[name] = group
        return out

    def _transport(self, addr: str):
        transport = self._transports.get(addr)
        if transport is None:
            if self.transport_factory is not None:
                transport = self.transport_factory(addr)
            else:
                transport = RpcStub(addr, SERVICE_NAME, max_retries=2)
            self._transports[addr] = transport
        return transport

    def _migrate_out(self, request: dict) -> dict:
        """Source side of a live range move: stream every owned row in
        buckets [lo, hi) — with its optimizer slots — to the target's
        ``ingest_rows``, chunk-wise, WITHOUT stalling concurrent
        pulls/pushes (the service lock is held only per chunk read;
        tiered tables serve cold chunks from segment reads, never
        promoting them through the hot budget). Writes landing in the
        range during the copy are recorded and re-shipped in catch-up
        rounds; the final round fences the range so the authority can
        flip the map against frozen bytes."""
        mig_id = str(request["migration_id"])
        lo, hi = int(request["lo"]), int(request["hi"])
        target_addr = str(request["target_addr"])
        t0 = time.monotonic()
        transport = self._transport(target_addr)
        views = self._migration_views()
        moved_rows = 0
        moved_bytes = 0
        rounds = 0
        with self._lock:
            if self._out_migration is not None:
                raise RuntimeError(
                    f"migration {self._out_migration['id']} already in "
                    "flight; one outbound move at a time"
                )
            self._out_migration = {
                "id": mig_id, "lo": lo, "hi": hi, "touched": {},
            }
        try:
            # Self-tag the whole outbound stream (bulk chunks,
            # catch-up deltas, the step ship) as migration traffic:
            # every ingest_rows RPC below inherits the ambient
            # principal, so the target's meters bill these bytes to
            # purpose=migration — never to the client push that
            # triggered the move.
            with tracing.span("row_migrate_out", migration=mig_id,
                              lo=lo, hi=hi), \
                    wl_principal.pushed(purpose="migration"):
                # Bulk copy: enumerate once, then chunked reads.
                for primary, group in views.items():
                    for vname, table in group.items():
                        with self._lock:
                            ids = _all_ids(table)
                        b = bucket_of(ids)
                        sel = ids[(b >= lo) & (b < hi)]
                        for at in range(0, sel.size, MIGRATE_CHUNK_ROWS):
                            chunk = sel[at:at + MIGRATE_CHUNK_ROWS]
                            with self._lock:
                                rows = _peek_rows(table, chunk)
                            transport.call(
                                "ingest_rows", migration_id=mig_id,
                                table=vname, ids=chunk, rows=rows,
                            )
                            moved_rows += int(chunk.size)
                            moved_bytes += int(rows.nbytes)
                            hook = _mid_migrate_hook
                            if hook is not None:
                                hook(self, mig_id, vname, chunk)
                # Catch-up: re-ship rows written during the copy until
                # the delta is drained or rounds run out; the last
                # swap happens under a WRITE FENCE so no push can
                # slip between the final delta and the cutover.
                while True:
                    with self._lock:
                        touched = self._out_migration["touched"]
                        drained = not any(touched.values())
                        if drained or rounds >= MIGRATE_CATCHUP_ROUNDS:
                            self._fences.append(
                                (lo, hi,
                                 time.monotonic() + FENCE_TTL_SECS)
                            )
                            final = touched
                            self._out_migration["touched"] = {}
                            break
                        self._out_migration["touched"] = {}
                    rounds += 1
                    r, nbytes = self._ship_delta(
                        views, touched, transport, mig_id
                    )
                    moved_rows += r
                    moved_bytes += nbytes
                r, nbytes = self._ship_delta(
                    views, final, transport, mig_id
                )
                moved_rows += r
                moved_bytes += nbytes
                # Ship the per-table apply counts too (inside the
                # fenced window, so they are final): Adam bias
                # correction on a fresh target would otherwise apply
                # migrated rows' first update with a near-step-1
                # correction — a large unintended magnitude spike.
                with self._lock:
                    steps = {
                        primary: int(
                            self._optimizer._steps.get(primary, 0)
                        )
                        for primary in views
                    }
                if any(steps.values()):
                    transport.call(
                        "ingest_steps", migration_id=mig_id,
                        steps=steps,
                    )
        finally:
            with self._lock:
                self._out_migration = None
        secs = time.monotonic() - t0
        self._m_mig_rows.inc(moved_rows)
        self._m_mig_bytes.inc(moved_bytes)
        self._m_mig_secs.inc(secs)
        return {
            "rows": moved_rows, "bytes": moved_bytes,
            "seconds": secs, "catchup_rounds": rounds,
        }

    def _ship_delta(self, views, touched: Dict[str, set], transport,
                    mig_id: str):
        """Re-ship touched primaries + their slots (one catch-up or
        final-fence round)."""
        rows_out = 0
        bytes_out = 0
        for primary, id_set in touched.items():
            if not id_set:
                continue
            ids = np.asarray(sorted(id_set), np.int64)
            for vname, table in views.get(primary, {}).items():
                with self._lock:
                    rows = _peek_rows(table, ids)
                transport.call(
                    "ingest_rows", migration_id=mig_id,
                    table=vname, ids=ids, rows=rows,
                )
                rows_out += int(ids.size)
                bytes_out += int(rows.nbytes)
        return rows_out, bytes_out

    def _begin_ingest(self, request: dict) -> dict:
        """Target side: register an inbound migration (generation
        fence — chunks for an unregistered migration id are rejected,
        so a zombie source from an abandoned attempt cannot corrupt a
        later one)."""
        mig_id = str(request["migration_id"])
        with self._lock:
            self._ingests[mig_id] = {
                "lo": int(request["lo"]), "hi": int(request["hi"]),
                "rows": 0,
            }
        return {}

    def _end_ingest(self, request: dict) -> dict:
        with self._lock:
            info = self._ingests.pop(str(request["migration_id"]), None)
        return {"rows": int(info["rows"]) if info else 0}

    def _ingest_rows(self, request: dict) -> dict:
        """One migrated chunk: overwrite-set into the named view
        (idempotent — a re-run migration re-ships the same bytes).
        ``set`` marks the rows dirty when checkpointing is on, so
        ingested rows ride the target's next delta checkpoint."""
        mig_id = str(request["migration_id"])
        vname = str(request["table"])
        ids = np.asarray(request["ids"], np.int64)
        rows = np.asarray(request["rows"], np.float32)
        flat = {}
        for group in self._migration_views().values():
            flat.update(group)
        table = flat.get(vname)
        if table is None:
            raise ValueError(f"ingest for unknown view {vname!r}")
        with self._lock:
            info = self._ingests.get(mig_id)
            if info is None:
                raise ValueError(
                    f"ingest for unregistered migration {mig_id!r} "
                    "(stale source? re-run the migration)"
                )
            table.set(ids, rows)
            info["rows"] += int(ids.size)
        # Bills to the wire principal (the source's ambient
        # purpose=migration rode the RPC here).
        wl_usage.meter_rows(wl_principal.current(), "ingest_rows",
                            rows=int(ids.size),
                            nbytes=int(rows.nbytes))
        return {}

    def _ingest_steps(self, request: dict) -> dict:
        """Adopt the source's per-table apply counts by MAX: a target
        that already applied its own pushes keeps its larger count
        (bias correction must only ever see a step as large as the
        oldest state it covers), a fresh split target inherits the
        source's so migrated rows' next Adam update is not corrected
        as if it were step 1."""
        mig_id = str(request["migration_id"])
        steps = request.get("steps") or {}
        with self._lock:
            if mig_id not in self._ingests:
                raise ValueError(
                    f"steps for unregistered migration {mig_id!r}"
                )
            for table, count in steps.items():
                if table in self._tables:
                    self._optimizer._steps[table] = max(
                        int(self._optimizer._steps.get(table, 0)),
                        int(count),
                    )
        return {}

    # ---- live resharding: hot-row read replicas ------------------------

    def _shard_stats(self, request: dict) -> dict:
        """Load + hot-set snapshot for the authority's policy tick."""
        top_k = int(request.get("top_k", 64))
        with self._hot_lock:
            hot = {
                table: [[int(i), int(n)]
                        for i, n in counts.most_common(top_k)]
                for table, counts in self._hot_counts.items()
            }
        with self._lock:
            return {
                "shard_id": self._shard_id,
                "map_version": (
                    self._shard_map.version
                    if self._shard_map is not None else 0
                ),
                "pulled_rows": self._stat_pulled_rows,
                "pushed_rows": self._stat_pushed_rows,
                "num_rows": {
                    name: int(t.num_rows)
                    for name, t in self._tables.items()
                    if hasattr(t, "num_rows")
                },
                "hot": hot,
            }

    def _pull_replica_rows(self, request: dict) -> dict:
        """Serve hot-id reads from the replica store. Per-id found
        mask: a miss (refresh not landed yet) falls back to the home
        shard client-side — a replica is an accelerator, never an
        availability dependency."""
        table = str(request["table"])
        ids = np.asarray(request["ids"], np.int64)
        dim = int(self._tables[table].dim)
        rows = np.zeros((ids.size, dim), np.float32)
        found = np.zeros(ids.size, bool)
        applied_at = 0.0
        with self._lock:
            store = self._replica_store.get(table, {})
            stamps = []
            for k, i in enumerate(ids.tolist()):
                entry = store.get(i)
                if entry is not None:
                    rows[k] = entry[0]
                    found[k] = True
                    stamps.append(entry[1])
            if stamps:
                # MIN over served copies: the conservative freshness
                # stamp (same discipline as _ShardedTable).
                applied_at = min(stamps)
        self._m_replica_reads.inc(int(found.sum()))
        wl_usage.meter_rows(wl_principal.current(), "pull_replica_rows",
                            rows=int(found.sum()),
                            nbytes=int(rows.nbytes))
        return {"rows": rows, "found": found, "applied_at": applied_at}

    def _replica_refresh(self, request: dict) -> dict:
        """Home-pushed copy of hot rows: store them and observe the
        replication lag (home read-time → here, wall clock — same
        cross-process clock caveat as row_freshness_seconds).

        ``map_version`` is the epoch the HOME computed the fan-out
        under. A newer-than-ours epoch is accepted wholesale: the
        designation distribution races the home's warm-up refreshes
        (the home gets the new map first and fans out immediately), and
        dropping those copies would leave this replica cold until the
        next organic push per id. Our own install prunes anything the
        epoch turns out not to replicate here. Only a refresh from an
        epoch at-or-below ours applies the per-id designation guard
        (a zombie home's stale fan-out must not resurrect copies)."""
        table = str(request["table"])
        ids = np.asarray(request["ids"], np.int64)
        rows = np.asarray(request["rows"], np.float32)
        applied_at = float(request.get("applied_at", 0.0))
        read_at = float(request.get("read_at", 0.0))
        sender_version = int(request.get("map_version", 0))
        now = time.time()
        with self._lock:
            m = self._shard_map
            ahead = m is None or sender_version > m.version
            store = self._replica_store.setdefault(table, {})
            for k, i in enumerate(ids.tolist()):
                if not ahead and self._shard_id not in (
                    m.replica_targets(table, i)
                ):
                    continue  # stale designation; don't serve it
                store[i] = (rows[k].copy(), applied_at, read_at)
        if read_at:
            self._m_replica_stale.observe(max(0.0, now - read_at))
        wl_usage.meter_rows(wl_principal.current(), "replica_refresh",
                            rows=int(ids.size),
                            nbytes=int(rows.nbytes))
        return {}

    def _queue_refresh(self, table: str, ids: np.ndarray):
        if self._replica_thread is None:
            import queue as _queue

            with self._lock:
                if self._replica_thread is None:
                    self._replica_queue = _queue.Queue(maxsize=128)
                    self._replica_thread = threading.Thread(
                        target=self._replica_loop, daemon=True,
                        name="row-replica-refresh",
                    )
                    self._replica_thread.start()
        try:
            self._replica_queue.put_nowait((table, ids))
        except Exception:
            # Full queue: drop this refresh — replicas are best-effort
            # bounded-staleness copies; the next push re-enqueues.
            pass

    def _replica_loop(self):
        while True:
            item = self._replica_queue.get()
            if item is None:
                return
            table, ids = item
            try:
                self._do_refresh(table, ids)
            except Exception as exc:
                logger.warning("replica refresh failed: %s", exc)

    def _do_refresh(self, table_name: str, ids: np.ndarray):
        with self._lock:
            m = self._shard_map
            if m is None:
                return
            per = m.replicas.get(table_name)
            if not per:
                return
            table = self._tables[table_name]
            if hasattr(table, "contains"):
                ids = ids[table.contains(ids)]
            if not ids.size:
                return
            rows = _peek_rows(table, ids)
            applied_at = self._applied_at.get(table_name, 0.0)
            shards = list(m.shards)
            map_version = m.version
        read_at = time.time()
        targets: Dict[int, list] = {}
        for k, i in enumerate(ids.tolist()):
            for s in per.get(i, ()):
                if s != self._shard_id:
                    targets.setdefault(s, []).append(k)
        # Refreshes run on the dedicated refresher thread (no ambient
        # principal): self-tag the fan-out so replica bytes bill to
        # purpose=replica_refresh at the receiving shards.
        with wl_principal.pushed(purpose="replica_refresh"):
            for s, picks in targets.items():
                sel = np.asarray(picks, np.intp)
                try:
                    self._transport(shards[s]).call(
                        "replica_refresh", table=table_name,
                        ids=ids[sel], rows=rows[sel],
                        applied_at=applied_at, read_at=read_at,
                        map_version=map_version,
                    )
                except Exception as exc:
                    logger.warning(
                        "replica refresh to shard %d failed: %s", s, exc
                    )

    def _warm_replicas(self):
        """On a new map: push this shard's owned, already-materialized
        replicated ids out once so replicas start warm (afterwards
        refreshes are push-driven)."""
        with self._lock:
            m = self._shard_map
            if m is None:
                return
            work = []
            for table, per in m.replicas.items():
                if table not in self._tables or not per:
                    continue
                ids = np.fromiter(per.keys(), np.int64,
                                  count=len(per))
                owned = ids[m.owns(self._shard_id, ids)]
                if owned.size:
                    work.append((table, owned))
        for table, owned in work:
            self._queue_refresh(table, owned)

    # ---- tiered storage ------------------------------------------------

    def configure_tiering(self, cold_dir: str, hot_budget_rows: int,
                          segment_max_bytes: int = 8 << 20,
                          compact_live_fraction: float = 0.5,
                          background_compact: bool = True):
        """Re-house every table behind a two-tier store (hot arena
        bounded by ``hot_budget_rows`` per table, cold rows spilled to
        CRC-framed segments under ``cold_dir`` — storage/tiered.py):
        the beyond-RAM path, letting this shard serve tables far larger
        than host memory as long as the working set fits the budget.

        Must run BEFORE ``configure_checkpoint``: checkpoint config
        enables dirty tracking on the table views it sees, and the
        tier wrapper owns that tracking once tiering is on (a row
        demoted while dirty must still ride the next delta)."""
        from elasticdl_tpu.storage import TierPolicy, tier_host_tables

        with self._lock:
            if self._saver is not None:
                raise RuntimeError(
                    "configure_tiering must run before "
                    "configure_checkpoint (dirty tracking moves to the "
                    "tier wrapper)"
                )
            self._tables = tier_host_tables(
                self._tables, cold_dir,
                TierPolicy(
                    hot_budget_rows,
                    segment_max_bytes=segment_max_bytes,
                    compact_live_fraction=compact_live_fraction,
                    background_compact=background_compact,
                ),
            )
            for table in self._tables.values():
                # The push handler sweeps AFTER releasing the service
                # lock (maybe_sweep below); a fused apply must not
                # also sweep inside it.
                table.defer_apply_sweep = True
        logger.info(
            "Row service tiering on: hot budget %d rows/table, cold "
            "tier at %s", hot_budget_rows, cold_dir,
        )
        return self

    def tier_stats(self) -> Dict[str, dict]:
        """Per-table tier occupancy/garbage (tests, debug endpoints)."""
        with self._lock:
            return {
                name: table.tier_stats()
                for name, table in self._tables.items()
                if hasattr(table, "tier_stats")
            }

    # ---- checkpoint ----------------------------------------------------

    def configure_checkpoint(self, checkpoint_dir: str,
                             checkpoint_steps: int = 0, keep_max: int = 3,
                             delta_chain_max: int = 8,
                             async_write: bool = True):
        """Attach (or re-point) the checkpoint saver and restore the
        newest valid version (chain-aware).

        ``delta_chain_max`` > 0 (default): interval saves write
        incremental deltas — dirty rows + their optimizer slots since
        the previous save — with a periodic full base compaction.
        ``async_write`` (default): the push handler pays only capture
        + enqueue; serialization and file IO run on the bounded
        background writer (``CheckpointWriter``). The chaos harness
        passes False for deterministic schedules."""
        from elasticdl_tpu.checkpoint.saver import (
            ChainPlanner,
            CheckpointSaver,
        )
        from elasticdl_tpu.checkpoint.writer import CheckpointWriter

        if self._ckpt_writer is not None:
            # Re-point: land (and surface) anything queued on the old
            # writer before abandoning it — an orphaned writer's
            # deferred failure would never raise, and its parked
            # thread never retire.
            self._ckpt_writer.close()
        self._saver = CheckpointSaver(
            checkpoint_dir, keep_max=keep_max,
            delta_chain_max=delta_chain_max,
        )
        self._ckpt_writer = CheckpointWriter(
            max_pending=1, sync=not async_write
        )
        self._ckpt_planner = ChainPlanner(delta_chain_max)
        self._checkpoint_steps = int(checkpoint_steps)
        for view in self.host_tables.values():
            # Turn dirty tracking on now that a consumer drains it
            # (host_tables pre-creates the optimizer slot tables, so
            # this covers them too; tables are OFF by default — the
            # marked-ids set would otherwise grow unbounded on
            # services that never checkpoint).
            enable = getattr(view, "enable_dirty_tracking", None)
            if enable is not None:
                enable()
        self._restore_latest()
        return self

    # ---- write-ahead push log (zero-RPO state plane) --------------------

    def configure_push_log(self, log_dir: str, group_ms: float = 2.0,
                           ack: str = "durable",
                           segment_max_bytes: int = 8 << 20):
        """Attach the write-ahead push log (storage/pushlog.py) and
        replay its tail: every record past the restored checkpoint
        version is re-applied through the normal apply path, where the
        checkpointed (client, seq) dedup map makes replay idempotent
        and the installed shard map filters ranges that migrated away.

        Must run AFTER ``configure_checkpoint`` (restore-chain first,
        then the log tail) and after ``configure_tiering``. With no
        checkpoint configured the whole log replays — the log alone is
        a valid (unbounded) durability story; the checkpoint chain is
        what lets it truncate.

        ``ack="durable"`` (default): push replies wait for the group
        commit covering their record — acked-push RPO = 0.
        ``ack="applied"``: replies return after the in-memory apply;
        RPO = one ``group_ms`` window.
        """
        from elasticdl_tpu.observability import default_registry
        from elasticdl_tpu.storage.pushlog import PushLog

        if self._push_log is not None:
            self._push_log.close()
        log = PushLog(
            log_dir, group_ms=group_ms, ack=ack,
            segment_max_bytes=segment_max_bytes,
        )
        m_replayed = default_registry().counter(
            "row_push_log_replayed_records_total",
            "Push-log records re-applied on relaunch (past the "
            "restored checkpoint version)",
        )
        with self._lock:
            restored = self._push_count
        replayed = covered = 0
        # Self-tag the tail replay: its cold faults and apply work
        # meter as purpose=replay, never as the client traffic the
        # records originally were.
        with wl_principal.pushed(purpose="replay"):
            for record in log.replay_records():
                if self._replay_push_record(record):
                    replayed += 1
                else:
                    covered += 1
        if replayed:
            m_replayed.inc(replayed)
        for table in self._tables.values():
            # Tiered tables: replay deferred every budget sweep; one
            # sweep per table now brings the hot arena back under
            # budget before serving starts.
            sweep = getattr(table, "maybe_sweep", None)
            if sweep is not None:
                sweep()
        # Sealed segments at or below the restored tip are covered by
        # the chain already — reclaim them now rather than re-scanning
        # them on every future relaunch.
        log.truncate_through(restored)
        self._push_log = log
        logger.info(
            "Row service push log at %s (ack=%s, group %.1fms): "
            "replayed %d record(s) past checkpoint version %d "
            "(%d already covered/filtered)",
            log_dir, ack, group_ms, replayed, restored, covered,
        )
        return self

    def _replay_push_record(self, record: dict) -> bool:
        """Re-apply one logged push on relaunch. Returns whether it
        mutated state (False = covered by the restored checkpoint,
        deduped, or fully migrated away). The push version advances
        either way: the log is a total order of this shard's applies,
        and checkpoint versions must keep counting from where the
        dead incarnation stopped."""
        version = int(record["v"])
        table_name = str(record["table"])
        with self._lock:
            if version <= self._push_count:
                return False  # the restored chain already holds it
            applied = False
            table = self._tables.get(table_name)
            if table is None:
                logger.warning(
                    "push-log record v%d names unknown table %r; "
                    "skipped (different model module?)",
                    version, table_name,
                )
            else:
                ids = np.asarray(record["ids"], np.int64)
                grads = np.asarray(record["grads"], np.float32)
                if self._shard_map is not None:
                    # Ranges that migrated away between the record and
                    # the checkpointed map belong to another shard now
                    # — the cutover already moved (or erased) them.
                    own = self._shard_map.owns(self._shard_id, ids)
                    ids, grads = ids[own], grads[own]
                client = str(record.get("client") or "")
                seq = int(record.get("seq", -1))
                dup = bool(
                    client and seq >= 0
                    and seq <= self._applied_seq.get(
                        _client_key(client), -1
                    )
                )
                if ids.size and not dup:
                    prefault = getattr(table, "prefault_group", None)
                    if prefault is not None:
                        # Tiered tables: fault the rows (and slots)
                        # back hot before the apply — replay runs
                        # single-threaded at startup, so doing the
                        # disk read under the lock contends with
                        # nobody.
                        prefault(ids)
                    self._optimizer.apply_gradients(table, ids, grads)
                    self._table_versions[table_name] += 1
                    self._applied_at[table_name] = max(
                        self._applied_at.get(table_name, 0.0),
                        float(record.get("applied_at", 0.0)),
                    )
                    self._stat_pushed_rows += int(ids.size)
                    applied = True
                if client and seq >= 0 and not dup:
                    self._applied_seq[_client_key(client)] = seq
            self._push_count = version
        return applied

    def _checkpoint(self, version: int, blocking: bool = False) -> bool:
        """Capture/write split: ONE lock acquisition across the whole
        capture so rows, optimizer slots, step counters, and the seq
        map are snapshotted at the same version — but the handler pays
        only that capture (dirty rows when a delta is planned) plus an
        enqueue; serialization + IO run on the background writer.
        Backpressure is the writer's bounded queue: an interval
        trigger that finds it full skips (its rows stay dirty and ride
        the next interval) while the drain path (checkpoint_now)
        blocks for its turn. Returns whether a write was enqueued."""
        if not self._ckpt_trigger.acquire(blocking=blocking):
            # Another trigger is mid-plan/capture: this interval's
            # state is covered by the next one.
            return False
        try:
            # Checkpoint capture is system work, not the triggering
            # push's: re-tag so its time/faults never bill to the
            # client whose push crossed the interval.
            with wl_principal.pushed(purpose="checkpoint"):
                return self._checkpoint_locked(version, blocking)
        finally:
            self._ckpt_trigger.release()

    def _checkpoint_locked(self, version: int, blocking: bool) -> bool:
        if not blocking and self._ckpt_writer.busy:
            # Skip BEFORE planning or draining anything: the rows stay
            # dirty, the chain stays unbroken, and this interval's
            # state is covered by the next one.
            return False
        from elasticdl_tpu.checkpoint.saver import (
            CorruptCheckpointError,
            capture_tables,
            remark_dirty,
        )

        t0 = time.monotonic()
        plan, base, prev = self._ckpt_planner.plan(version)
        with self._lock:
            # ONE lock acquisition around the shared capture helper so
            # rows, slots, seq map, and step counters snapshot at the
            # same version. The shard map snapshots with them: a
            # restored shard must come back owning exactly the rows
            # the checkpoint holds (checkpoint meta, not a sidecar —
            # the pair is captured atomically).
            captured, dirty_ids = capture_tables(
                self.host_tables, delta=plan == "delta"
            )
            meta = {}
            if self._shard_map is not None:
                meta = {
                    "shard_map": self._shard_map.to_json(),
                    "shard_id": self._shard_id,
                }

        def remark():
            remark_dirty(self.host_tables, dirty_ids)

        def write():
            try:
                if plan == "delta":
                    if not self._saver.element_exists(prev):
                        # The predecessor this delta was planned
                        # against never became durable (its write
                        # failed ahead of us in the queue): writing
                        # would produce an unrestorable element while
                        # reporting success.
                        raise CorruptCheckpointError(
                            f"delta {version}: predecessor {prev} "
                            "never became durable; restarting chain"
                        )
                    self._saver.save_delta(
                        version, {}, captured, base, prev, meta=meta
                    )
                else:
                    self._saver.save(
                        version, {}, embeddings=captured, meta=meta
                    )
                log = self._push_log
                if log is not None:
                    # The version is durable (save/save_delta fsync +
                    # publish before returning) — sealed log segments
                    # it covers are now reclaimable. Truncation is
                    # fenced to THIS point by construction: it only
                    # ever runs on the writer thread, after the
                    # publish, against the chain element that covers
                    # the reclaimed records (saver chain meta).
                    log.truncate_through(int(version))
            except BaseException:
                # A failed write must put the drained rows back into
                # the dirty sets (or they vanish from every future
                # delta), and the chain must restart from a fresh base
                # (queued deltas linking through the failure are
                # unrestorable).
                remark()
                self._ckpt_planner.reset()
                raise

        def write_tagged():
            # The writer thread has no ambient principal; the
            # serialization + IO is checkpoint work.
            with wl_principal.pushed(purpose="checkpoint"):
                write()

        try:
            ok = self._ckpt_writer.submit(
                write_tagged, label=f"rows-v{version}-{plan}",
                block=blocking
            )
        except RuntimeError:
            # Writer closed under us (stop()/re-point racing a push
            # across a checkpoint interval): the push itself was
            # applied — put the drained rows back and skip the save
            # instead of failing the RPC.
            ok = False
        if not ok:
            remark()
            self._ckpt_planner.reset()
        self._m_stall.observe(time.monotonic() - t0)
        return ok

    def checkpoint_now(self) -> bool:
        """DURABLE checkpoint at the current push count — the
        graceful-drain write (SIGTERM grace period / scripted shard
        relaunch): rows pushed since the last interval save must not
        be lost to a planned restart. Unlike the interval trigger this
        blocks for its writer-queue turn AND flushes the writer before
        returning, so the caller observes a fully durable version —
        not a queued one. Returns False when no saver is configured."""
        if self._saver is None:
            return False
        # Land any queued write FIRST: the on-disk tip lags the async
        # writer queue, and comparing against the lagging tip would
        # re-capture and re-write state already on its way to disk —
        # a full-table blocking save exactly when the SIGTERM grace
        # budget is tightest.
        self._ckpt_writer.flush()
        with self._lock:
            version = self._push_count
        if self._saver.get_valid_latest_version() == version:
            return True
        ok = self._checkpoint(version, blocking=True)
        self._ckpt_writer.flush()
        return ok

    def _restore_latest(self):
        try:
            version, _, embeddings = self._saver.restore()
        except FileNotFoundError:
            return
        targets = self.host_tables
        missing = [n for n in targets if n not in embeddings]
        if missing:
            raise ValueError(
                "row-service checkpoint lacks payload for "
                f"{sorted(missing)}; different optimizer or tables?"
            )
        for name, view in targets.items():
            ids, rows = embeddings[name].to_arrays()
            if ids.size:
                view.set(ids, rows)
            if getattr(view, "supports_dirty_rows", False):
                # The refill marked every restored row dirty; disk
                # already holds them — the first post-restore delta
                # must not re-ship the whole table.
                view.clear_dirty()
        self._push_count = int(version)
        # The map rides the checkpoint meta: a relaunched shard comes
        # back routing/enforcing the epoch it was checkpointed under
        # (the authority's sync bumps it forward if the world moved).
        restored_meta = getattr(self._saver, "last_restored_meta", {})
        map_json = restored_meta.get("shard_map")
        if map_json and self._shard_map is None:
            self._shard_map = ShardMap.from_json(map_json)
            self._shard_id = int(restored_meta.get("shard_id", 0))
            self._m_map_version.set(float(self._shard_map.version))
        logger.info(
            "Row service restored version %d (%d tables)",
            version, len(targets),
        )

    # ---- lifecycle / checkpoint ---------------------------------------

    def start(self, addr: str = "localhost:0",
              tag: str = "", max_workers: int = 64,
              admission_limit: int = 0) -> "HostRowService":
        """``tag`` identifies this shard to chaos fault plans (e.g.
        ``rowservice/0``) — several shards of the same service can run
        in one test process and a plan must be able to stall just one.
        ``max_workers`` bounds handler concurrency (the reshard bench
        runs 1-worker shards to model per-shard capacity).
        ``admission_limit`` > 0 installs priority admission control
        (comm/overload.py) in front of every handler: bounded in-flight
        work, shed lowest-priority-first by principal purpose, so a
        stalled shard keeps serving reads while background work yields.
        0 (default) = no admission gate."""
        admission = None
        if admission_limit > 0:
            admission = wl_overload.AdmissionController(
                admission_limit, tag=tag or SERVICE_NAME,
            )
        self._server = RpcServer(
            addr, {SERVICE_NAME: self.handlers()}, tag=tag,
            max_workers=max_workers, admission=admission,
        ).start()
        logger.info("Row service on port %d", self._server.port)
        return self

    @property
    def port(self) -> int:
        return self._server.port

    def stop(self, grace: Optional[float] = None):
        if self._server is not None:
            # Drain in-flight handlers BEFORE closing the writer: a
            # push crossing a checkpoint interval during shutdown
            # must not hit a closed writer — its RPC would fail after
            # the grads were already applied.
            ev = self._server.stop(grace)
            if ev is not None:
                ev.wait((grace or 0) + 30.0)
        if self._push_log is not None:
            try:
                # Drain the group-commit queue (one final fsync covers
                # it) AFTER the handlers drained — SIGTERM is always
                # clean: every push the server ever acked (or even
                # just applied) is on disk before the process exits.
                self._push_log.close()
            except BaseException as exc:
                logger.error("push-log drain on stop failed: %s", exc)
        if self._ckpt_writer is not None:
            try:
                # Land any queued checkpoint write and retire the
                # writer thread before the process goes away; failures
                # are logged, not raised — stop() is a teardown path.
                self._ckpt_writer.close()
            except BaseException as exc:
                logger.error(
                    "checkpoint flush on stop failed: %s", exc
                )
        if self._replica_thread is not None:
            # Retire the replica refresher (drains after in-flight
            # handlers, so no push can re-arm it post-close).
            self._replica_queue.put(None)
            self._replica_thread.join(timeout=10.0)
            self._replica_thread = None
        for transport in self._transports.values():
            close = getattr(transport, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
        self._transports.clear()
        for table in self._tables.values():
            # Tiered tables: flush cold segments, stop the compactor,
            # and snapshot the index (the clean-close marker
            # tools/check_store.py audits against).
            group = getattr(table, "tier_group", None)
            if group is not None:
                try:
                    group.close()
                except BaseException as exc:
                    logger.error("cold-tier close failed: %s", exc)

    def wait(self):
        """Block until the server stops (process-main lifetime)."""
        self._server.wait()

    @property
    def host_tables(self) -> Dict:
        """Rows + optimizer slots + step counters + push-dedup map,
        lock-guarded — pass to CheckpointHook/restore_from_dir in the
        SERVER process (the reference checkpoints on the PS for the
        same reason, ps/servicer.py:242-257)."""
        from elasticdl_tpu.embedding.host_engine import (
            _LockedTable,
            locked_checkpoint_tables,
        )

        out = locked_checkpoint_tables(
            self._tables, self._optimizer, self._lock
        )
        out[SEQS_TABLE_NAME] = _LockedTable(_SeqTable(self), self._lock)
        return out


# CANCELLED is transient too: a server-initiated GOAWAY during service
# shutdown cancels in-flight calls, and every method here is safe to
# retry (pulls are idempotent; pushes are deduped by (client, seq)).
# RESOURCE_EXHAUSTED is an admission shed — the server said "later"
# and stamped a retry-after hint into the detail.
_TRANSIENT_CODES = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED",
                    "RESOURCE_EXHAUSTED")


class ReshardRedirect(Exception):
    """The shard does not own the requested buckets under its map —
    retry against the carried (newer) map. Nothing was applied."""

    def __init__(self, map_json):
        super().__init__("row home moved (stale shard-map epoch)")
        self.map_json = map_json


class ReshardFenced(Exception):
    """Writes to the range are briefly fenced (a migration is between
    its final delta and the cutover) — back off and retry."""


def _check_reshard(resp: dict):
    info = resp.get("reshard") if isinstance(resp, dict) else None
    if not info:
        return
    if info.get("reason") == "fenced":
        raise ReshardFenced()
    raise ReshardRedirect(info.get("map"))


def _call_with_retry(stub: RpcStub, method: str, retries: int,
                     backoff_secs: float, hedge=None, **fields):
    """Ride out a service relaunch (reference workers retry PS RPCs via
    the ≤64 minibatch retry + 3x300s channel waits; here a bounded
    decorrelated-jitter backoff on the row plane). Only transport-level
    codes retry — INTERNAL (handler bugs, bad table names) is permanent
    and surfaces immediately.

    Every retry spends a token from the shared ``RowService:rideout``
    budget (comm/overload.py): a patient ride-out of one relaunch
    sustains on the refill, but a fleet-wide retry storm is RATE-CAPPED
    instead of amplifying. A denied spend waits for refill rather than
    abandoning the ride-out — this loop's callers (migration pushes,
    replica refresh, the worker's row plane) hold correctness on
    eventually-getting-through, so the budget shapes their traffic
    instead of failing it. Admission sheds (RESOURCE_EXHAUSTED) carry a
    server retry-after hint that overrides the local backoff, and an
    expired ambient deadline (or a server expired-on-arrival verdict)
    ends the ride-out immediately: nobody is waiting for the answer.

    ``hedge`` (an ``overload.HedgeTimer``) turns each ATTEMPT of an
    idempotent read into a hedged pair — a second identical send after
    the tracked p99 delay, first response wins. Hedging rides inside
    the retry loop (one budgeted attempt = one hedged pair), never
    around it: two stacked ride-outs would double the worst case."""
    delay = backoff_secs
    budget = None
    if wl_overload.controls_enabled():
        budget = wl_overload.retry_budget_for("RowService:rideout")
    for attempt in range(retries + 1):
        try:
            if hedge is not None:
                t0 = time.monotonic()
                resp = wl_overload.hedged_call(
                    lambda: stub.call(method, **fields),
                    lambda: stub.call(method, **fields),
                    hedge.delay(), service=SERVICE_NAME, method=method,
                )
                hedge.observe(time.monotonic() - t0)
            else:
                resp = stub.call(method, **fields)
            if budget is not None:
                budget.on_success()
            return resp
        except RpcError as exc:
            if (exc.code not in _TRANSIENT_CODES
                    or attempt == retries
                    or EXPIRED_DETAIL in str(exc)
                    or wl_deadline.expired()):
                raise
            while budget is not None and not budget.try_spend():
                # Rate-capped, not abandoned: wait out the refill
                # (~1 token/s) unless the caller's deadline dies first.
                if wl_deadline.expired():
                    raise
                time.sleep(0.25)
            hint = None
            if exc.code == "RESOURCE_EXHAUSTED":
                hint = wl_overload.parse_retry_after(str(exc))
            sleep_for = delay if hint is None else hint
            left = wl_deadline.remaining()
            if left is not None:
                sleep_for = min(sleep_for, max(left, 0.0))
            logger.warning(
                "row service %s failed (attempt %d/%d); retrying in %.1fs",
                method, attempt + 1, retries, sleep_for,
            )
            time.sleep(sleep_for)
            # Fresh channel per retry: a channel whose connects were
            # refused while the service was (re)starting can wedge
            # permanently in-container; the ride-out window (~4 min)
            # must actually span a pod relaunch, not spin on a dead
            # channel (same fix as the worker's master ride-out, PR 5).
            stub.reconnect()
            delay = decorrelated_jitter(delay, base=backoff_secs,
                                        cap=30.0)


class _RemoteTable:
    """Table-like view pulling rows over RPC (get-only: writes happen
    server-side via the optimizer push). ``concurrent_safe``: the stub
    is thread-safe and the SERVER serializes row access, so the client
    engine lets pulls overlap in-flight pushes (reference Go PS
    concurrent serving, ps/server.go:162-192)."""

    concurrent_safe = True

    def __init__(self, stub: RpcStub, name: str, dim: int,
                 retries: int = 12, backoff_secs: float = 0.5,
                 hedge=None):
        self._stub = stub
        self.name = name
        self.dim = dim
        self._retries = retries
        self._backoff = backoff_secs
        # Shared overload.HedgeTimer (None = hedging off): idempotent
        # reads re-send after the fleet-p99 delay, first response wins.
        self._hedge = hedge
        # Wall-clock stamp of the service's last applied push as of
        # our newest pull (0.0 = never pushed / never pulled): what
        # serving's HostRowResolver turns into the
        # edl_tpu_row_freshness_seconds observation.
        self.last_applied_at = 0.0
        # Newest piggybacked shard-map epoch seen on this shard's
        # responses (0 until one rides a pull).
        self.last_map_version = 0

    def get(self, ids) -> np.ndarray:
        resp = _call_with_retry(
            self._stub, "pull_rows", self._retries, self._backoff,
            hedge=self._hedge,
            table=self.name, ids=np.asarray(ids, np.int64),
        )
        _check_reshard(resp)
        self.last_applied_at = float(resp.get("applied_at", 0.0) or 0.0)
        # Piggybacked epoch: lets the sharded wrapper notice replica-
        # only epochs (no ownership change = no REDIRECT ever fires).
        self.last_map_version = int(resp.get("map_version", 0) or 0)
        return np.asarray(resp["rows"], np.float32)

    def fetch_map(self) -> Optional[dict]:
        return _call_with_retry(
            self._stub, "get_shard_map", self._retries, self._backoff,
        ).get("map")

    def pull_replica(self, ids) -> dict:
        """Hot-id read from this shard's REPLICA store: per-id found
        mask (misses fall back to the home shard caller-side)."""
        resp = _call_with_retry(
            self._stub, "pull_replica_rows", self._retries,
            self._backoff, hedge=self._hedge, table=self.name,
            ids=np.asarray(ids, np.int64),
        )
        stamp = float(resp.get("applied_at", 0.0) or 0.0)
        if stamp > 0:
            self.last_applied_at = stamp
        return resp

    def export_ids(self, ids) -> np.ndarray:
        """Dense rows for explicit ids (trained rows over lazy init) —
        the map-routed export path; redirects like a pull."""
        resp = _call_with_retry(
            self._stub, "export_rows", self._retries, self._backoff,
            table=self.name, ids=np.asarray(ids, np.int64),
        )
        _check_reshard(resp)
        return np.asarray(resp["rows"], np.float32)

    def pull_version(self) -> int:
        """This table's monotonic update counter on the service — the
        hot-row cache's invalidation probe (serving/model_store.py).
        One small RPC, no row payload."""
        resp = _call_with_retry(
            self._stub, "table_versions", self._retries, self._backoff,
        )
        return int(resp["versions"][self.name])

    def export_range(self, lo: int, hi: int, stride: int = 1,
                     offset: int = 0) -> np.ndarray:
        """Dense rows ``lo+offset, +stride, ... < hi`` (trained rows
        over deterministic lazy init; see _export_rows)."""
        return np.asarray(_call_with_retry(
            self._stub, "export_rows", self._retries, self._backoff,
            table=self.name, lo=int(lo), hi=int(hi),
            stride=int(stride), offset=int(offset),
        )["rows"], np.float32)

    def export_dense(self, vocab: int, chunk: int = 65536) -> np.ndarray:
        """Serving-export materialization, served chunk-wise by the
        service (no live-table inflation; see _export_rows)."""
        parts = [
            self.export_range(lo, min(lo + chunk, vocab))
            for lo in range(0, int(vocab), chunk)
        ]
        return np.concatenate(parts, axis=0)


class _RemoteOptimizer:
    """Optimizer-like view pushing row grads over RPC; the server
    applies them (reference push_gradients semantics).

    Concurrent-safe via PER-THREAD (client, seq) streams: the server's
    exactly-once dedup drops any seq <= the client's last applied, so
    two threads sharing one stream would lose whichever concurrent push
    arrived second. Each pushing thread gets its own client id instead
    (the server is multi-client by design); within a thread, seqs stay
    monotone so lost-reply retries still dedup correctly."""

    concurrent_safe = True

    def __init__(self, stub: RpcStub, retries: int = 12,
                 backoff_secs: float = 0.5):
        import threading
        import uuid

        self._stub = stub
        self._retries = retries
        self._backoff = backoff_secs
        self._client_base = uuid.uuid4().hex
        self._local = threading.local()
        # Fresh-counter client ids (NOT thread idents — idents are
        # reused after a thread dies, which would resurrect a dead
        # stream with a reset seq and get every push deduped away).
        self._client_counter = itertools.count()
        self._counter_lock = threading.Lock()

    def apply_gradients(self, table, ids, grads):
        # (client, seq) lets the server drop a retried push whose first
        # attempt applied but whose reply was lost.
        if not hasattr(self._local, "client"):
            with self._counter_lock:
                n = next(self._client_counter)
            self._local.client = f"{self._client_base}-{n}"
            self._local.seq = 0
        self._local.seq += 1
        resp = _call_with_retry(
            self._stub, "push_row_grads", self._retries, self._backoff,
            table=table.name,
            ids=np.asarray(ids, np.int64),
            grads=np.asarray(grads, np.float32),
            client=self._local.client, seq=self._local.seq,
        )
        # A redirected/fenced push applied NOTHING server-side; the
        # burned seq is harmless (dedup only needs monotonicity) and
        # the caller re-routes under the newer map.
        _check_reshard(resp)
        return table


_RESHARD_ATTEMPTS = 20
_FENCE_BACKOFF_SECS = 0.02


def _run_jobs(pool, jobs):
    """Run job thunks, fanned on the pool only when there is real
    fan-out (a single-target wave — the common case for small pulls
    and for single-shard fleets — stays inline, no thread hop).
    Pool threads do not inherit thread-locals, so each job is bound to
    the submitting thread's ambient deadline (comm/deadline.py): a
    wave fanned out under one 500 ms budget spends ONE budget across
    every shard leg, and expiry is visible inside each leg's stub."""
    if pool is None or len(jobs) == 1:
        for job in jobs:
            job()
        return
    futures = [pool.submit(wl_deadline.bind(job)) for job in jobs]
    for f in futures:
        f.result()


class _ShardRegistry:
    """Client-side view of the live shard FLEET: one stub / remote
    table / remote optimizer per shard address, created lazily — maps
    learned via REDIRECT can name addresses the engine was never
    configured with (a split's fresh target), and the registry is
    where they materialize. Shared by every table and the optimizer of
    one engine, plus the fan-out pool."""

    def __init__(self, retries: int, backoff_secs: float,
                 hedge_reads: bool = False):
        self._retries = retries
        self._backoff = backoff_secs
        self._lock = threading.Lock()
        self._stubs: Dict[str, RpcStub] = {}
        self._tables: Dict = {}
        self._optimizers: Dict = {}
        self._pool = None
        # Tail-tolerant hedging for idempotent reads (opt-in): one
        # shared p99 tracker for the whole fleet — the hedge delay
        # models "this read is slower than the fleet's tail", not one
        # shard's own (a stalled shard must not teach itself that
        # stalls are normal).
        self._hedge = (wl_overload.HedgeTimer()
                       if hedge_reads else None)

    def stub(self, addr: str) -> RpcStub:
        with self._lock:
            stub = self._stubs.get(addr)
            if stub is None:
                # max_retries=0: _call_with_retry owns the (much
                # longer) retry budget; stacking the stub's own
                # backoff under it would multiply attempts.
                stub = RpcStub(addr, SERVICE_NAME, max_retries=0)
                self._stubs[addr] = stub
            return stub

    def table(self, addr: str, name: str, dim: int) -> "_RemoteTable":
        key = (addr, name)
        with self._lock:
            table = self._tables.get(key)
        if table is None:
            table = _RemoteTable(
                self.stub(addr), name, dim, self._retries,
                self._backoff, hedge=self._hedge,
            )
            with self._lock:
                table = self._tables.setdefault(key, table)
        return table

    def tables_named(self, name: str):
        with self._lock:
            return [t for (_a, n), t in self._tables.items()
                    if n == name]

    def optimizer(self, addr: str) -> "_RemoteOptimizer":
        with self._lock:
            opt = self._optimizers.get(addr)
        if opt is None:
            # Build outside the lock (stub() takes it; non-reentrant).
            opt = _RemoteOptimizer(
                self.stub(addr), self._retries, self._backoff
            )
            with self._lock:
                opt = self._optimizers.setdefault(addr, opt)
        return opt

    @property
    def pool(self):
        with self._lock:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._pool = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="row-shard",
                )
            return self._pool


class _ShardedTable:
    """Client-side scatter/gather over the live row-service fleet,
    routed through the shared ``ClientShardMap``: a row's home is
    whatever shard owns its BUCKET under the newest map epoch this
    client has seen — no shard-count arithmetic anywhere. A stale
    epoch surfaces as a REDIRECT from the shard that stopped owning
    the buckets; the redirect carries the newer map, the shared holder
    adopts it (version-monotonic), and only the unresolved ids retry —
    sub-pulls that already landed on their correct homes never
    re-execute. Hot ids with replica sets fan reads across home +
    replicas (round-robin); a replica miss (refresh not landed) falls
    back to the home, and writes never touch replicas. Fan-out runs on
    the registry's pool so N shards' line rates aggregate WHEN the
    servers are the binding constraint (each on its own cores/NIC);
    on a single host, sharding splits requests into smaller sub-RPCs —
    use shards for capacity partitioning and skew isolation, not
    single-host throughput (ROW_SERVICE_SCALING.json)."""

    concurrent_safe = True

    def __init__(self, name: str, dim: int, cmap: ClientShardMap,
                 registry: _ShardRegistry):
        self.name = name
        self.dim = int(dim)
        self._cmap = cmap
        self._reg = registry
        self._rr = itertools.count()

    def _remote(self, m: ShardMap, shard: int) -> "_RemoteTable":
        return self._reg.table(m.shards[shard], self.name, self.dim)

    def get(self, ids) -> np.ndarray:
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).ravel())
        out = np.empty((ids.size, self.dim), np.float32)
        pending = np.arange(ids.size, dtype=np.intp)
        force_home = np.zeros(ids.size, bool)
        delay = _FENCE_BACKOFF_SECS
        for _attempt in range(_RESHARD_ATTEMPTS):
            m = self._cmap.get()
            sub = ids[pending]
            home = m.home_of_ids(sub)
            target = home.copy()
            via_replica = np.zeros(pending.size, bool)
            per = m.replicas.get(self.name)
            if per:
                rr = next(self._rr)
                for k in range(pending.size):
                    if force_home[pending[k]]:
                        continue
                    reps = per.get(int(sub[k]))
                    if reps:
                        cands = (int(home[k]),) + tuple(
                            s for s in reps if s != home[k]
                        )
                        pick = cands[rr % len(cands)]
                        if pick != home[k]:
                            target[k] = pick
                            via_replica[k] = True
            outcome = {"map": None, "unresolved": [], "refresh": None}
            olock = threading.Lock()
            jobs = []
            for s in sorted(set(target.tolist())):
                for is_rep in (False, True):
                    mask = (target == s) & (via_replica == is_rep)
                    if mask.any():
                        jobs.append(self._pull_job(
                            m, int(s), is_rep, pending[mask], ids,
                            out, outcome, olock, force_home,
                        ))
            _run_jobs(
                self._reg.pool if len(jobs) > 1 else None, jobs
            )
            if outcome["refresh"] is not None:
                # A shard piggybacked a NEWER epoch than ours without
                # redirecting (replica-only change): fetch it so the
                # next pulls route through the new replica sets.
                try:
                    fresh = outcome["refresh"].fetch_map()
                    if fresh:
                        self._cmap.update(fresh)
                except RpcError:
                    pass  # opportunistic; next pull retries
            if outcome["map"] is not None:
                progressed = self._cmap.update(outcome["map"])
            else:
                progressed = bool(
                    force_home[np.asarray(outcome["unresolved"],
                                          np.intp)].any()
                ) if outcome["unresolved"] else False
            if not outcome["unresolved"]:
                return out
            pending = np.asarray(sorted(outcome["unresolved"]),
                                 np.intp)
            if not progressed:
                # No newer map and no replica fallback to try: wait
                # out whatever transition the server is mid-way
                # through before re-asking.
                time.sleep(delay)
                delay = min(delay * 2, 4.0)
        raise RuntimeError(
            f"row pulls for table {self.name!r} kept redirecting "
            f"after {_RESHARD_ATTEMPTS} attempts (shard-map churn?)"
        )

    def _pull_job(self, m, shard, is_replica, positions, ids, out,
                  outcome, olock, force_home):
        def job():
            remote = self._remote(m, shard)
            try:
                if is_replica:
                    resp = remote.pull_replica(ids[positions])
                    found = np.asarray(resp["found"], bool)
                    rows = np.asarray(resp["rows"], np.float32)
                    out[positions[found]] = rows[found]
                    miss = positions[~found]
                    if miss.size:
                        with olock:
                            outcome["unresolved"].extend(
                                miss.tolist()
                            )
                            force_home[miss] = True
                else:
                    out[positions] = remote.get(ids[positions])
                    if remote.last_map_version > m.version:
                        with olock:
                            outcome["refresh"] = remote
            except ReshardRedirect as exc:
                with olock:
                    cur = outcome["map"]
                    if cur is None or (
                        exc.map_json
                        and exc.map_json["version"] > cur["version"]
                    ):
                        outcome["map"] = exc.map_json
                    outcome["unresolved"].extend(positions.tolist())
            except RpcError:
                if not is_replica:
                    raise
                # A dead replica must not fail the read — fall back
                # to the authoritative home.
                with olock:
                    outcome["unresolved"].extend(positions.tolist())
                    force_home[positions] = True
        return job

    def pull_version(self) -> int:
        """Sum of the fleet's counters under the current map: any
        shard applying a push changes the sum, and counters only grow
        per-process, so an unchanged sum means no shard changed. (A
        shard RESTART resets its counter and can lower the sum —
        still a change unless every other shard's growth exactly
        cancels it, which the cache's != comparison treats identically
        to growth anyway.)"""
        m = self._cmap.get()
        return sum(
            self._remote(m, s).pull_version()
            for s in range(len(m.shards))
        )

    @property
    def last_applied_at(self) -> float:
        """OLDEST applied-push stamp across shards that have reported
        one — the conservative freshness bound. max() would let three
        healthy shards mask one whose push pipeline stalled, which is
        exactly the regime the freshness SLO exists to catch; shards
        that never saw a push (stamp 0) are excluded rather than
        pinning the metric to 'never'. Replica reads feed the same
        stamps (their copies carry the home's applied-at)."""
        stamps = [
            t.last_applied_at
            for t in self._reg.tables_named(self.name)
            if t.last_applied_at > 0
        ]
        return min(stamps) if stamps else 0.0

    def export_dense(self, vocab: int, chunk: int = 65536) -> np.ndarray:
        """Each shard exports ONLY the ids it owns under the current
        map (explicit-id ``export_rows``), merged client-side — the
        total transfer is one table, not N (untrained ids fall back to
        the home shard's deterministic lazy init). Redirects retry
        like pulls, so an export racing a cutover stays correct."""
        parts = []
        for lo in range(0, int(vocab), chunk):
            want = np.arange(lo, min(lo + chunk, int(vocab)),
                             dtype=np.int64)
            out = np.empty((want.size, self.dim), np.float32)
            pending = np.arange(want.size, dtype=np.intp)
            for _attempt in range(_RESHARD_ATTEMPTS):
                m = self._cmap.get()
                home = m.home_of_ids(want[pending])
                outcome = {"map": None, "unresolved": []}
                olock = threading.Lock()
                jobs = [
                    self._export_job(m, int(s), pending[home == s],
                                     want, out, outcome, olock)
                    for s in sorted(set(home.tolist()))
                ]
                _run_jobs(
                    self._reg.pool if len(jobs) > 1 else None, jobs
                )
                if outcome["map"] is not None:
                    self._cmap.update(outcome["map"])
                if not outcome["unresolved"]:
                    break
                pending = np.asarray(sorted(outcome["unresolved"]),
                                     np.intp)
            else:
                raise RuntimeError(
                    f"export for table {self.name!r} kept redirecting"
                )
            parts.append(out)
        return np.concatenate(parts, axis=0)

    def _export_job(self, m, shard, positions, want, out, outcome,
                    olock):
        def job():
            try:
                out[positions] = self._remote(m, shard).export_ids(
                    want[positions]
                )
            except ReshardRedirect as exc:
                with olock:
                    cur = outcome["map"]
                    if cur is None or (
                        exc.map_json
                        and exc.map_json["version"] > cur["version"]
                    ):
                        outcome["map"] = exc.map_json
                    outcome["unresolved"].extend(positions.tolist())
        return job


class _ShardedOptimizer:
    """Push scatter over the fleet, routed through the same shared
    map: each shard receives only the row grads it HOMES (writes are
    never fanned to replicas — single-home writes keep the exactly-
    once dedup and the replica-refresh fan-out trivially correct).
    Each sub-push either fully applies or fully rejects (the server
    checks ownership/fences before touching anything), so a redirect
    retries only its own ids under the newer map — no double-apply."""

    concurrent_safe = True

    def __init__(self, cmap: ClientShardMap, registry: _ShardRegistry):
        self._cmap = cmap
        self._reg = registry

    def apply_gradients(self, table, ids, grads):
        ids = np.ascontiguousarray(np.asarray(ids, np.int64).ravel())
        grads = np.asarray(grads, np.float32)
        pending = np.arange(ids.size, dtype=np.intp)
        delay = _FENCE_BACKOFF_SECS
        for _attempt in range(_RESHARD_ATTEMPTS):
            m = self._cmap.get()
            home = m.home_of_ids(ids[pending])
            outcome = {"map": None, "fenced": False, "unresolved": []}
            olock = threading.Lock()
            jobs = [
                self._push_job(m, int(s), table, ids, grads,
                               pending[home == s], outcome, olock)
                for s in sorted(set(home.tolist()))
            ]
            _run_jobs(
                self._reg.pool if len(jobs) > 1 else None, jobs
            )
            if outcome["map"] is not None:
                progressed = self._cmap.update(outcome["map"])
            else:
                progressed = False
            if not outcome["unresolved"]:
                return table
            pending = np.asarray(sorted(outcome["unresolved"]),
                                 np.intp)
            if outcome["fenced"] or not progressed:
                # Fence windows are short (final migration delta →
                # cutover); ride them out with bounded backoff.
                time.sleep(delay)
                delay = min(delay * 2, 4.0)
        raise RuntimeError(
            "row pushes kept redirecting/fenced after "
            f"{_RESHARD_ATTEMPTS} attempts (shard-map churn?)"
        )

    def _push_job(self, m, shard, table, ids, grads, positions,
                  outcome, olock):
        def job():
            opt = self._reg.optimizer(m.shards[shard])
            try:
                opt.apply_gradients(
                    table, ids[positions], grads[positions]
                )
            except ReshardRedirect as exc:
                with olock:
                    cur = outcome["map"]
                    if cur is None or (
                        exc.map_json
                        and exc.map_json["version"] > cur["version"]
                    ):
                        outcome["map"] = exc.map_json
                    outcome["unresolved"].extend(positions.tolist())
            except ReshardFenced:
                with olock:
                    outcome["fenced"] = True
                    outcome["unresolved"].extend(positions.tolist())
        return job


def make_remote_engine(
    addr: str, id_keys: Dict[str, str],
    retries: int = 12, backoff_secs: float = 0.5,
    table_fanout: bool = True,
    hedge_reads: bool = False,
) -> HostEmbeddingEngine:
    """Client-side engine over running `HostRowService` shard(s).

    ``addr`` is one address or a comma list — the BOOTSTRAP fleet.
    Routing goes through a versioned ``ShardMap``
    (embedding/shard_map.py): the engine adopts the newest map any
    listed shard has installed (a resharded fleet), or builds the
    bootstrap map over the listed addresses (static topology — the
    servers then never redirect and behavior matches the classic
    N-shard deployment). The topology can change UNDER a live engine:
    a split/merge cutover surfaces as a retryable REDIRECT carrying
    the newer map, and shard addresses the engine was never configured
    with materialize lazily in its registry. Pulls/pushes retry with
    bounded backoff across a shard relaunch; the default budget (0.5s
    doubling, capped 30s, 12 retries ≈ 4 minutes) spans a real pod
    relaunch like the reference workers' 3x300s channel waits.
    ``hedge_reads`` opts idempotent pulls/replica reads into
    tail-tolerant hedging (comm/overload.py): re-send after the
    fleet-p99 delay, first response wins."""
    addrs = [a.strip() for a in addr.split(",") if a.strip()]
    if not addrs:
        raise ValueError("empty row-service address")
    registry = _ShardRegistry(retries, backoff_secs,
                              hedge_reads=hedge_reads)
    stubs = [registry.stub(a) for a in addrs]
    infos = [
        _call_with_retry(stub, "table_info", retries, backoff_secs)[
            "tables"
        ]
        for stub in stubs
    ]
    for a, info in zip(addrs[1:], infos[1:]):
        if info != infos[0]:
            raise ValueError(
                f"row-service shard {a} serves different tables "
                f"({sorted(info)}) than shard {addrs[0]} "
                f"({sorted(infos[0])}); all shards must run the same "
                "model module"
            )
    best = None
    for stub in stubs:
        try:
            resp = _call_with_retry(
                stub, "get_shard_map", retries, backoff_secs
            )
        except RpcError:
            continue
        map_json = resp.get("map")
        if map_json and (
            best is None or map_json["version"] > best["version"]
        ):
            best = map_json
    cmap = ClientShardMap(
        ShardMap.from_json(best) if best is not None
        else ShardMap.bootstrap(addrs)
    )
    tables = {
        name: _ShardedTable(name, meta["dim"], cmap, registry)
        for name, meta in infos[0].items()
    }
    optimizer = _ShardedOptimizer(cmap, registry)
    engine = HostEmbeddingEngine(
        tables, optimizer, id_keys=id_keys, table_fanout=table_fanout
    )
    engine.remote = True  # server owns checkpointing (see HostStepRunner)
    engine.shard_map = cmap  # routing-epoch introspection (tests)
    return engine


# Placement scheme recorded in shard_layout.json: bucket-range shard
# maps (embedding/shard_map.py). Markers without the field predate the
# map (the id%N era) — multi-shard checkpoints from that era cannot be
# restored under map routing without an offline repartition.
PLACEMENT_SCHEME = "bucket-range-v1"


def validate_shard_layout(checkpoint_dir: str, shard: int,
                          num_shards: int):
    """Refuse to restore a checkpoint written under a DIFFERENT static
    shard layout or placement scheme: restoring rows onto a shard that
    no longer homes them would silently re-lazy-init every moved row
    (trained embeddings reset with no error). A ``shard_layout.json``
    marker records the layout + placement; a checkpoint dir holding
    versions but no marker is treated as num_shards=1 (the pre-shard
    layout, placement-compatible by construction). LIVE topology
    changes are exempt — they move bytes before flipping the map and
    the map rides the checkpoint meta; this guard is for the static
    ``--num_shards`` config changing across a relaunch."""
    import json
    import os

    marker = os.path.join(checkpoint_dir, "shard_layout.json")
    if os.path.exists(marker):
        with open(marker) as fh:
            recorded = json.load(fh)
    else:
        from elasticdl_tpu.checkpoint.saver import CheckpointSaver

        has_versions = bool(
            os.path.isdir(checkpoint_dir)
            and CheckpointSaver(checkpoint_dir).list_versions()
        )
        if not has_versions:
            os.makedirs(checkpoint_dir, exist_ok=True)
            with open(marker, "w") as fh:
                json.dump({"shard": shard, "num_shards": num_shards,
                           "placement": PLACEMENT_SCHEME}, fh)
            return
        recorded = {"shard": 0, "num_shards": 1}  # pre-shard layout
    recorded_placement = recorded.get(
        "placement",
        # Single-shard layouts are identical under every scheme (one
        # shard owns everything); multi-shard markers without the
        # field are id%N-era placements.
        PLACEMENT_SCHEME if int(recorded.get("num_shards", 1)) == 1
        else "id-mod-n",
    )
    if (
        int(recorded.get("num_shards", 1)) != num_shards
        or int(recorded.get("shard", 0)) != shard
        or recorded_placement != PLACEMENT_SCHEME
    ):
        raise SystemExit(
            f"checkpoint {checkpoint_dir} was written as shard "
            f"{recorded.get('shard', 0)}/{recorded.get('num_shards', 1)}"
            f" (placement {recorded_placement}) but this process is "
            f"shard {shard}/{num_shards} (placement "
            f"{PLACEMENT_SCHEME}); changing the static shard layout "
            "across a restore would silently lose the rows whose home "
            "moved. Start a fresh checkpoint dir (or repartition "
            "offline via checkpoint.saver), or grow the fleet LIVE "
            "through the shard-map controller instead "
            "(master/row_reshard.py)."
        )


def main(argv=None):
    """Process entry: ``python -m elasticdl_tpu.embedding.row_service
    --model_zoo ... --model_def ... [--addr :6100] [--checkpoint_dir ...]``
    — the zoo module supplies ``make_row_service()`` (the deployment
    unit the reference's PS pod mapped to). ``--shard_id/--num_shards``
    record the shard layout so a relaunch with a different
    --num_row_service_shards fails loudly instead of silently losing
    rows (see validate_shard_layout)."""
    import argparse

    from elasticdl_tpu.core.model_spec import load_model_zoo_module

    parser = argparse.ArgumentParser("elasticdl_tpu-row-service")
    parser.add_argument("--model_zoo", required=True)
    parser.add_argument("--model_def", required=True)
    parser.add_argument("--addr", default="[::]:6100")
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--checkpoint_steps", type=int, default=0)
    parser.add_argument("--keep_checkpoint_max", type=int, default=3)
    parser.add_argument("--checkpoint_delta_chain", type=int, default=8,
                        help="Max incremental delta checkpoints riding "
                             "one full base before a save compacts "
                             "into a fresh base; 0 = full snapshots "
                             "only (docs/fault_tolerance.md)")
    parser.add_argument("--checkpoint_sync", action="store_true",
                        help="Write checkpoints inline on the push "
                             "handler instead of the background "
                             "writer (debugging / deterministic "
                             "schedules)")
    parser.add_argument("--push_log_dir", default="",
                        help="Write-ahead push log directory "
                             "(storage/pushlog.py): every applied "
                             "push is group-committed to disk and "
                             "replayed on relaunch, so acked pushes "
                             "survive SIGKILL independently of "
                             "checkpoint cadence "
                             "(docs/fault_tolerance.md 'Zero-RPO row "
                             "plane'). Empty (default) = off")
    parser.add_argument("--push_log_group_ms", type=float, default=2.0,
                        help="Group-commit window: one fsync covers "
                             "every push landing within it")
    parser.add_argument("--push_log_ack", default="durable",
                        choices=["durable", "applied"],
                        help="durable (default): push replies wait "
                             "for the covering fsync (RPO=0). "
                             "applied: reply after the in-memory "
                             "apply (RPO = one group window)")
    parser.add_argument("--push_durable_wait_secs", type=float,
                        default=60.0,
                        help="Ceiling on the durable-ack fsync wait "
                             "in the push path; a propagated request "
                             "deadline shrinks it per-push. Abandoned "
                             "waits count in "
                             "row_push_durable_wait_timeouts_total")
    parser.add_argument("--admission_limit", type=int, default=0,
                        help="Priority admission control: bound on "
                             "concurrently admitted handlers; beyond "
                             "it, requests shed lowest-priority-first "
                             "by principal purpose with a retryable "
                             "RESOURCE_EXHAUSTED + retry-after hint "
                             "(docs/fault_tolerance.md 'Graceful "
                             "degradation'). 0 (default) = off")
    parser.add_argument("--hot_budget_rows", type=int, default=0,
                        help="Tiered storage: max rows/table resident "
                             "in the hot in-memory arena; colder rows "
                             "spill to CRC-framed disk segments "
                             "(docs/sparse_path.md 'Tiered storage'). "
                             "0 (default) = everything in memory")
    parser.add_argument("--cold_dir", default="",
                        help="Cold-tier segment directory (spill "
                             "cache, wiped on start — checkpoints own "
                             "durability). Default: "
                             "<checkpoint_dir>_cold, or a tempdir "
                             "when no checkpoint dir is set")
    parser.add_argument("--cold_segment_mb", type=int, default=8,
                        help="Cold-tier segment file size bound (MB)")
    parser.add_argument("--cold_compact_live_fraction", type=float,
                        default=0.5,
                        help="Compact a cold segment when its live "
                             "record fraction drops below this")
    parser.add_argument("--shard_id", type=int, default=0)
    parser.add_argument("--num_shards", type=int, default=1)
    parser.add_argument("--metrics_port", type=int, default=-1,
                        help="Serve this process's own registry "
                             "(row_service_* pull/push metrics) as "
                             "Prometheus /metrics; 0 = ephemeral, "
                             "-1 (default) = disabled")
    parser.add_argument("--flight_recorder", type=int, default=0,
                        help="Install a span flight recorder of this "
                             "many entries (served on /traces next to "
                             "/metrics; tools/dump_metrics.py "
                             "--traces); 0 (default) = tracing off")
    parser.add_argument("--profile_hz", type=float, default=0.0,
                        help="Always-on sampling profiler rate (Hz); "
                             "flame windows serve on /profile next to "
                             "/metrics and piggyback to the master "
                             "with --master_addr. 0 (default) = off")
    parser.add_argument("--profile_window_secs", type=float,
                        default=10.0,
                        help="Sampling-profiler window length (secs)")
    parser.add_argument("--master_addr", default="",
                        help="Report this shard's registry snapshot "
                             "(plus spans/profile windows) into the "
                             "master's cluster view every "
                             "--metrics_report_secs, keyed "
                             "rowservice-<shard_id> — how master-side "
                             "SLO rules and incident bundles see the "
                             "row plane. Empty (default) = standalone")
    parser.add_argument("--metrics_report_secs", type=float,
                        default=15.0,
                        help="Master telemetry report interval (with "
                             "--master_addr)")
    args = parser.parse_args(argv)

    module, _ = load_model_zoo_module(args.model_zoo, args.model_def)
    factory = getattr(module, "make_row_service", None)
    if factory is None:
        raise SystemExit(
            f"{args.model_def}: module defines no make_row_service()"
        )
    service = factory()
    if args.hot_budget_rows > 0:
        # BEFORE checkpoint config: restore refills stream through the
        # tier (the budget holds from the first row), and dirty
        # tracking lands on the tier wrapper.
        cold_dir = args.cold_dir
        if not cold_dir:
            if args.checkpoint_dir:
                cold_dir = args.checkpoint_dir.rstrip("/") + "_cold"
            else:
                import tempfile

                cold_dir = tempfile.mkdtemp(prefix="edl_cold_")
        service.configure_tiering(
            cold_dir, args.hot_budget_rows,
            segment_max_bytes=args.cold_segment_mb << 20,
            compact_live_fraction=args.cold_compact_live_fraction,
        )
    if args.checkpoint_dir:
        validate_shard_layout(
            args.checkpoint_dir, args.shard_id, args.num_shards
        )
        service.configure_checkpoint(
            args.checkpoint_dir, args.checkpoint_steps,
            args.keep_checkpoint_max,
            delta_chain_max=args.checkpoint_delta_chain,
            async_write=not args.checkpoint_sync,
        )
    if args.push_log_dir:
        # AFTER checkpoint config: restore the chain first, then
        # replay the log tail through the normal apply path.
        service.configure_push_log(
            args.push_log_dir, group_ms=args.push_log_group_ms,
            ack=args.push_log_ack,
        )
    service.configure_push_durable_wait(args.push_durable_wait_secs)
    service.start(args.addr, tag=f"rowservice/{args.shard_id}",
                  admission_limit=args.admission_limit)
    logger.info("Row service serving on %s", args.addr)
    import signal

    def _graceful(_sig, _frame):
        # Planned eviction: drain handlers, land a durable checkpoint,
        # and flush the push-log queue — SIGTERM is always clean (a
        # SIGKILL loses at most unacked/applied-ack records inside one
        # group window; durable acks lose nothing either way).
        logger.warning(
            "SIGTERM: draining row service (checkpoint + push-log "
            "flush)"
        )
        try:
            service.checkpoint_now()
        except BaseException as exc:
            logger.error("drain checkpoint failed: %s", exc)
        service.stop(grace=5.0)

    try:
        signal.signal(signal.SIGTERM, _graceful)
    except ValueError:
        pass  # not the main thread (embedded use)
    if args.flight_recorder > 0:
        tracing.set_process_role("rowservice", str(args.shard_id))
        tracing.install_recorder(
            tracing.FlightRecorder(args.flight_recorder)
        )
    from elasticdl_tpu.observability import profiler as profiler_mod

    profiler_mod.maybe_start_from_args(
        args, "rowservice", str(args.shard_id)
    )
    if args.metrics_port >= 0:
        # A row-service pod reports to no master by default, so its
        # registry (row_service_* counters/latency) is scrapeable
        # directly — without this its metrics would be write-only.
        # /traces serves the flight recorder the same way when one is
        # installed, and /profile the sampling profiler's own flame
        # windows (tools/dump_metrics.py --profile).
        from elasticdl_tpu.observability import (
            MetricsHTTPServer,
            default_registry,
            render_prometheus,
        )

        def _local_profile(params: dict):
            prof = profiler_mod.profiler()
            if prof is None:
                return {"error": "profiler off (--profile_hz 0)"}
            merged = profiler_mod.merge_windows(
                prof.snapshot_windows(include_open=True)
            )
            if merged is None:
                return {"error": "no samples yet"}
            return {
                "component": f"rowservice-{args.shard_id}",
                "window": merged,
                "folded": profiler_mod.folded_text(merged["samples"]),
                "pprof": profiler_mod.pprof_json(merged),
            }

        MetricsHTTPServer(
            lambda: render_prometheus(default_registry().snapshot()),
            port=args.metrics_port,
            traces=lambda: {"spans": tracing.recorder_spans()},
            json_routes={"/profile": _local_profile},
            render_openmetrics=lambda: render_prometheus(
                default_registry().snapshot(), exemplars=True
            ),
        ).start()
    if args.master_addr:
        from elasticdl_tpu.observability.reporter import (
            ComponentMetricsReporter,
        )

        ComponentMetricsReporter(
            args.master_addr, "rowservice", args.shard_id,
            interval_secs=args.metrics_report_secs,
        ).start()
    service.wait()


if __name__ == "__main__":
    main()
