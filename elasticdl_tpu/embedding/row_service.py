"""Shared row service: the host tier served over RPC.

The one parameter-server role the mesh cannot absorb: several *worker
processes* training one >HBM embedding table need a shared row plane.
The reference serves it with the Pserver gRPC service
(``pull_embedding_vectors`` / ``push_gradients``,
``elasticdl/proto/elasticdl.proto:137-145``; Go impl
``pkg/ps/server.go:149,162``). Here the same contract rides the
framework's msgpack RPC (comm/rpc.py):

- **Server** (`HostRowService`): owns the tables (Python or C++ row
  store) and the row optimizer; applies pushed gradients under a lock
  (async-PS semantics — concurrent workers interleave, reference
  async_sgd.md); exposes `host_tables` so the server-side process
  checkpoints rows + optimizer slots exactly like a local engine.
- **Client** (`make_remote_engine`): a `HostEmbeddingEngine` whose
  tables pull rows over RPC and whose "optimizer" pushes gradients
  back. `HostStepRunner` works unchanged on top; its `host_tables` is
  None (the server owns checkpointing).

Worker-side dedup/bucketing still applies: each pull moves only the
batch's unique rows, mirroring the reference worker's dedup before
push (worker.py:487-599).
"""

import itertools
import threading
import time
from typing import Dict, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.comm.rpc import RpcError, RpcServer, RpcStub
from elasticdl_tpu.embedding.host_engine import HostEmbeddingEngine
from elasticdl_tpu.observability import tracing

logger = get_logger("row_service")

SERVICE_NAME = "RowService"
SEQS_TABLE_NAME = "__row_service_seqs__"


def _client_key(client: str) -> int:
    """Stable 63-bit key for a client id string (dict/table row id)."""
    import hashlib

    return int.from_bytes(
        hashlib.blake2b(client.encode("utf-8"), digest_size=8).digest(),
        "big",
    ) >> 1


class _SeqTable:
    """Checkpoint adapter persisting the push-dedup map ({client key:
    last applied seq}) as a dim-1 table, closing the
    die-between-checkpoint-and-reply double-apply window: a relaunch
    restores the map with the rows it belongs to."""

    dim = 1

    def __init__(self, service: "HostRowService"):
        self._service = service

    def to_arrays(self):
        items = sorted(self._service._applied_seq.items())
        ids = np.array([k for k, _ in items], np.int64)
        rows = np.array(
            [[v] for _, v in items], np.float64
        ).reshape(-1, 1)
        return ids, rows

    def set(self, ids, values):
        values = np.asarray(values).reshape(len(list(ids)), -1)
        for key, row in zip(ids, values):
            self._service._applied_seq[int(key)] = int(round(float(row[0])))


class HostRowService:
    """Server side of the shared host tier.

    ``checkpoint_dir``/``checkpoint_steps``: save rows + optimizer
    state every N gradient pushes — the reference PS checkpoints inside
    ``push_gradients`` every checkpoint_steps versions
    (ps/servicer.py:242-257, pkg/ps/server.go:114-127); the push count
    is the service's version. At start the newest valid version is
    restored, so a relaunched service pod resumes lossless (reference
    PS relaunch + checkpoint-restore semantics).
    """

    def __init__(self, tables: Dict, optimizer, checkpoint_dir: str = "",
                 checkpoint_steps: int = 0, keep_max: int = 3,
                 metrics_registry=None):
        self._tables = tables
        self._optimizer = optimizer
        # Telemetry: served row traffic + handler latency (the row
        # plane's pressure gauges; scrape the serving process).
        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self._m_pull = registry.histogram(
            "row_service_pull_seconds", "pull_rows handler latency",
        )
        self._m_push = registry.histogram(
            "row_service_push_seconds", "push_row_grads handler latency",
        )
        self._m_pulled = registry.counter(
            "row_service_pulled_rows_total", "Rows served to pulls",
        )
        self._m_pushed = registry.counter(
            "row_service_pushed_rows_total",
            "Row gradients applied from pushes",
        )
        self._m_dup = registry.counter(
            "row_service_duplicate_pushes_total",
            "Retried pushes dropped by (client, seq) dedup",
        )
        self._m_stall = registry.histogram(
            "checkpoint_stall_seconds",
            "Step/push-path time spent capturing + enqueuing a "
            "checkpoint (the part the hot path actually waits on)",
        )
        self._lock = threading.RLock()
        self._server: Optional[RpcServer] = None
        self._push_count = 0
        # Per-table monotonic update counter: bumped under the lock on
        # every APPLIED push (duplicates don't count — they changed
        # nothing). Serving-side hot-row caches poll this via the
        # ``table_versions`` RPC: an unchanged counter proves every
        # cached row is still current, a changed one invalidates the
        # table's cache entries. Not persisted: a restarted service
        # reports 0 again, and caches compare by != (not <), so the
        # reset reads as "changed" and flushes them — safe.
        self._table_versions: Dict[str, int] = {
            name: 0 for name in tables
        }
        # Wall-clock stamp of the last APPLIED push per table — the
        # ROADMAP's push-to-servable freshness signal: pulls return it,
        # and serving-side readers observe ``now - applied_at`` as
        # ``edl_tpu_row_freshness_seconds`` (how stale the rows a
        # prediction just used could be). Wall clock on purpose: the
        # reader is another process; monotonic clocks don't compare.
        self._applied_at: Dict[str, float] = {}
        self._checkpoint_steps = 0
        self._saver = None
        self._ckpt_writer = None
        self._ckpt_planner = None
        # Serializes the busy-check/plan/capture/submit sequence:
        # concurrent push handlers at consecutive checkpoint versions
        # must not interleave inside the planner, or two deltas name
        # the same prev and the chain walk drops the second (its
        # drained rows would be silently unrestorable). An overlapping
        # interval trigger skips (non-blocking acquire), the drain
        # path waits — the old single-writer semaphore's discipline,
        # now at the trigger instead of the write.
        self._ckpt_trigger = threading.Lock()
        # Push dedup: {client key: last applied seq} — retried pushes
        # after an ambiguous failure must not double-apply. Persisted
        # with the checkpoint (see _SeqTable).
        self._applied_seq: Dict[int, int] = {}
        if checkpoint_dir:
            self.configure_checkpoint(
                checkpoint_dir, checkpoint_steps, keep_max
            )

    # ---- RPC handlers --------------------------------------------------

    def handlers(self):
        return {
            "table_info": self._table_info,
            "table_versions": self._table_versions_handler,
            "pull_rows": self._pull_rows,
            "push_row_grads": self._push_row_grads,
            "export_rows": self._export_rows,
        }

    def _table_info(self, request: dict) -> dict:
        return {
            "tables": {
                name: {"dim": int(table.dim)}
                for name, table in self._tables.items()
            }
        }

    def _table_versions_handler(self, request: dict) -> dict:
        """Monotonic per-table update counters — the serving cache's
        invalidation signal. One tiny fixed-size reply regardless of
        table size, so a cache can poll it far cheaper than re-pulling
        rows."""
        with self._lock:
            return {"versions": dict(self._table_versions),
                    "applied_at": dict(self._applied_at)}

    def table_version(self, table: str) -> int:
        """In-process accessor (tests / local tables)."""
        with self._lock:
            return self._table_versions[table]

    def _pull_rows(self, request: dict) -> dict:
        t0 = time.monotonic()
        table = self._tables[request["table"]]
        ids = np.asarray(request["ids"], np.int64)
        # Ambient span: nests under the RPC server span (role
        # rowservice) so lock-wait + store time is attributable
        # separately from wire/serde time; free with no recorder.
        tiered = hasattr(table, "prefault")
        with tracing.span("row_pull", table=request["table"],
                          rows=int(ids.size)):
            if tiered:
                # Fault this pull's cold rows with the DISK READ
                # outside the service lock: concurrent pushes wait on
                # in-memory bookkeeping only, and the host engine's
                # pull-ahead turns the fault into prefetch
                # (storage/tiered.py "Tiered storage").
                table.prefault(ids)
            with self._lock:
                rows = (table.get(ids, _defer_sweep=True) if tiered
                        else table.get(ids))
                applied_at = self._applied_at.get(request["table"], 0.0)
            if tiered:
                # Budget sweep AFTER releasing the service lock: the
                # eviction's cold write stalls no handler but this one.
                table.maybe_sweep()
        self._m_pulled.inc(ids.size)
        self._m_pull.observe(time.monotonic() - t0)
        # applied_at rides every pull so readers can observe row
        # freshness without an extra RPC (0.0 = never pushed).
        return {"rows": np.asarray(rows, np.float32),
                "applied_at": applied_at}

    def _export_rows(self, request: dict) -> dict:
        """Dense rows ``lo+offset, lo+offset+stride, ... < hi`` for
        serving export WITHOUT inflating the live table: trained rows
        overlay a throwaway table's deterministic lazy init
        (serving/export.py materialization, server side).
        ``stride``/``offset`` let a sharded client pull only the rows
        this shard owns (id % N == shard) instead of the whole range."""
        table = self._tables[request["table"]]
        lo, hi = int(request["lo"]), int(request["hi"])
        stride = int(request.get("stride", 1))
        offset = int(request.get("offset", 0))
        want = np.arange(lo + offset, hi, stride)
        with self._lock:
            ids, rows = table.to_arrays()
        from elasticdl_tpu.serving.export import _clone_empty

        dense = np.asarray(_clone_empty(table).get(want))
        keep = (ids >= lo + offset) & (ids < hi)
        if stride != 1:
            keep &= (ids - lo - offset) % stride == 0
        dense[(ids[keep] - lo - offset) // stride] = rows[keep]
        return {"rows": dense.astype(np.float32)}

    def _push_row_grads(self, request: dict) -> dict:
        t0 = time.monotonic()
        table = self._tables[request["table"]]
        client = request.get("client", "")
        seq = int(request.get("seq", -1))
        ids = np.asarray(request["ids"], np.int64)
        prefault = getattr(table, "prefault_group", None)
        with tracing.span("row_push", table=request["table"],
                          rows=int(ids.size)):
            if prefault is not None:
                # Cold reads for evicted rows (and their optimizer
                # slots) OUTSIDE the service lock; a duplicate push
                # merely promotes rows it would have touched anyway.
                prefault(ids)
            with self._lock:
                if client and seq >= 0:
                    key = _client_key(client)
                    if seq <= self._applied_seq.get(key, -1):
                        # Retried push whose first attempt DID apply
                        # before the reply was lost (at-most-once
                        # semantics).
                        self._m_dup.inc()
                        return {"duplicate": True}
                self._optimizer.apply_gradients(
                    table,
                    ids,
                    np.asarray(request["grads"], np.float32),
                )
                self._table_versions[request["table"]] += 1
                self._applied_at[request["table"]] = time.time()
                if client and seq >= 0:
                    # Record only AFTER apply succeeds: a failed apply
                    # must leave the seq unburned so the client's retry
                    # is not dropped as a duplicate (the gradient would
                    # be lost).
                    self._applied_seq[_client_key(client)] = seq
                self._push_count += 1
                version = self._push_count
            if prefault is not None:
                # Deferred half of the fused apply's budget sweep —
                # eviction's cold writes run with the lock released.
                table.maybe_sweep()
        self._m_pushed.inc(ids.size)
        self._m_push.observe(time.monotonic() - t0)
        if (
            self._saver is not None and self._checkpoint_steps
            and version % self._checkpoint_steps == 0
        ):
            self._checkpoint(version)
        return {}

    # ---- tiered storage ------------------------------------------------

    def configure_tiering(self, cold_dir: str, hot_budget_rows: int,
                          segment_max_bytes: int = 8 << 20,
                          compact_live_fraction: float = 0.5,
                          background_compact: bool = True):
        """Re-house every table behind a two-tier store (hot arena
        bounded by ``hot_budget_rows`` per table, cold rows spilled to
        CRC-framed segments under ``cold_dir`` — storage/tiered.py):
        the beyond-RAM path, letting this shard serve tables far larger
        than host memory as long as the working set fits the budget.

        Must run BEFORE ``configure_checkpoint``: checkpoint config
        enables dirty tracking on the table views it sees, and the
        tier wrapper owns that tracking once tiering is on (a row
        demoted while dirty must still ride the next delta)."""
        from elasticdl_tpu.storage import TierPolicy, tier_host_tables

        with self._lock:
            if self._saver is not None:
                raise RuntimeError(
                    "configure_tiering must run before "
                    "configure_checkpoint (dirty tracking moves to the "
                    "tier wrapper)"
                )
            self._tables = tier_host_tables(
                self._tables, cold_dir,
                TierPolicy(
                    hot_budget_rows,
                    segment_max_bytes=segment_max_bytes,
                    compact_live_fraction=compact_live_fraction,
                    background_compact=background_compact,
                ),
            )
            for table in self._tables.values():
                # The push handler sweeps AFTER releasing the service
                # lock (maybe_sweep below); a fused apply must not
                # also sweep inside it.
                table.defer_apply_sweep = True
        logger.info(
            "Row service tiering on: hot budget %d rows/table, cold "
            "tier at %s", hot_budget_rows, cold_dir,
        )
        return self

    def tier_stats(self) -> Dict[str, dict]:
        """Per-table tier occupancy/garbage (tests, debug endpoints)."""
        with self._lock:
            return {
                name: table.tier_stats()
                for name, table in self._tables.items()
                if hasattr(table, "tier_stats")
            }

    # ---- checkpoint ----------------------------------------------------

    def configure_checkpoint(self, checkpoint_dir: str,
                             checkpoint_steps: int = 0, keep_max: int = 3,
                             delta_chain_max: int = 8,
                             async_write: bool = True):
        """Attach (or re-point) the checkpoint saver and restore the
        newest valid version (chain-aware).

        ``delta_chain_max`` > 0 (default): interval saves write
        incremental deltas — dirty rows + their optimizer slots since
        the previous save — with a periodic full base compaction.
        ``async_write`` (default): the push handler pays only capture
        + enqueue; serialization and file IO run on the bounded
        background writer (``CheckpointWriter``). The chaos harness
        passes False for deterministic schedules."""
        from elasticdl_tpu.checkpoint.saver import (
            ChainPlanner,
            CheckpointSaver,
        )
        from elasticdl_tpu.checkpoint.writer import CheckpointWriter

        if self._ckpt_writer is not None:
            # Re-point: land (and surface) anything queued on the old
            # writer before abandoning it — an orphaned writer's
            # deferred failure would never raise, and its parked
            # thread never retire.
            self._ckpt_writer.close()
        self._saver = CheckpointSaver(
            checkpoint_dir, keep_max=keep_max,
            delta_chain_max=delta_chain_max,
        )
        self._ckpt_writer = CheckpointWriter(
            max_pending=1, sync=not async_write
        )
        self._ckpt_planner = ChainPlanner(delta_chain_max)
        self._checkpoint_steps = int(checkpoint_steps)
        for view in self.host_tables.values():
            # Turn dirty tracking on now that a consumer drains it
            # (host_tables pre-creates the optimizer slot tables, so
            # this covers them too; tables are OFF by default — the
            # marked-ids set would otherwise grow unbounded on
            # services that never checkpoint).
            enable = getattr(view, "enable_dirty_tracking", None)
            if enable is not None:
                enable()
        self._restore_latest()
        return self

    def _checkpoint(self, version: int, blocking: bool = False) -> bool:
        """Capture/write split: ONE lock acquisition across the whole
        capture so rows, optimizer slots, step counters, and the seq
        map are snapshotted at the same version — but the handler pays
        only that capture (dirty rows when a delta is planned) plus an
        enqueue; serialization + IO run on the background writer.
        Backpressure is the writer's bounded queue: an interval
        trigger that finds it full skips (its rows stay dirty and ride
        the next interval) while the drain path (checkpoint_now)
        blocks for its turn. Returns whether a write was enqueued."""
        if not self._ckpt_trigger.acquire(blocking=blocking):
            # Another trigger is mid-plan/capture: this interval's
            # state is covered by the next one.
            return False
        try:
            return self._checkpoint_locked(version, blocking)
        finally:
            self._ckpt_trigger.release()

    def _checkpoint_locked(self, version: int, blocking: bool) -> bool:
        if not blocking and self._ckpt_writer.busy:
            # Skip BEFORE planning or draining anything: the rows stay
            # dirty, the chain stays unbroken, and this interval's
            # state is covered by the next one.
            return False
        from elasticdl_tpu.checkpoint.saver import (
            CorruptCheckpointError,
            capture_tables,
            remark_dirty,
        )

        t0 = time.monotonic()
        plan, base, prev = self._ckpt_planner.plan(version)
        with self._lock:
            # ONE lock acquisition around the shared capture helper so
            # rows, slots, seq map, and step counters snapshot at the
            # same version.
            captured, dirty_ids = capture_tables(
                self.host_tables, delta=plan == "delta"
            )

        def remark():
            remark_dirty(self.host_tables, dirty_ids)

        def write():
            try:
                if plan == "delta":
                    if not self._saver.element_exists(prev):
                        # The predecessor this delta was planned
                        # against never became durable (its write
                        # failed ahead of us in the queue): writing
                        # would produce an unrestorable element while
                        # reporting success.
                        raise CorruptCheckpointError(
                            f"delta {version}: predecessor {prev} "
                            "never became durable; restarting chain"
                        )
                    self._saver.save_delta(
                        version, {}, captured, base, prev
                    )
                else:
                    self._saver.save(version, {}, embeddings=captured)
            except BaseException:
                # A failed write must put the drained rows back into
                # the dirty sets (or they vanish from every future
                # delta), and the chain must restart from a fresh base
                # (queued deltas linking through the failure are
                # unrestorable).
                remark()
                self._ckpt_planner.reset()
                raise

        try:
            ok = self._ckpt_writer.submit(
                write, label=f"rows-v{version}-{plan}", block=blocking
            )
        except RuntimeError:
            # Writer closed under us (stop()/re-point racing a push
            # across a checkpoint interval): the push itself was
            # applied — put the drained rows back and skip the save
            # instead of failing the RPC.
            ok = False
        if not ok:
            remark()
            self._ckpt_planner.reset()
        self._m_stall.observe(time.monotonic() - t0)
        return ok

    def checkpoint_now(self) -> bool:
        """DURABLE checkpoint at the current push count — the
        graceful-drain write (SIGTERM grace period / scripted shard
        relaunch): rows pushed since the last interval save must not
        be lost to a planned restart. Unlike the interval trigger this
        blocks for its writer-queue turn AND flushes the writer before
        returning, so the caller observes a fully durable version —
        not a queued one. Returns False when no saver is configured."""
        if self._saver is None:
            return False
        # Land any queued write FIRST: the on-disk tip lags the async
        # writer queue, and comparing against the lagging tip would
        # re-capture and re-write state already on its way to disk —
        # a full-table blocking save exactly when the SIGTERM grace
        # budget is tightest.
        self._ckpt_writer.flush()
        with self._lock:
            version = self._push_count
        if self._saver.get_valid_latest_version() == version:
            return True
        ok = self._checkpoint(version, blocking=True)
        self._ckpt_writer.flush()
        return ok

    def _restore_latest(self):
        try:
            version, _, embeddings = self._saver.restore()
        except FileNotFoundError:
            return
        targets = self.host_tables
        missing = [n for n in targets if n not in embeddings]
        if missing:
            raise ValueError(
                "row-service checkpoint lacks payload for "
                f"{sorted(missing)}; different optimizer or tables?"
            )
        for name, view in targets.items():
            ids, rows = embeddings[name].to_arrays()
            if ids.size:
                view.set(ids, rows)
            if getattr(view, "supports_dirty_rows", False):
                # The refill marked every restored row dirty; disk
                # already holds them — the first post-restore delta
                # must not re-ship the whole table.
                view.clear_dirty()
        self._push_count = int(version)
        logger.info(
            "Row service restored version %d (%d tables)",
            version, len(targets),
        )

    # ---- lifecycle / checkpoint ---------------------------------------

    def start(self, addr: str = "localhost:0",
              tag: str = "") -> "HostRowService":
        """``tag`` identifies this shard to chaos fault plans (e.g.
        ``rowservice/0``) — several shards of the same service can run
        in one test process and a plan must be able to stall just one."""
        self._server = RpcServer(
            addr, {SERVICE_NAME: self.handlers()}, tag=tag
        ).start()
        logger.info("Row service on port %d", self._server.port)
        return self

    @property
    def port(self) -> int:
        return self._server.port

    def stop(self, grace: Optional[float] = None):
        if self._server is not None:
            # Drain in-flight handlers BEFORE closing the writer: a
            # push crossing a checkpoint interval during shutdown
            # must not hit a closed writer — its RPC would fail after
            # the grads were already applied.
            ev = self._server.stop(grace)
            if ev is not None:
                ev.wait((grace or 0) + 30.0)
        if self._ckpt_writer is not None:
            try:
                # Land any queued checkpoint write and retire the
                # writer thread before the process goes away; failures
                # are logged, not raised — stop() is a teardown path.
                self._ckpt_writer.close()
            except BaseException as exc:
                logger.error(
                    "checkpoint flush on stop failed: %s", exc
                )
        for table in self._tables.values():
            # Tiered tables: flush cold segments, stop the compactor,
            # and snapshot the index (the clean-close marker
            # tools/check_store.py audits against).
            group = getattr(table, "tier_group", None)
            if group is not None:
                try:
                    group.close()
                except BaseException as exc:
                    logger.error("cold-tier close failed: %s", exc)

    def wait(self):
        """Block until the server stops (process-main lifetime)."""
        self._server.wait()

    @property
    def host_tables(self) -> Dict:
        """Rows + optimizer slots + step counters + push-dedup map,
        lock-guarded — pass to CheckpointHook/restore_from_dir in the
        SERVER process (the reference checkpoints on the PS for the
        same reason, ps/servicer.py:242-257)."""
        from elasticdl_tpu.embedding.host_engine import (
            _LockedTable,
            locked_checkpoint_tables,
        )

        out = locked_checkpoint_tables(
            self._tables, self._optimizer, self._lock
        )
        out[SEQS_TABLE_NAME] = _LockedTable(_SeqTable(self), self._lock)
        return out


# CANCELLED is transient too: a server-initiated GOAWAY during service
# shutdown cancels in-flight calls, and every method here is safe to
# retry (pulls are idempotent; pushes are deduped by (client, seq)).
_TRANSIENT_CODES = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED")


def _call_with_retry(stub: RpcStub, method: str, retries: int,
                     backoff_secs: float, **fields):
    """Ride out a service relaunch (reference workers retry PS RPCs via
    the ≤64 minibatch retry + 3x300s channel waits; here a bounded
    exponential backoff on the row plane). Only transport-level codes
    retry — INTERNAL (handler bugs, bad table names) is permanent and
    surfaces immediately."""
    delay = backoff_secs
    for attempt in range(retries + 1):
        try:
            return stub.call(method, **fields)
        except RpcError as exc:
            if exc.code not in _TRANSIENT_CODES or attempt == retries:
                raise
            logger.warning(
                "row service %s failed (attempt %d/%d); retrying in %.1fs",
                method, attempt + 1, retries, delay,
            )
            time.sleep(delay)
            # Fresh channel per retry: a channel whose connects were
            # refused while the service was (re)starting can wedge
            # permanently in-container; the retry budget (~4 min) must
            # actually span a pod relaunch, not spin on a dead channel
            # (same fix as the worker's master ride-out, PR 5).
            stub.reconnect()
            delay = min(delay * 2, 30.0)


class _RemoteTable:
    """Table-like view pulling rows over RPC (get-only: writes happen
    server-side via the optimizer push). ``concurrent_safe``: the stub
    is thread-safe and the SERVER serializes row access, so the client
    engine lets pulls overlap in-flight pushes (reference Go PS
    concurrent serving, ps/server.go:162-192)."""

    concurrent_safe = True

    def __init__(self, stub: RpcStub, name: str, dim: int,
                 retries: int = 12, backoff_secs: float = 0.5):
        self._stub = stub
        self.name = name
        self.dim = dim
        self._retries = retries
        self._backoff = backoff_secs
        # Wall-clock stamp of the service's last applied push as of
        # our newest pull (0.0 = never pushed / never pulled): what
        # serving's HostRowResolver turns into the
        # edl_tpu_row_freshness_seconds observation.
        self.last_applied_at = 0.0

    def get(self, ids) -> np.ndarray:
        resp = _call_with_retry(
            self._stub, "pull_rows", self._retries, self._backoff,
            table=self.name, ids=np.asarray(ids, np.int64),
        )
        self.last_applied_at = float(resp.get("applied_at", 0.0) or 0.0)
        return np.asarray(resp["rows"], np.float32)

    def pull_version(self) -> int:
        """This table's monotonic update counter on the service — the
        hot-row cache's invalidation probe (serving/model_store.py).
        One small RPC, no row payload."""
        resp = _call_with_retry(
            self._stub, "table_versions", self._retries, self._backoff,
        )
        return int(resp["versions"][self.name])

    def export_range(self, lo: int, hi: int, stride: int = 1,
                     offset: int = 0) -> np.ndarray:
        """Dense rows ``lo+offset, +stride, ... < hi`` (trained rows
        over deterministic lazy init; see _export_rows)."""
        return np.asarray(_call_with_retry(
            self._stub, "export_rows", self._retries, self._backoff,
            table=self.name, lo=int(lo), hi=int(hi),
            stride=int(stride), offset=int(offset),
        )["rows"], np.float32)

    def export_dense(self, vocab: int, chunk: int = 65536) -> np.ndarray:
        """Serving-export materialization, served chunk-wise by the
        service (no live-table inflation; see _export_rows)."""
        parts = [
            self.export_range(lo, min(lo + chunk, vocab))
            for lo in range(0, int(vocab), chunk)
        ]
        return np.concatenate(parts, axis=0)


class _RemoteOptimizer:
    """Optimizer-like view pushing row grads over RPC; the server
    applies them (reference push_gradients semantics).

    Concurrent-safe via PER-THREAD (client, seq) streams: the server's
    exactly-once dedup drops any seq <= the client's last applied, so
    two threads sharing one stream would lose whichever concurrent push
    arrived second. Each pushing thread gets its own client id instead
    (the server is multi-client by design); within a thread, seqs stay
    monotone so lost-reply retries still dedup correctly."""

    concurrent_safe = True

    def __init__(self, stub: RpcStub, retries: int = 12,
                 backoff_secs: float = 0.5):
        import threading
        import uuid

        self._stub = stub
        self._retries = retries
        self._backoff = backoff_secs
        self._client_base = uuid.uuid4().hex
        self._local = threading.local()
        # Fresh-counter client ids (NOT thread idents — idents are
        # reused after a thread dies, which would resurrect a dead
        # stream with a reset seq and get every push deduped away).
        self._client_counter = itertools.count()
        self._counter_lock = threading.Lock()

    def apply_gradients(self, table, ids, grads):
        # (client, seq) lets the server drop a retried push whose first
        # attempt applied but whose reply was lost.
        if not hasattr(self._local, "client"):
            with self._counter_lock:
                n = next(self._client_counter)
            self._local.client = f"{self._client_base}-{n}"
            self._local.seq = 0
        self._local.seq += 1
        _call_with_retry(
            self._stub, "push_row_grads", self._retries, self._backoff,
            table=table.name,
            ids=np.asarray(ids, np.int64),
            grads=np.asarray(grads, np.float32),
            client=self._local.client, seq=self._local.seq,
        )
        return table


def _scatter_by_home(pool, n: int, ids: np.ndarray, per_shard):
    """Run ``per_shard(shard_idx, mask)`` concurrently for every shard
    owning at least one of ``ids`` (home shard = id % n), and join.
    The one fan-out loop both the pull and push scatters share."""
    home = ids % n
    futures = []
    for s in range(n):
        mask = home == s
        if mask.any():
            futures.append(pool.submit(per_shard, s, mask))
    for f in futures:
        f.result()


class _ShardedTable:
    """Client-side scatter/gather over N row-service shards: row id
    lives on shard ``int_to_id(id, N)`` (= ``id % N`` — the same
    placement ``checkpoint/saver.py`` uses for row file shards, so a
    table checkpointed under either layout repartitions onto the
    other). The TPU-native shape of the reference worker's pull scatter
    over N PS pods (``worker/worker.py:362-391``,
    ``common/hash_utils.py:4-49``); per-shard pulls fan out on the
    engine's shard pool, so N servers' line rates aggregate WHEN the
    servers are the binding constraint (each on its own cores/NIC —
    the reference's N-pod regime). Measured on this repo's 1-core
    bench host (ROW_SERVICE_SCALING.json, tools/bench_row_service.py):
    one native-store shard serves ~2.2M pull / ~1.8M push rows/s
    through the full msgpack-RPC path, and sharding there only splits
    requests into smaller sub-RPCs — use shards for capacity
    partitioning and for multi-host deployments, not single-host
    throughput."""

    concurrent_safe = True

    def __init__(self, shards, pool):
        self._shards = list(shards)
        self._pool = pool
        self.name = self._shards[0].name
        self.dim = self._shards[0].dim

    def get(self, ids) -> np.ndarray:
        ids = np.asarray(ids, np.int64)
        out = np.empty((ids.size, self.dim), np.float32)

        def pull(s, mask):
            # Disjoint row slices: concurrent writes never overlap.
            out[mask] = self._shards[s].get(ids[mask])

        _scatter_by_home(self._pool, len(self._shards), ids, pull)
        return out

    def pull_version(self) -> int:
        """Sum of the shards' counters: any shard applying a push
        changes the sum, and counters only grow per-process, so an
        unchanged sum means no shard changed. (A shard RESTART resets
        its counter and can lower the sum — still a change unless every
        other shard's growth exactly cancels it, which the cache's
        != comparison treats identically to growth anyway.)"""
        return sum(s.pull_version() for s in self._shards)

    @property
    def last_applied_at(self) -> float:
        """OLDEST applied-push stamp across shards that have reported
        one — the conservative freshness bound. max() would let three
        healthy shards mask one whose push pipeline stalled, which is
        exactly the regime the freshness SLO exists to catch; shards
        that never saw a push (stamp 0) are excluded rather than
        pinning the metric to 'never'."""
        stamps = [
            s.last_applied_at for s in self._shards
            if s.last_applied_at > 0
        ]
        return min(stamps) if stamps else 0.0

    def export_dense(self, vocab: int, chunk: int = 65536) -> np.ndarray:
        """Each shard exports ONLY its owned rows (strided
        ``export_range``: ids ≡ s mod N), interleaved client-side — the
        total transfer is one table, not N (untrained rows fall back to
        the home shard's deterministic lazy init)."""
        n = len(self._shards)
        parts = []
        for lo in range(0, int(vocab), chunk):
            hi = min(lo + chunk, vocab)
            out = np.empty((hi - lo, self.dim), np.float32)

            def fill(s, lo=lo, hi=hi, out=out):
                offset = (s - lo) % n
                rows = self._shards[s].export_range(
                    lo, hi, stride=n, offset=offset
                )
                out[np.arange(lo + offset, hi, n) - lo] = rows

            futures = [
                self._pool.submit(fill, s)
                for s in range(n) if lo + (s - lo) % n < hi
            ]
            for f in futures:
                f.result()
            parts.append(out)
        return np.concatenate(parts, axis=0)


class _ShardedOptimizer:
    """Push scatter over N shards (reference ``worker.py:570-580``):
    each shard receives only the row grads it owns, applied by its own
    ``_RemoteOptimizer`` (whose per-thread (client, seq) streams keep
    the exactly-once dedup intact per shard)."""

    concurrent_safe = True

    def __init__(self, optimizers, pool):
        self._optimizers = list(optimizers)
        self._pool = pool

    def apply_gradients(self, table, ids, grads):
        ids = np.asarray(ids, np.int64)
        grads = np.asarray(grads, np.float32)

        def push(s, mask):
            self._optimizers[s].apply_gradients(
                table, ids[mask], grads[mask]
            )

        _scatter_by_home(
            self._pool, len(self._optimizers), ids, push
        )
        return table


def make_remote_engine(
    addr: str, id_keys: Dict[str, str],
    retries: int = 12, backoff_secs: float = 0.5,
    table_fanout: bool = True,
) -> HostEmbeddingEngine:
    """Client-side engine over running `HostRowService` shard(s).

    ``addr`` is one address or a comma list of N shard addresses —
    the reference's N parameter servers (``--ps_pods``); rows scatter
    by ``id % N`` client-side (``_ShardedTable``/``_ShardedOptimizer``)
    and each shard process runs the UNCHANGED single-server
    ``HostRowService`` (its lazy tables only ever materialize the rows
    hashed to it). Table names and dims come from the services
    themselves (verified consistent across shards); pulls/pushes retry
    with bounded backoff across a shard relaunch. The default budget
    (0.5s doubling, capped 30s, 12 retries ≈ 4 minutes) spans a real
    pod relaunch — scheduling + image pull + checkpoint restore — like
    the reference workers' 3x300s channel waits."""
    addrs = [a.strip() for a in addr.split(",") if a.strip()]
    if not addrs:
        raise ValueError("empty row-service address")
    # max_retries=0: _call_with_retry owns the (much longer) retry
    # budget here — stacking the stub's own backoff under it would
    # multiply attempts.
    stubs = [RpcStub(a, SERVICE_NAME, max_retries=0) for a in addrs]
    infos = [
        _call_with_retry(stub, "table_info", retries, backoff_secs)[
            "tables"
        ]
        for stub in stubs
    ]
    for a, info in zip(addrs[1:], infos[1:]):
        if info != infos[0]:
            raise ValueError(
                f"row-service shard {a} serves different tables "
                f"({sorted(info)}) than shard {addrs[0]} "
                f"({sorted(infos[0])}); all shards must run the same "
                "model module"
            )
    if len(addrs) == 1:
        stub = stubs[0]
        tables = {
            name: _RemoteTable(
                stub, name, meta["dim"], retries, backoff_secs
            )
            for name, meta in infos[0].items()
        }
        optimizer = _RemoteOptimizer(stub, retries, backoff_secs)
    else:
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(
            max_workers=2 * len(addrs),
            thread_name_prefix="row-shard",
        )
        tables = {
            name: _ShardedTable(
                [
                    _RemoteTable(
                        stub, name, meta["dim"], retries, backoff_secs
                    )
                    for stub in stubs
                ],
                pool,
            )
            for name, meta in infos[0].items()
        }
        optimizer = _ShardedOptimizer(
            [_RemoteOptimizer(s, retries, backoff_secs) for s in stubs],
            pool,
        )
    engine = HostEmbeddingEngine(
        tables, optimizer, id_keys=id_keys, table_fanout=table_fanout
    )
    engine.remote = True  # server owns checkpointing (see HostStepRunner)
    return engine


def validate_shard_layout(checkpoint_dir: str, shard: int,
                          num_shards: int):
    """Refuse to restore a checkpoint written under a DIFFERENT shard
    layout: rows live by id % N client-side, so restoring an N-shard
    checkpoint into an M-shard job would silently re-lazy-init every
    row whose home moved (trained embeddings reset with no error). A
    ``shard_layout.json`` marker records the layout; a checkpoint dir
    holding versions but no marker is treated as num_shards=1 (the
    pre-shard layout)."""
    import json
    import os

    marker = os.path.join(checkpoint_dir, "shard_layout.json")
    if os.path.exists(marker):
        with open(marker) as fh:
            recorded = json.load(fh)
    else:
        from elasticdl_tpu.checkpoint.saver import CheckpointSaver

        has_versions = bool(
            os.path.isdir(checkpoint_dir)
            and CheckpointSaver(checkpoint_dir).list_versions()
        )
        if not has_versions:
            os.makedirs(checkpoint_dir, exist_ok=True)
            with open(marker, "w") as fh:
                json.dump({"shard": shard, "num_shards": num_shards}, fh)
            return
        recorded = {"shard": 0, "num_shards": 1}  # pre-shard layout
    if (
        int(recorded.get("num_shards", 1)) != num_shards
        or int(recorded.get("shard", 0)) != shard
    ):
        raise SystemExit(
            f"checkpoint {checkpoint_dir} was written as shard "
            f"{recorded.get('shard', 0)}/{recorded.get('num_shards', 1)}"
            f" but this process is shard {shard}/{num_shards}; "
            "changing --num_row_service_shards across a restore would "
            "silently lose the rows whose id%N home moved. Start a "
            "fresh checkpoint dir (or repartition offline via "
            "checkpoint.saver, which uses the same id%N placement)."
        )


def main(argv=None):
    """Process entry: ``python -m elasticdl_tpu.embedding.row_service
    --model_zoo ... --model_def ... [--addr :6100] [--checkpoint_dir ...]``
    — the zoo module supplies ``make_row_service()`` (the deployment
    unit the reference's PS pod mapped to). ``--shard_id/--num_shards``
    record the shard layout so a relaunch with a different
    --num_row_service_shards fails loudly instead of silently losing
    rows (see validate_shard_layout)."""
    import argparse

    from elasticdl_tpu.core.model_spec import load_model_zoo_module

    parser = argparse.ArgumentParser("elasticdl_tpu-row-service")
    parser.add_argument("--model_zoo", required=True)
    parser.add_argument("--model_def", required=True)
    parser.add_argument("--addr", default="[::]:6100")
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--checkpoint_steps", type=int, default=0)
    parser.add_argument("--keep_checkpoint_max", type=int, default=3)
    parser.add_argument("--checkpoint_delta_chain", type=int, default=8,
                        help="Max incremental delta checkpoints riding "
                             "one full base before a save compacts "
                             "into a fresh base; 0 = full snapshots "
                             "only (docs/fault_tolerance.md)")
    parser.add_argument("--checkpoint_sync", action="store_true",
                        help="Write checkpoints inline on the push "
                             "handler instead of the background "
                             "writer (debugging / deterministic "
                             "schedules)")
    parser.add_argument("--hot_budget_rows", type=int, default=0,
                        help="Tiered storage: max rows/table resident "
                             "in the hot in-memory arena; colder rows "
                             "spill to CRC-framed disk segments "
                             "(docs/sparse_path.md 'Tiered storage'). "
                             "0 (default) = everything in memory")
    parser.add_argument("--cold_dir", default="",
                        help="Cold-tier segment directory (spill "
                             "cache, wiped on start — checkpoints own "
                             "durability). Default: "
                             "<checkpoint_dir>_cold, or a tempdir "
                             "when no checkpoint dir is set")
    parser.add_argument("--cold_segment_mb", type=int, default=8,
                        help="Cold-tier segment file size bound (MB)")
    parser.add_argument("--cold_compact_live_fraction", type=float,
                        default=0.5,
                        help="Compact a cold segment when its live "
                             "record fraction drops below this")
    parser.add_argument("--shard_id", type=int, default=0)
    parser.add_argument("--num_shards", type=int, default=1)
    parser.add_argument("--metrics_port", type=int, default=-1,
                        help="Serve this process's own registry "
                             "(row_service_* pull/push metrics) as "
                             "Prometheus /metrics; 0 = ephemeral, "
                             "-1 (default) = disabled")
    parser.add_argument("--flight_recorder", type=int, default=0,
                        help="Install a span flight recorder of this "
                             "many entries (served on /traces next to "
                             "/metrics; tools/dump_metrics.py "
                             "--traces); 0 (default) = tracing off")
    args = parser.parse_args(argv)

    module, _ = load_model_zoo_module(args.model_zoo, args.model_def)
    factory = getattr(module, "make_row_service", None)
    if factory is None:
        raise SystemExit(
            f"{args.model_def}: module defines no make_row_service()"
        )
    service = factory()
    if args.hot_budget_rows > 0:
        # BEFORE checkpoint config: restore refills stream through the
        # tier (the budget holds from the first row), and dirty
        # tracking lands on the tier wrapper.
        cold_dir = args.cold_dir
        if not cold_dir:
            if args.checkpoint_dir:
                cold_dir = args.checkpoint_dir.rstrip("/") + "_cold"
            else:
                import tempfile

                cold_dir = tempfile.mkdtemp(prefix="edl_cold_")
        service.configure_tiering(
            cold_dir, args.hot_budget_rows,
            segment_max_bytes=args.cold_segment_mb << 20,
            compact_live_fraction=args.cold_compact_live_fraction,
        )
    if args.checkpoint_dir:
        validate_shard_layout(
            args.checkpoint_dir, args.shard_id, args.num_shards
        )
        service.configure_checkpoint(
            args.checkpoint_dir, args.checkpoint_steps,
            args.keep_checkpoint_max,
            delta_chain_max=args.checkpoint_delta_chain,
            async_write=not args.checkpoint_sync,
        )
    service.start(args.addr, tag=f"rowservice/{args.shard_id}")
    logger.info("Row service serving on %s", args.addr)
    if args.flight_recorder > 0:
        tracing.set_process_role("rowservice", str(args.shard_id))
        tracing.install_recorder(
            tracing.FlightRecorder(args.flight_recorder)
        )
    if args.metrics_port >= 0:
        # A row-service pod reports to no master, so its registry
        # (row_service_* counters/latency) is scrapeable directly —
        # without this its metrics would be write-only. /traces serves
        # the flight recorder the same way when one is installed.
        from elasticdl_tpu.observability import (
            MetricsHTTPServer,
            default_registry,
            render_prometheus,
        )

        MetricsHTTPServer(
            lambda: render_prometheus(default_registry().snapshot()),
            port=args.metrics_port,
            traces=lambda: {"spans": tracing.recorder_spans()},
        ).start()
    service.wait()


if __name__ == "__main__":
    main()
