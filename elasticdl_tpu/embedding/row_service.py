"""Shared row service: the host tier served over RPC.

The one parameter-server role the mesh cannot absorb: several *worker
processes* training one >HBM embedding table need a shared row plane.
The reference serves it with the Pserver gRPC service
(``pull_embedding_vectors`` / ``push_gradients``,
``elasticdl/proto/elasticdl.proto:137-145``; Go impl
``pkg/ps/server.go:149,162``). Here the same contract rides the
framework's msgpack RPC (comm/rpc.py):

- **Server** (`HostRowService`): owns the tables (Python or C++ row
  store) and the row optimizer; applies pushed gradients under a lock
  (async-PS semantics — concurrent workers interleave, reference
  async_sgd.md); exposes `host_tables` so the server-side process
  checkpoints rows + optimizer slots exactly like a local engine.
- **Client** (`make_remote_engine`): a `HostEmbeddingEngine` whose
  tables pull rows over RPC and whose "optimizer" pushes gradients
  back. `HostStepRunner` works unchanged on top; its `host_tables` is
  None (the server owns checkpointing).

Worker-side dedup/bucketing still applies: each pull moves only the
batch's unique rows, mirroring the reference worker's dedup before
push (worker.py:487-599).
"""

import itertools
import threading
import time
from typing import Dict, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.comm.rpc import RpcError, RpcServer, RpcStub
from elasticdl_tpu.embedding.host_engine import HostEmbeddingEngine

logger = get_logger("row_service")

SERVICE_NAME = "RowService"
SEQS_TABLE_NAME = "__row_service_seqs__"


def _client_key(client: str) -> int:
    """Stable 63-bit key for a client id string (dict/table row id)."""
    import hashlib

    return int.from_bytes(
        hashlib.blake2b(client.encode("utf-8"), digest_size=8).digest(),
        "big",
    ) >> 1


class _SeqTable:
    """Checkpoint adapter persisting the push-dedup map ({client key:
    last applied seq}) as a dim-1 table, closing the
    die-between-checkpoint-and-reply double-apply window: a relaunch
    restores the map with the rows it belongs to."""

    dim = 1

    def __init__(self, service: "HostRowService"):
        self._service = service

    def to_arrays(self):
        items = sorted(self._service._applied_seq.items())
        ids = np.array([k for k, _ in items], np.int64)
        rows = np.array(
            [[v] for _, v in items], np.float64
        ).reshape(-1, 1)
        return ids, rows

    def set(self, ids, values):
        values = np.asarray(values).reshape(len(list(ids)), -1)
        for key, row in zip(ids, values):
            self._service._applied_seq[int(key)] = int(round(float(row[0])))


class HostRowService:
    """Server side of the shared host tier.

    ``checkpoint_dir``/``checkpoint_steps``: save rows + optimizer
    state every N gradient pushes — the reference PS checkpoints inside
    ``push_gradients`` every checkpoint_steps versions
    (ps/servicer.py:242-257, pkg/ps/server.go:114-127); the push count
    is the service's version. At start the newest valid version is
    restored, so a relaunched service pod resumes lossless (reference
    PS relaunch + checkpoint-restore semantics).
    """

    def __init__(self, tables: Dict, optimizer, checkpoint_dir: str = "",
                 checkpoint_steps: int = 0, keep_max: int = 3):
        self._tables = tables
        self._optimizer = optimizer
        self._lock = threading.RLock()
        self._server: Optional[RpcServer] = None
        self._push_count = 0
        self._checkpoint_steps = 0
        self._saver = None
        self._ckpt_writer_free = threading.Semaphore(1)
        # Push dedup: {client key: last applied seq} — retried pushes
        # after an ambiguous failure must not double-apply. Persisted
        # with the checkpoint (see _SeqTable).
        self._applied_seq: Dict[int, int] = {}
        if checkpoint_dir:
            self.configure_checkpoint(
                checkpoint_dir, checkpoint_steps, keep_max
            )

    # ---- RPC handlers --------------------------------------------------

    def handlers(self):
        return {
            "table_info": self._table_info,
            "pull_rows": self._pull_rows,
            "push_row_grads": self._push_row_grads,
            "export_rows": self._export_rows,
        }

    def _table_info(self, request: dict) -> dict:
        return {
            "tables": {
                name: {"dim": int(table.dim)}
                for name, table in self._tables.items()
            }
        }

    def _pull_rows(self, request: dict) -> dict:
        table = self._tables[request["table"]]
        with self._lock:
            rows = table.get(np.asarray(request["ids"], np.int64))
        return {"rows": np.asarray(rows, np.float32)}

    def _export_rows(self, request: dict) -> dict:
        """Dense [lo, hi) rows for serving export WITHOUT inflating the
        live table: trained rows overlay a throwaway table's
        deterministic lazy init (serving/export.py materialization,
        server side)."""
        table = self._tables[request["table"]]
        lo, hi = int(request["lo"]), int(request["hi"])
        with self._lock:
            ids, rows = table.to_arrays()
        from elasticdl_tpu.serving.export import _clone_empty

        dense = np.asarray(_clone_empty(table).get(np.arange(lo, hi)))
        keep = (ids >= lo) & (ids < hi)
        dense[ids[keep] - lo] = rows[keep]
        return {"rows": dense.astype(np.float32)}

    def _push_row_grads(self, request: dict) -> dict:
        table = self._tables[request["table"]]
        client = request.get("client", "")
        seq = int(request.get("seq", -1))
        with self._lock:
            if client and seq >= 0:
                key = _client_key(client)
                if seq <= self._applied_seq.get(key, -1):
                    # Retried push whose first attempt DID apply before
                    # the reply was lost (at-most-once semantics).
                    return {"duplicate": True}
            self._optimizer.apply_gradients(
                table,
                np.asarray(request["ids"], np.int64),
                np.asarray(request["grads"], np.float32),
            )
            if client and seq >= 0:
                # Record only AFTER apply succeeds: a failed apply must
                # leave the seq unburned so the client's retry is not
                # dropped as a duplicate (the gradient would be lost).
                self._applied_seq[_client_key(client)] = seq
            self._push_count += 1
            version = self._push_count
        if (
            self._saver is not None and self._checkpoint_steps
            and version % self._checkpoint_steps == 0
        ):
            self._checkpoint(version)
        return {}

    # ---- checkpoint ----------------------------------------------------

    def configure_checkpoint(self, checkpoint_dir: str,
                             checkpoint_steps: int = 0, keep_max: int = 3):
        """Attach (or re-point) the checkpoint saver and restore the
        newest valid version."""
        from elasticdl_tpu.checkpoint.saver import CheckpointSaver

        self._saver = CheckpointSaver(checkpoint_dir, keep_max=keep_max)
        self._checkpoint_steps = int(checkpoint_steps)
        self._restore_latest()
        return self

    def _checkpoint(self, version: int):
        """ONE lock acquisition across the whole snapshot so rows,
        optimizer slots, and step counters are captured at the same
        version; the file write happens outside (pushes keep flowing
        during IO). A single writer at a time: overlapping triggers
        skip (their version is covered by the next interval)."""
        from elasticdl_tpu.embedding.table import EmbeddingTable

        if not self._ckpt_writer_free.acquire(blocking=False):
            return
        try:
            snapshot = {}
            with self._lock:
                for name, view in self.host_tables.items():
                    ids, rows = view.to_arrays()
                    snapshot[name] = EmbeddingTable.from_arrays(
                        name, ids, rows,
                        dtype=rows.dtype if rows.size else np.float32,
                    )
            self._saver.save(version, {}, embeddings=snapshot)
        finally:
            self._ckpt_writer_free.release()

    def _restore_latest(self):
        try:
            version, _, embeddings = self._saver.restore()
        except FileNotFoundError:
            return
        targets = self.host_tables
        missing = [n for n in targets if n not in embeddings]
        if missing:
            raise ValueError(
                "row-service checkpoint lacks payload for "
                f"{sorted(missing)}; different optimizer or tables?"
            )
        for name, view in targets.items():
            ids, rows = embeddings[name].to_arrays()
            if ids.size:
                view.set(ids, rows)
        self._push_count = int(version)
        logger.info(
            "Row service restored version %d (%d tables)",
            version, len(targets),
        )

    # ---- lifecycle / checkpoint ---------------------------------------

    def start(self, addr: str = "localhost:0") -> "HostRowService":
        self._server = RpcServer(
            addr, {SERVICE_NAME: self.handlers()}
        ).start()
        logger.info("Row service on port %d", self._server.port)
        return self

    @property
    def port(self) -> int:
        return self._server.port

    def stop(self, grace: Optional[float] = None):
        if self._server is not None:
            self._server.stop(grace)

    def wait(self):
        """Block until the server stops (process-main lifetime)."""
        self._server.wait()

    @property
    def host_tables(self) -> Dict:
        """Rows + optimizer slots + step counters + push-dedup map,
        lock-guarded — pass to CheckpointHook/restore_from_dir in the
        SERVER process (the reference checkpoints on the PS for the
        same reason, ps/servicer.py:242-257)."""
        from elasticdl_tpu.embedding.host_engine import (
            _LockedTable,
            locked_checkpoint_tables,
        )

        out = locked_checkpoint_tables(
            self._tables, self._optimizer, self._lock
        )
        out[SEQS_TABLE_NAME] = _LockedTable(_SeqTable(self), self._lock)
        return out


# CANCELLED is transient too: a server-initiated GOAWAY during service
# shutdown cancels in-flight calls, and every method here is safe to
# retry (pulls are idempotent; pushes are deduped by (client, seq)).
_TRANSIENT_CODES = ("UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED")


def _call_with_retry(stub: RpcStub, method: str, retries: int,
                     backoff_secs: float, **fields):
    """Ride out a service relaunch (reference workers retry PS RPCs via
    the ≤64 minibatch retry + 3x300s channel waits; here a bounded
    exponential backoff on the row plane). Only transport-level codes
    retry — INTERNAL (handler bugs, bad table names) is permanent and
    surfaces immediately."""
    delay = backoff_secs
    for attempt in range(retries + 1):
        try:
            return stub.call(method, **fields)
        except RpcError as exc:
            if exc.code not in _TRANSIENT_CODES or attempt == retries:
                raise
            logger.warning(
                "row service %s failed (attempt %d/%d); retrying in %.1fs",
                method, attempt + 1, retries, delay,
            )
            time.sleep(delay)
            delay = min(delay * 2, 30.0)


class _RemoteTable:
    """Table-like view pulling rows over RPC (get-only: writes happen
    server-side via the optimizer push). ``concurrent_safe``: the stub
    is thread-safe and the SERVER serializes row access, so the client
    engine lets pulls overlap in-flight pushes (reference Go PS
    concurrent serving, ps/server.go:162-192)."""

    concurrent_safe = True

    def __init__(self, stub: RpcStub, name: str, dim: int,
                 retries: int = 12, backoff_secs: float = 0.5):
        self._stub = stub
        self.name = name
        self.dim = dim
        self._retries = retries
        self._backoff = backoff_secs

    def get(self, ids) -> np.ndarray:
        resp = _call_with_retry(
            self._stub, "pull_rows", self._retries, self._backoff,
            table=self.name, ids=np.asarray(ids, np.int64),
        )
        return np.asarray(resp["rows"], np.float32)

    def export_dense(self, vocab: int, chunk: int = 65536) -> np.ndarray:
        """Serving-export materialization, served chunk-wise by the
        service (no live-table inflation; see _export_rows)."""
        parts = [
            np.asarray(_call_with_retry(
                self._stub, "export_rows", self._retries, self._backoff,
                table=self.name, lo=lo, hi=min(lo + chunk, vocab),
            )["rows"], np.float32)
            for lo in range(0, int(vocab), chunk)
        ]
        return np.concatenate(parts, axis=0)


class _RemoteOptimizer:
    """Optimizer-like view pushing row grads over RPC; the server
    applies them (reference push_gradients semantics).

    Concurrent-safe via PER-THREAD (client, seq) streams: the server's
    exactly-once dedup drops any seq <= the client's last applied, so
    two threads sharing one stream would lose whichever concurrent push
    arrived second. Each pushing thread gets its own client id instead
    (the server is multi-client by design); within a thread, seqs stay
    monotone so lost-reply retries still dedup correctly."""

    concurrent_safe = True

    def __init__(self, stub: RpcStub, retries: int = 12,
                 backoff_secs: float = 0.5):
        import threading
        import uuid

        self._stub = stub
        self._retries = retries
        self._backoff = backoff_secs
        self._client_base = uuid.uuid4().hex
        self._local = threading.local()
        # Fresh-counter client ids (NOT thread idents — idents are
        # reused after a thread dies, which would resurrect a dead
        # stream with a reset seq and get every push deduped away).
        self._client_counter = itertools.count()
        self._counter_lock = threading.Lock()

    def apply_gradients(self, table, ids, grads):
        # (client, seq) lets the server drop a retried push whose first
        # attempt applied but whose reply was lost.
        if not hasattr(self._local, "client"):
            with self._counter_lock:
                n = next(self._client_counter)
            self._local.client = f"{self._client_base}-{n}"
            self._local.seq = 0
        self._local.seq += 1
        _call_with_retry(
            self._stub, "push_row_grads", self._retries, self._backoff,
            table=table.name,
            ids=np.asarray(ids, np.int64),
            grads=np.asarray(grads, np.float32),
            client=self._local.client, seq=self._local.seq,
        )
        return table


def make_remote_engine(
    addr: str, id_keys: Dict[str, str],
    retries: int = 12, backoff_secs: float = 0.5,
) -> HostEmbeddingEngine:
    """Client-side engine over a running `HostRowService`. Table names
    and dims come from the service itself; pulls/pushes retry with
    bounded backoff across a service relaunch. The default budget
    (0.5s doubling, capped 30s, 12 retries ≈ 4 minutes) spans a real
    pod relaunch — scheduling + image pull + checkpoint restore — like
    the reference workers' 3x300s channel waits."""
    stub = RpcStub(addr, SERVICE_NAME)
    info = _call_with_retry(stub, "table_info", retries, backoff_secs)[
        "tables"
    ]
    tables = {
        name: _RemoteTable(stub, name, meta["dim"], retries, backoff_secs)
        for name, meta in info.items()
    }
    engine = HostEmbeddingEngine(
        tables, _RemoteOptimizer(stub, retries, backoff_secs),
        id_keys=id_keys,
    )
    engine.remote = True  # server owns checkpointing (see HostStepRunner)
    return engine


def main(argv=None):
    """Process entry: ``python -m elasticdl_tpu.embedding.row_service
    --model_zoo ... --model_def ... [--addr :6100] [--checkpoint_dir ...]``
    — the zoo module supplies ``make_row_service()`` (the deployment
    unit the reference's PS pod mapped to)."""
    import argparse

    from elasticdl_tpu.core.model_spec import load_model_zoo_module

    parser = argparse.ArgumentParser("elasticdl_tpu-row-service")
    parser.add_argument("--model_zoo", required=True)
    parser.add_argument("--model_def", required=True)
    parser.add_argument("--addr", default="[::]:6100")
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--checkpoint_steps", type=int, default=0)
    parser.add_argument("--keep_checkpoint_max", type=int, default=3)
    args = parser.parse_args(argv)

    module, _ = load_model_zoo_module(args.model_zoo, args.model_def)
    factory = getattr(module, "make_row_service", None)
    if factory is None:
        raise SystemExit(
            f"{args.model_def}: module defines no make_row_service()"
        )
    service = factory()
    if args.checkpoint_dir:
        service.configure_checkpoint(
            args.checkpoint_dir, args.checkpoint_steps,
            args.keep_checkpoint_max,
        )
    service.start(args.addr)
    logger.info("Row service serving on %s", args.addr)
    service.wait()


if __name__ == "__main__":
    main()
