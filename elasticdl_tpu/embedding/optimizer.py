"""Row optimizers: sparse updates touching only looked-up rows.

Counterpart of two reference components:

- ``elasticdl/python/ps/optimizer_wrapper.py:57-338`` — make a vanilla
  optimizer update *externally stored* embedding rows plus their slot
  rows (momentum/m/v/accumulator), creating slots lazily;
- the Go/C++ PS update kernels (``elasticdl/pkg/ps/optimizer.go``,
  ``pkg/kernel/capi/kernel_api.cc:6-96``) — SGD, Momentum(+Nesterov),
  Adam(+amsgrad, bias correction), Adagrad.

Here the update math is pure array code, so the same functions serve
- the **device path**: scatter-apply on a (possibly mesh-sharded) in-HBM
  table inside a jit step, touching only unique looked-up rows,
- the **host path**: numpy rows pulled from a lazy `EmbeddingTable`
  (apply → write back, mirroring OptimizerWrapper.apply_gradients).
"""

from dataclasses import dataclass
from typing import Dict, Tuple

import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.embedding.table import EmbeddingTable, get_slot_table_name


@dataclass(frozen=True)
class RowOptimizer:
    """Per-row update rule. ``slot_names`` mirrors the reference per-opt
    slot tables (optimizer_wrapper.py:103-133); slots are created
    zero-initialized (constant-init slot tables, ps/parameters.py:156)."""

    name: str = "sgd"
    lr: float = 0.01
    momentum: float = 0.0
    nesterov: bool = False
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    amsgrad: bool = False
    slot_names: Tuple[str, ...] = ()

    def apply_rows(self, rows, grads, slots: Dict[str, "jnp.ndarray"],
                   step):
        """(rows, slots) -> (new_rows, new_slots); ``step`` is the 1-based
        apply count used for Adam bias correction (kernel_api.cc:52-55)."""
        raise NotImplementedError


@dataclass(frozen=True)
class SGD(RowOptimizer):
    name: str = "sgd"

    def apply_rows(self, rows, grads, slots, step):
        return rows - self.lr * grads, slots


@dataclass(frozen=True)
class Momentum(RowOptimizer):
    name: str = "momentum"
    momentum: float = 0.9
    slot_names: Tuple[str, ...] = ("momentum",)

    def apply_rows(self, rows, grads, slots, step):
        vel = self.momentum * slots["momentum"] + grads
        if self.nesterov:
            update = self.momentum * vel + grads
        else:
            update = vel
        return rows - self.lr * update, {"momentum": vel}


@dataclass(frozen=True)
class Adam(RowOptimizer):
    name: str = "adam"
    lr: float = 0.001
    slot_names: Tuple[str, ...] = ("m", "v")

    def __post_init__(self):
        if self.amsgrad and "max_v" not in self.slot_names:
            raise ValueError(
                "amsgrad needs the 'max_v' slot table: use AdamAmsgrad or "
                "make_row_optimizer('Adam', amsgrad=True)"
            )

    def apply_rows(self, rows, grads, slots, step):
        xp = jnp if isinstance(rows, jnp.ndarray) else np
        m = self.beta1 * slots["m"] + (1.0 - self.beta1) * grads
        v = self.beta2 * slots["v"] + (1.0 - self.beta2) * grads * grads
        new_slots = {"m": m, "v": v}
        step = xp.asarray(step, rows.dtype)
        m_hat = m / (1.0 - self.beta1**step)
        v_hat = v / (1.0 - self.beta2**step)
        if self.amsgrad:
            vmax = xp.maximum(slots["max_v"], v_hat)
            new_slots["max_v"] = vmax
            v_hat = vmax
        new_rows = rows - self.lr * m_hat / (xp.sqrt(v_hat) + self.epsilon)
        return new_rows, new_slots


@dataclass(frozen=True)
class AdamAmsgrad(Adam):
    amsgrad: bool = True
    slot_names: Tuple[str, ...] = ("m", "v", "max_v")


@dataclass(frozen=True)
class Adagrad(RowOptimizer):
    name: str = "adagrad"
    slot_names: Tuple[str, ...] = ("accumulator",)
    initial_accumulator: float = 0.1

    def apply_rows(self, rows, grads, slots, step):
        xp = jnp if isinstance(rows, jnp.ndarray) else np
        acc = slots["accumulator"] + grads * grads
        new_rows = rows - self.lr * grads / (xp.sqrt(acc) + self.epsilon)
        return new_rows, {"accumulator": acc}


_OPTIMIZERS = {
    "SGD": SGD,
    "sgd": SGD,
    "Momentum": Momentum,
    "momentum": Momentum,
    "Adam": Adam,
    "adam": Adam,
    "Adagrad": Adagrad,
    "adagrad": Adagrad,
}


def make_row_optimizer(opt_type: str, **kwargs) -> RowOptimizer:
    """Flag-string construction (reference pkg/ps/optimizer.go:290-312:
    the master serializes the user optimizer to -opt_type/-opt_args)."""
    if opt_type in ("Adam", "adam") and kwargs.pop("amsgrad", False):
        return AdamAmsgrad(**kwargs)
    cls = _OPTIMIZERS.get(opt_type)
    if cls is None:
        raise ValueError(
            f"Unsupported row optimizer {opt_type!r}; "
            f"have {sorted(set(_OPTIMIZERS))}"
        )
    return cls(**kwargs)


def slot_init_value(opt: RowOptimizer, slot_name: str) -> float:
    if isinstance(opt, Adagrad) and slot_name == "accumulator":
        return opt.initial_accumulator
    return 0.0


# ---- device path: sparse scatter apply on an in-HBM table ----------------


def _pallas_sparse_apply(opt: RowOptimizer, table, slot_tables,
                         unique_ids, row_grads, step,
                         interpret: bool = False):
    """Kernel edition of sparse_apply for the supported optimizers
    (ops/pallas_embedding in-place updates; same OOR pad contract)."""
    from elasticdl_tpu.ops import pallas_embedding as pe

    if isinstance(opt, Adam) and opt.amsgrad:
        new_table, m, v, max_v = pe.sparse_adam_amsgrad_update(
            table, slot_tables["m"], slot_tables["v"],
            slot_tables["max_v"], unique_ids, row_grads, lr=opt.lr,
            beta1=opt.beta1, beta2=opt.beta2, epsilon=opt.epsilon,
            step=step, interpret=interpret,
        )
        return new_table, {
            **slot_tables, "m": m, "v": v, "max_v": max_v
        }
    if isinstance(opt, Adam):
        new_table, m, v = pe.sparse_adam_update(
            table, slot_tables["m"], slot_tables["v"], unique_ids,
            row_grads, lr=opt.lr, beta1=opt.beta1, beta2=opt.beta2,
            epsilon=opt.epsilon, step=step, interpret=interpret,
        )
        return new_table, {**slot_tables, "m": m, "v": v}
    if isinstance(opt, Adagrad):
        new_table, acc = pe.sparse_adagrad_update(
            table, slot_tables["accumulator"], unique_ids, row_grads,
            lr=opt.lr, epsilon=opt.epsilon, interpret=interpret,
        )
        return new_table, {**slot_tables, "accumulator": acc}
    if isinstance(opt, Momentum):
        new_table, vel = pe.sparse_momentum_update(
            table, slot_tables["momentum"], unique_ids, row_grads,
            lr=opt.lr, momentum=opt.momentum, nesterov=opt.nesterov,
            interpret=interpret,
        )
        return new_table, {**slot_tables, "momentum": vel}
    if not isinstance(opt, SGD):
        # Loud, not a silent SGD downgrade: an unkernelized optimizer's
        # slots would go stale and the math would drift.
        raise ValueError(
            f"no Pallas kernel for {type(opt).__name__}; "
            "use use_pallas='never' (XLA path)"
        )
    new_table = pe.sparse_sgd_update(
        table, unique_ids, row_grads, lr=opt.lr, interpret=interpret
    )
    return new_table, slot_tables


def _fused_sparse_apply(opt: RowOptimizer, table, slot_tables,
                        unique_ids, row_grads, interpret: bool = False):
    """Route to the block-pipelined fused scatter-apply kernels
    (ops/pallas_embedding fused_* — SGD + Momentum coverage). Callers
    guarantee ``fused_apply_supported``."""
    from elasticdl_tpu.ops import pallas_embedding as pe

    if isinstance(opt, Momentum):
        new_table, vel = pe.fused_momentum_scatter_apply(
            table, slot_tables["momentum"], unique_ids, row_grads,
            lr=opt.lr, momentum=opt.momentum, nesterov=opt.nesterov,
            interpret=interpret,
        )
        return new_table, {**slot_tables, "momentum": vel}
    new_table = pe.fused_sgd_scatter_apply(
        table, unique_ids, row_grads, lr=opt.lr, interpret=interpret
    )
    return new_table, slot_tables


def fused_apply_supported(opt: RowOptimizer, dim: int) -> bool:
    """Whether the fused scatter-apply kernels cover (opt, dim):
    lane-aligned rows and SGD / Momentum(+Nesterov) — the first fused
    tier (Adam/Adagrad stay on the serial kernels or XLA). When this
    says no, ``sparse_apply(use_pallas='fused')`` falls back to the XLA
    gather→update→scatter path cleanly (no error): 'fused' means
    'fuse where a kernel exists', unlike 'always' which is loud."""
    from elasticdl_tpu.ops import pallas_embedding as pe

    if not pe.dim_supported(dim):
        return False
    return isinstance(opt, (SGD, Momentum))


def kernelizable(opt: RowOptimizer, dim: int) -> bool:
    """Whether the Pallas in-place kernels cover (opt, dim): lane-aligned
    rows and one of SGD / Momentum(+Nesterov) / Adagrad /
    Adam(+amsgrad) — the reference's full C++ kernel family
    (kernel_api.cc), with nothing left on XLA-only."""
    from elasticdl_tpu.ops import pallas_embedding as pe

    if not pe.dim_supported(dim):
        return False
    return isinstance(opt, (SGD, Momentum, Adagrad, Adam))


def sparse_apply(opt: RowOptimizer, table, slot_tables: Dict[str, "jnp.ndarray"],
                 unique_ids, row_grads, step, use_pallas: str = "auto",
                 interpret: bool = False):
    """Scatter-update only ``unique_ids`` rows of a full ``(V, D)`` table.

    ``unique_ids`` must be deduplicated with padding set to an
    OUT-OF-RANGE id (``unique_pad(ids, fill_id=vocab)``): pad gathers
    clamp (their grads are zero so values are irrelevant) and pad
    scatters are dropped — a pad id aliasing a real row would otherwise
    race its duplicate scatter and, for Adam/Adagrad, corrupt slot state
    even with zero grad. The Pallas kernels skip OOR ids outright.

    ``use_pallas``: "auto" takes the XLA gather/scatter path — round-3
    device-time measurement showed the per-row-DMA kernels lose to it
    at every size (the flat-view retiling copy plus ~0.05us/row DMA
    latency; see ops/pallas_embedding.py's dispatch note), overturning
    round-2's wall-clock tiers — unless ``use_pallas_apply`` (the
    fused-kernel sweep predicate) flips for this shape. "fused" takes
    the block-pipelined fused scatter-apply kernels where they exist
    (SGD/Momentum, lane-aligned dim) and falls back to XLA cleanly
    everywhere else. "always" pins the serial kernels (reference
    -parity implementations, on-chip tested); "never" pins XLA
    explicitly.
    """
    if use_pallas not in ("auto", "never", "always", "fused"):
        raise ValueError(f"use_pallas={use_pallas!r}")
    from elasticdl_tpu.ops import pallas_embedding as pe

    dim = int(table.shape[1])
    if use_pallas == "fused" and fused_apply_supported(opt, dim):
        return _fused_sparse_apply(
            opt, table, slot_tables, unique_ids, row_grads,
            interpret=interpret,
        )
    if (
        use_pallas == "auto"
        and fused_apply_supported(opt, dim)
        and pe.use_pallas_apply(dim, int(row_grads.shape[0]))
    ):
        return _fused_sparse_apply(
            opt, table, slot_tables, unique_ids, row_grads,
            interpret=interpret,
        )
    if use_pallas == "always":
        # Fail with a clear message up front, not deep inside
        # pallas_call with an opaque input_output_aliases shape error
        # (mirrors lookup_combine's force_pallas validation).
        if not pe.dim_supported(dim):
            raise ValueError(
                f"use_pallas='always' needs dim % {pe.LANE} == 0, "
                f"got dim={dim}"
            )
        if not kernelizable(opt, dim):
            raise ValueError(
                f"use_pallas='always': no Pallas kernel for "
                f"{type(opt).__name__} (kernelizable() is False)"
            )
        return _pallas_sparse_apply(
            opt, table, slot_tables, unique_ids, row_grads, step,
            interpret=interpret,
        )
    rows = table.at[unique_ids].get(mode="clip")
    slots = {
        name: slot_tables[name].at[unique_ids].get(mode="clip")
        for name in opt.slot_names
    }
    new_rows, new_slots = opt.apply_rows(rows, row_grads, slots, step)
    table = table.at[unique_ids].set(new_rows, mode="drop")
    slot_tables = dict(slot_tables)
    for name in opt.slot_names:
        slot_tables[name] = slot_tables[name].at[unique_ids].set(
            new_slots[name], mode="drop"
        )
    return table, slot_tables


def init_slot_tables(opt: RowOptimizer, vocab: int, dim: int,
                     dtype=jnp.float32) -> Dict[str, "jnp.ndarray"]:
    return {
        name: jnp.full((vocab, dim), slot_init_value(opt, name), dtype)
        for name in opt.slot_names
    }


# ---- packed layout: slots interleaved into the main table rows ----------
#
# XLA's TPU scatter is per-INDEX-latency bound, not bytes bound
# (measured v5e, BASELINE.md round-5: 16384-row scatter into (1M, 256)
# 1.33 ms; into (1M, 512) 1.74 ms — 2x the bytes for 1.3x the time).
# The unpacked apply pays one scatter per table PLUS one per slot table;
# packing every slot next to its row turns (1 + n_slots) scatters (and
# gathers) into ONE of a wider row. Same update math, same touched-row
# contract; the trade is +n_slots x dim bytes per FORWARD lookup row
# (gathers are coalesced and ~9x cheaper per row, so the swap wins by
# ~35% of apply time for Adagrad and more for Adam's 3 tables).


def packed_width(opt: RowOptimizer, dim: int) -> int:
    return dim * (1 + len(opt.slot_names))


def pack_table(table, slot_tables: Dict[str, "jnp.ndarray"],
               opt: RowOptimizer):
    """(V, D) main + per-slot (V, D) -> one (V, D*(1+n_slots)):
    [row | slot0 | slot1 | ...] in ``slot_names`` order."""
    return jnp.concatenate(
        [table] + [slot_tables[n] for n in opt.slot_names], axis=1
    )


def unpack_table(packed, opt: RowOptimizer, dim: int):
    """Inverse of ``pack_table`` (checkpoint interop, tests)."""
    table = packed[:, :dim]
    slots = {
        n: packed[:, dim * (i + 1): dim * (i + 2)]
        for i, n in enumerate(opt.slot_names)
    }
    return table, slots


def sparse_apply_packed(opt: RowOptimizer, packed, unique_ids, row_grads,
                        step, dim: int):
    """``sparse_apply`` over a packed (V, D*(1+n_slots)) store: one
    gather, the row update math, one scatter. Contract matches
    ``sparse_apply`` (globally-unique ids, out-of-range pad sentinel
    rows dropped)."""
    rows_packed = packed.at[unique_ids].get(mode="clip")
    rows, slots = unpack_table(rows_packed, opt, dim)
    new_rows, new_slots = opt.apply_rows(rows, row_grads, slots, step)
    new_packed = pack_table(new_rows, new_slots, opt).astype(packed.dtype)
    return packed.at[unique_ids].set(new_packed, mode="drop")


def unique_pad(ids, fill_id: int):
    """Static-shape dedup: ``jnp.unique`` padded to ``ids.size`` with
    ``fill_id`` (pass the vocab size — an out-of-range sentinel, see
    ``sparse_apply``); returns (unique_ids, inverse) with inverse mapping
    each original position to its unique slot (XLA static-shape
    requirement; reference dedups with dynamic shapes in
    tensor_utils.py:66-101)."""
    flat = jnp.ravel(ids)
    uniq, inverse = jnp.unique(
        flat, size=flat.size, fill_value=fill_id, return_inverse=True
    )
    return uniq, jnp.reshape(inverse, jnp.shape(ids))


# ---- host path: apply to lazy EmbeddingTables ----------------------------


class HostOptimizerWrapper:
    """Apply row updates to host-tier lazy tables
    (OptimizerWrapper.apply_gradients:143 semantics: lookup rows, create
    slots lazily, apply, write rows+slots back)."""

    def __init__(self, opt: RowOptimizer):
        self.opt = opt
        self._slot_tables: Dict[str, EmbeddingTable] = {}
        # Per-table apply counts: one wrapper serves many tables, and Adam
        # bias correction needs each table's own step (the reference's
        # optimizer.iterations covers all variables of one training step;
        # per-table counting is equivalent when every table is updated
        # each step and correct when some are skipped).
        self._steps: Dict[str, int] = {}

    def _slot_table(self, table: EmbeddingTable, slot_name: str):
        key = get_slot_table_name(table.name, slot_name)
        if key not in self._slot_tables:
            make = getattr(table, "make_slot_table", None)
            if make is not None:
                # Tiered primaries (storage/tiered.py) create their
                # slots inside their own TierGroup: a demoted row must
                # take its optimizer state with it, and a fault must
                # bring it back — lockstep only holds when the slot
                # shares the primary's recency map and budget.
                self._slot_tables[key] = make(
                    key, slot_init_value(self.opt, slot_name)
                )
                return self._slot_tables[key]
            st = EmbeddingTable(
                key,
                table.dim,
                is_slot=True,
                slot_init_value=slot_init_value(self.opt, slot_name),
                dtype=table.dtype,
            )
            if getattr(table, "supports_dirty_rows", False):
                # A slot created after checkpointing was configured
                # inherits tracking from its main table, or its rows
                # would never ride a delta.
                st.enable_dirty_tracking()
            self._slot_tables[key] = st
        return self._slot_tables[key]

    def apply_gradients(self, table: EmbeddingTable, ids, grads):
        """ids must be unique; grads is (len(ids), dim)."""
        ids = [int(i) for i in ids]
        if len(set(ids)) != len(ids):
            raise ValueError("ids must be deduplicated before apply")
        step = self._steps.get(table.name, 0) + 1
        self._steps[table.name] = step
        # Tiered tables: defer every per-get/set budget sweep to ONE
        # sweep after the whole apply (or to the row-service handler's
        # post-lock maybe_sweep when defer_apply_sweep is set) —
        # otherwise each of these 2+2*slots calls runs eviction's
        # cold-tier writes inside whatever lock the caller holds.
        tiered = hasattr(table, "maybe_sweep")
        kw = {"_defer_sweep": True} if tiered else {}
        rows = table.get(ids, **kw)
        slots = {
            name: self._slot_table(table, name).get(ids, **kw)
            for name in self.opt.slot_names
        }
        new_rows, new_slots = self.opt.apply_rows(
            rows, np.asarray(grads, table.dtype), slots, step
        )
        table.set(ids, np.asarray(new_rows), **kw)
        for name in self.opt.slot_names:
            self._slot_table(table, name).set(
                ids, np.asarray(new_slots[name]), **kw
            )
        if tiered and not table.defer_apply_sweep:
            table.maybe_sweep()
        return table

    def state_tables(self, main_tables: Dict) -> Dict:
        """Slot tables + step counters for checkpointing (see
        wrapper_state_tables)."""
        return wrapper_state_tables(self, main_tables)


# ---- checkpoint integration ----------------------------------------------

STEPS_TABLE_NAME = "__row_optimizer_steps__"


class _StepCountersTable:
    """Checkpoint adapter persisting a wrapper's per-table apply counts
    as a dim-1 table (row id = crc32 of the main-table name). Exposes
    exactly the to_arrays/set surface the checkpoint hook and
    restore_from_dir use, so step counts ride the normal embeddings
    payload (Adam bias correction must not restart at 1 after a
    relaunch)."""

    dim = 1

    def __init__(self, wrapper, table_names):
        import zlib

        self._wrapper = wrapper
        self._name_of = {
            zlib.crc32(name.encode("utf-8")): name for name in table_names
        }
        if len(self._name_of) < len(list(table_names)):
            raise ValueError(
                f"table-name hash collision among {list(table_names)}"
            )

    def to_arrays(self):
        items = sorted(
            (tid, self._wrapper._steps[name])
            for tid, name in self._name_of.items()
            if self._wrapper._steps.get(name)
        )
        ids = np.array([t for t, _ in items], np.int64)
        rows = np.array([[s] for _, s in items], np.float64).reshape(-1, 1)
        return ids, rows

    def set(self, ids, values):
        values = np.asarray(values).reshape(len(list(ids)), -1)
        for tid, row in zip(ids, values):
            name = self._name_of.get(int(tid))
            if name is not None:
                self._wrapper._steps[name] = int(round(float(row[0])))


def wrapper_state_tables(wrapper, main_tables: Dict) -> Dict:
    """Slot tables + step counters of a host/native optimizer wrapper,
    keyed for the checkpoint embeddings payload. Pre-creates every slot
    table for ``main_tables`` so a FRESH wrapper (relaunch path) has
    live objects for restore to refill."""
    for table in main_tables.values():
        for slot in wrapper.opt.slot_names:
            wrapper._slot_table(table, slot)
    out = dict(wrapper._slot_tables)
    out[STEPS_TABLE_NAME] = _StepCountersTable(wrapper, list(main_tables))
    return out
