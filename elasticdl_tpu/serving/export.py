"""Serving-bundle export: the TPU-native SavedModel equivalent.

The reference exports a tf SavedModel at train end (reference
callbacks.py:26-54, common/model_handler.py:234-276 restores Keras embeddings
before export). The TPU-native artifact is a directory:

- ``params.msgpack``   — flax-serialized trained params (+ batch_stats),
- ``metadata.json``    — model version, model_def, feature shape signature,
- ``predict.stablehlo``— a ``jax.export`` serialized compilation of the
  predict function, self-contained: loadable and callable with NO access to
  the user's model-zoo code, which is what makes it a SavedModel equivalent
  rather than a checkpoint.

``load_predictor`` prefers the StableHLO artifact and falls back to
re-applying the flax module when the caller passes one (the checkpoint-style
path, mirroring the reference's restore-then-export flow
save_utils.py:206-259).
"""

import json
import os
from typing import Any, Callable, Optional

import jax
import numpy as np
from flax import serialization

PARAMS_FILE = "params.msgpack"
META_FILE = "metadata.json"
HLO_FILE = "predict.stablehlo"


def _predict_fn(model):
    def predict(variables, features):
        return model.apply(variables, features, training=False)

    return predict


def _variables(state):
    variables = {"params": state.params}
    if getattr(state, "batch_stats", None):
        variables["batch_stats"] = state.batch_stats
    return variables


def export_serving_bundle(
    output_dir: str,
    model: Any,
    state: Any,
    batch_example: Optional[Any] = None,
    model_def: str = "",
) -> str:
    """Write the serving bundle; returns ``output_dir``."""
    os.makedirs(output_dir, exist_ok=True)
    variables = _variables(state)
    with open(os.path.join(output_dir, PARAMS_FILE), "wb") as f:
        f.write(serialization.to_bytes(variables))

    meta = {
        "model_version": int(state.step),
        "model_def": model_def,
        "format": 1,
    }
    hlo_written = False
    if model is not None and batch_example is not None:
        features = batch_example.get("features", batch_example)
        var_shapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), variables
        )
        feat_shapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype),
            features,
        )
        exported = jax.export.export(jax.jit(_predict_fn(model)))(
            var_shapes, feat_shapes
        )
        with open(os.path.join(output_dir, HLO_FILE), "wb") as f:
            f.write(exported.serialize())
        hlo_written = True
        meta["batch_size"] = int(
            jax.tree.leaves(features)[0].shape[0]
            if jax.tree.leaves(features)
            else 0
        )
    meta["self_contained"] = hlo_written
    with open(os.path.join(output_dir, META_FILE), "w") as f:
        json.dump(meta, f, indent=1)
    return output_dir


def load_predictor(
    bundle_dir: str, model: Any = None
) -> Callable[[Any], Any]:
    """Load a bundle as ``predict(features) -> predictions``.

    With a StableHLO artifact the returned callable is fully standalone;
    otherwise ``model`` (the same flax module used at export) is required.
    """
    with open(os.path.join(bundle_dir, META_FILE)) as f:
        meta = json.load(f)
    with open(os.path.join(bundle_dir, PARAMS_FILE), "rb") as f:
        raw = f.read()
    hlo_path = os.path.join(bundle_dir, HLO_FILE)
    if meta.get("self_contained") and os.path.exists(hlo_path):
        with open(hlo_path, "rb") as f:
            exported = jax.export.deserialize(bytearray(f.read()))
        variables = serialization.msgpack_restore(raw)
        return lambda features: exported.call(variables, features)
    if model is None:
        raise ValueError(
            f"Bundle {bundle_dir} has no StableHLO artifact; pass the flax "
            "module via `model` to rebuild the predictor"
        )
    variables = serialization.msgpack_restore(raw)
    predict = jax.jit(_predict_fn(model))
    return lambda features: predict(variables, features)
