"""Serving-bundle export: the TPU-native SavedModel equivalent.

The reference exports a tf SavedModel at train end (reference
callbacks.py:26-54, common/model_handler.py:234-276 restores Keras embeddings
before export). The TPU-native artifact is a directory:

- ``params.msgpack``   — flax-serialized trained params (+ batch_stats),
- ``metadata.json``    — model version, model_def, feature shape signature,
- ``predict.stablehlo``— a ``jax.export`` serialized compilation of the
  predict function, self-contained: loadable and callable with NO access to
  the user's model-zoo code, which is what makes it a SavedModel equivalent
  rather than a checkpoint.

``load_predictor`` prefers the StableHLO artifact and falls back to
re-applying the flax module when the caller passes one (the checkpoint-style
path, mirroring the reference's restore-then-export flow
save_utils.py:206-259).
"""

import json
import os
from typing import Any, Callable, Optional

import jax
import numpy as np
from flax import serialization

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("serving_export")

PARAMS_FILE = "params.msgpack"
META_FILE = "metadata.json"
HLO_FILE = "predict.stablehlo"


def _predict_fn(model):
    def predict(variables, features):
        return model.apply(variables, features, training=False)

    return predict


def _variables(state):
    variables = {"params": state.params}
    if getattr(state, "batch_stats", None):
        variables["batch_stats"] = state.batch_stats
    return variables


def _clone_empty(table):
    """Fresh table of the same type AND configuration (initializer,
    slot settings, dtype) — the lazy init for untouched ids must match
    the live table exactly."""
    return type(table)(
        table.name,
        table.dim,
        initializer=getattr(table, "initializer", "uniform"),
        is_slot=getattr(table, "is_slot", False),
        slot_init_value=getattr(table, "slot_init_value", 0.0),
        dtype=getattr(table, "dtype", np.float32),
    )


def _dense_overlay(table, vocab: int, chunk: int):
    """Dense (vocab, dim) WITHOUT touching the live table: trained rows
    come from a to_arrays snapshot; untouched ids materialize through
    per-chunk THROWAWAY tables with identical configuration (identical
    deterministic lazy init), so neither the live store nor any single
    throwaway inflates to full vocab."""
    ids, rows = table.to_arrays()
    parts = []
    for lo in range(0, int(vocab), chunk):
        hi = min(lo + chunk, vocab)
        # Fresh throwaway per chunk: a reused one would retain every
        # lazily inserted row and grow to full vocab itself.
        parts.append(
            np.asarray(_clone_empty(table).get(np.arange(lo, hi)))
        )
    dense = np.concatenate(parts, axis=0)
    keep = (ids >= 0) & (ids < vocab)
    dense[ids[keep]] = rows[keep]
    return dense


def materialize_host_rows(tables, vocab_sizes, chunk: int = 65536,
                          lock=None):
    """Full dense (vocab, dim) arrays from host/remote tables — the
    reference export path's EmbeddingTable→dense-weights conversion
    (model_handler.py:31-46, :234-260). Untouched ids materialize from
    the lazy initializer, like the reference, WITHOUT inserting them
    into the live store (export must not blow a >HBM table up to full
    vocab, nor race training threads — pass the engine lock)."""
    import contextlib

    missing = set(vocab_sizes) - set(tables)
    if missing:
        raise ValueError(
            f"host_serving_vocab names unknown tables {sorted(missing)}; "
            f"model tables: {sorted(tables)}"
        )
    out = {}
    for name, vocab in vocab_sizes.items():
        table = tables[name]
        if hasattr(table, "export_dense"):
            # Remote table: the service materializes server-side.
            out[name] = table.export_dense(int(vocab), chunk)
            continue
        with (lock if lock is not None else contextlib.nullcontext()):
            out[name] = _dense_overlay(table, int(vocab), chunk)
    return out


def export_serving_bundle(
    output_dir: str,
    model: Any,
    state: Any,
    batch_example: Optional[Any] = None,
    model_def: str = "",
    host_tables: Optional[dict] = None,
    host_vocab: Optional[dict] = None,
    host_lock=None,
) -> str:
    """Write the serving bundle; returns ``output_dir``.

    ``host_tables``+``host_vocab`` (host-tier models): each table is
    materialized dense into the ``host_rows`` collection so the bundle
    is standalone and serves raw ids (requires ``batch_example`` for
    the collection template; ``host_lock`` guards live tables)."""
    os.makedirs(output_dir, exist_ok=True)
    if batch_example is not None and not (
        isinstance(batch_example, dict) and "features" in batch_example
    ):
        batch_example = {"features": batch_example}
    variables = _variables(state)
    if host_tables and host_vocab and batch_example is not None:
        from elasticdl_tpu.embedding.host_engine import (
            HOST_ROWS_COLLECTION,
            _nest_rows,
            host_rows_template,
        )

        template = host_rows_template(model, batch_example)
        from elasticdl_tpu.embedding.host_engine import _iter_leaves

        model_tables = {k for k, _ in _iter_leaves(template)}
        if model_tables - set(host_vocab):
            raise ValueError(
                "host_serving_vocab is missing entries for model "
                f"tables {sorted(model_tables - set(host_vocab))}"
            )
        flat = materialize_host_rows(
            host_tables, host_vocab, lock=host_lock
        )
        variables[HOST_ROWS_COLLECTION] = _nest_rows(template, flat)
    with open(os.path.join(output_dir, PARAMS_FILE), "wb") as f:
        f.write(serialization.to_bytes(variables))

    meta = {
        "model_version": int(state.step),
        "model_def": model_def,
        "format": 1,
    }
    hlo_written = False
    if host_tables and host_vocab and batch_example is None:
        # No example -> no collection template: the host model cannot
        # trace (HostEmbedding reads the host_rows collection), so the
        # bundle degrades to params-only.
        model = None
    if model is not None and batch_example is not None:
        features = batch_example["features"]
        var_shapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), variables
        )

        leaves = jax.tree.leaves(features)
        example_batch_dim = (
            np.shape(leaves[0])[0]
            if leaves and np.ndim(leaves[0]) else 0
        )

        def feat_shapes_with(batch_dim):
            # Only leaves that actually carry the batch dim get the
            # symbolic size; scalars / non-batch leaves keep their
            # static shapes.
            def leaf_shape(x):
                shape = tuple(np.shape(x))
                if shape and shape[0] == example_batch_dim:
                    shape = (batch_dim,) + shape[1:]
                return jax.ShapeDtypeStruct(shape, np.asarray(x).dtype)

            return jax.tree.map(leaf_shape, features)

        # Prefer a batch-POLYMORPHIC artifact (serves any batch size —
        # the reference's SavedModel signatures had a None batch dim);
        # fall back to the example's static batch if the model's
        # computation can't be traced with a symbolic dim.
        export_fn = jax.export.export(jax.jit(_predict_fn(model)))
        batch_polymorphic = False
        try:
            sym_b = jax.export.symbolic_shape("b")[0]
            exported = export_fn(var_shapes, feat_shapes_with(sym_b))
            batch_polymorphic = True
        except Exception as exc:
            logger.warning(
                "Batch-polymorphic export failed (%s: %s); falling back "
                "to the example's static batch size %d — the bundle "
                "serves ONLY that batch size",
                type(exc).__name__, exc, example_batch_dim,
            )
            exported = export_fn(
                var_shapes, feat_shapes_with(example_batch_dim)
            )
        with open(os.path.join(output_dir, HLO_FILE), "wb") as f:
            f.write(exported.serialize())
        hlo_written = True
        meta["batch_polymorphic"] = batch_polymorphic
        meta["batch_size"] = int(
            jax.tree.leaves(features)[0].shape[0]
            if jax.tree.leaves(features)
            else 0
        )
    meta["self_contained"] = hlo_written
    with open(os.path.join(output_dir, META_FILE), "w") as f:
        json.dump(meta, f, indent=1)
    return output_dir


def load_predictor(
    bundle_dir: str, model: Any = None
) -> Callable[[Any], Any]:
    """Load a bundle as ``predict(features) -> predictions``.

    With a StableHLO artifact the returned callable is fully standalone;
    otherwise ``model`` (the same flax module used at export) is required.
    """
    with open(os.path.join(bundle_dir, META_FILE)) as f:
        meta = json.load(f)
    with open(os.path.join(bundle_dir, PARAMS_FILE), "rb") as f:
        raw = f.read()
    hlo_path = os.path.join(bundle_dir, HLO_FILE)
    if meta.get("self_contained") and os.path.exists(hlo_path):
        with open(hlo_path, "rb") as f:
            exported = jax.export.deserialize(bytearray(f.read()))
        variables = serialization.msgpack_restore(raw)
        return lambda features: exported.call(variables, features)
    if model is None:
        raise ValueError(
            f"Bundle {bundle_dir} has no StableHLO artifact; pass the flax "
            "module via `model` to rebuild the predictor"
        )
    variables = serialization.msgpack_restore(raw)
    predict = jax.jit(_predict_fn(model))
    return lambda features: predict(variables, features)
