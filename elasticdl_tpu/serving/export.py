"""Serving-bundle export: the TPU-native SavedModel equivalent.

The reference exports a tf SavedModel at train end (reference
callbacks.py:26-54, common/model_handler.py:234-276 restores Keras embeddings
before export). The TPU-native artifact is a directory:

- ``params.msgpack``   — flax-serialized trained params (+ batch_stats),
- ``metadata.json``    — model version, model_def, feature shape signature,
- ``predict.stablehlo``— a ``jax.export`` serialized compilation of the
  predict function, self-contained: loadable and callable with NO access to
  the user's model-zoo code, which is what makes it a SavedModel equivalent
  rather than a checkpoint.

``load_predictor`` prefers the StableHLO artifact and falls back to
re-applying the flax module when the caller passes one (the checkpoint-style
path, mirroring the reference's restore-then-export flow
save_utils.py:206-259).
"""

import json
import os
from typing import Any, Callable, Optional

import jax
import jax.export  # not auto-imported by `import jax`; used via jax.export.*
import numpy as np
from flax import serialization

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("serving_export")

PARAMS_FILE = "params.msgpack"
META_FILE = "metadata.json"
HLO_FILE = "predict.stablehlo"

# Row-service serving mode: the exported predict takes each host
# table's row block as an EXTRA feature under this key prefix (with a
# symbolic leading dim), so the online server can pull fresh rows from
# embedding/row_service.py per request instead of baking a dense
# (vocab, dim) copy into the bundle.
HOST_ROWS_FEATURE_PREFIX = "__host_rows__:"


def _predict_fn(model):
    def predict(variables, features):
        return model.apply(variables, features, training=False)

    return predict


def _variables(state):
    variables = {"params": state.params}
    if getattr(state, "batch_stats", None):
        variables["batch_stats"] = state.batch_stats
    return variables


def _clone_empty(table):
    """Fresh table of the same type AND configuration (initializer,
    slot settings, dtype) — the lazy init for untouched ids must match
    the live table exactly. A tiered table clones from its HOT tier's
    type (storage/tiered.py): the inner table owns lazy init, and a
    throwaway must not drag a cold store along."""
    table = getattr(table, "hot_inner", table)
    return type(table)(
        table.name,
        table.dim,
        initializer=getattr(table, "initializer", "uniform"),
        is_slot=getattr(table, "is_slot", False),
        slot_init_value=getattr(table, "slot_init_value", 0.0),
        dtype=getattr(table, "dtype", np.float32),
    )


def _dense_overlay(table, vocab: int, chunk: int):
    """Dense (vocab, dim) WITHOUT touching the live table: trained rows
    come from a to_arrays snapshot; untouched ids materialize through
    per-chunk THROWAWAY tables with identical configuration (identical
    deterministic lazy init), so neither the live store nor any single
    throwaway inflates to full vocab."""
    ids, rows = table.to_arrays()
    parts = []
    for lo in range(0, int(vocab), chunk):
        hi = min(lo + chunk, vocab)
        # Fresh throwaway per chunk: a reused one would retain every
        # lazily inserted row and grow to full vocab itself.
        parts.append(
            np.asarray(_clone_empty(table).get(np.arange(lo, hi)))
        )
    dense = np.concatenate(parts, axis=0)
    keep = (ids >= 0) & (ids < vocab)
    dense[ids[keep]] = rows[keep]
    return dense


def materialize_host_rows(tables, vocab_sizes, chunk: int = 65536,
                          lock=None):
    """Full dense (vocab, dim) arrays from host/remote tables — the
    reference export path's EmbeddingTable→dense-weights conversion
    (model_handler.py:31-46, :234-260). Untouched ids materialize from
    the lazy initializer, like the reference, WITHOUT inserting them
    into the live store (export must not blow a >HBM table up to full
    vocab, nor race training threads — pass the engine lock)."""
    import contextlib

    missing = set(vocab_sizes) - set(tables)
    if missing:
        raise ValueError(
            f"host_serving_vocab names unknown tables {sorted(missing)}; "
            f"model tables: {sorted(tables)}"
        )
    out = {}
    for name, vocab in vocab_sizes.items():
        table = tables[name]
        if hasattr(table, "export_dense"):
            # Remote table: the service materializes server-side.
            out[name] = table.export_dense(int(vocab), chunk)
            continue
        with (lock if lock is not None else contextlib.nullcontext()):
            out[name] = _dense_overlay(table, int(vocab), chunk)
    return out


def _feature_signature(features, batch_dim: int):
    """JSON-able {shape, dtype} tree of the predict input; the leading
    dim is ``None`` where it carries the batch (the reference
    SavedModel signature's None batch dim). Lets the serving plane
    coerce JSON payloads and synthesize load-generator traffic without
    the model code."""

    def leaf(x):
        shape = list(np.shape(x))
        if shape and shape[0] == batch_dim:
            shape[0] = None
        return {"shape": shape, "dtype": np.asarray(x).dtype.name}

    return jax.tree.map(leaf, features)


def export_serving_bundle(
    output_dir: str,
    model: Any,
    state: Any,
    batch_example: Optional[Any] = None,
    model_def: str = "",
    host_tables: Optional[dict] = None,
    host_vocab: Optional[dict] = None,
    host_lock=None,
    host_id_keys: Optional[dict] = None,
) -> str:
    """Write the serving bundle; returns ``output_dir``.

    ``host_tables``+``host_vocab`` (host-tier models): each table is
    materialized dense into the ``host_rows`` collection so the bundle
    is standalone and serves raw ids (requires ``batch_example`` for
    the collection template; ``host_lock`` guards live tables).

    ``host_id_keys`` ({table: feature key}) exports the ROW-SERVICE
    serving mode instead: no rows are baked in; the predict artifact
    takes each table's row block as an extra feature with a SYMBOLIC
    leading dim (``HOST_ROWS_FEATURE_PREFIX + table``), and the online
    server resolves raw ids against a live ``HostRowService`` per
    request (dedup -> pull -> bucket-pad; serving/model_store.py).
    This is the servable shape for host-partitioned tables too large
    to materialize dense. Mutually exclusive with ``host_tables``."""
    os.makedirs(output_dir, exist_ok=True)
    if host_id_keys and host_tables:
        raise ValueError(
            "host_id_keys (row-service serving) and host_tables "
            "(materialized dense rows) are mutually exclusive"
        )
    if host_id_keys:
        if batch_example is None:
            raise ValueError("host_id_keys export requires batch_example")
        return _export_row_service_bundle(
            output_dir, model, state, batch_example, model_def,
            host_id_keys,
        )
    if batch_example is not None and not (
        isinstance(batch_example, dict) and "features" in batch_example
    ):
        batch_example = {"features": batch_example}
    variables = _variables(state)
    if host_tables and host_vocab and batch_example is not None:
        from elasticdl_tpu.embedding.host_engine import (
            HOST_ROWS_COLLECTION,
            _nest_rows,
            host_rows_template,
        )

        template = host_rows_template(model, batch_example)
        from elasticdl_tpu.embedding.host_engine import _iter_leaves

        model_tables = {k for k, _ in _iter_leaves(template)}
        if model_tables - set(host_vocab):
            raise ValueError(
                "host_serving_vocab is missing entries for model "
                f"tables {sorted(model_tables - set(host_vocab))}"
            )
        flat = materialize_host_rows(
            host_tables, host_vocab, lock=host_lock
        )
        variables[HOST_ROWS_COLLECTION] = _nest_rows(template, flat)
    with open(os.path.join(output_dir, PARAMS_FILE), "wb") as f:
        f.write(serialization.to_bytes(variables))

    meta = {
        "model_version": int(state.step),
        "model_def": model_def,
        "format": 1,
    }
    if batch_example is not None:
        leaves = jax.tree.leaves(batch_example["features"])
        batch_dim = (
            np.shape(leaves[0])[0] if leaves and np.ndim(leaves[0]) else 0
        )
        meta["feature_signature"] = _feature_signature(
            batch_example["features"], batch_dim
        )
    hlo_written = False
    if host_tables and host_vocab and batch_example is None:
        # No example -> no collection template: the host model cannot
        # trace (HostEmbedding reads the host_rows collection), so the
        # bundle degrades to params-only.
        model = None
    if model is not None and batch_example is not None:
        features = batch_example["features"]
        var_shapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), variables
        )

        leaves = jax.tree.leaves(features)
        example_batch_dim = (
            np.shape(leaves[0])[0]
            if leaves and np.ndim(leaves[0]) else 0
        )

        def feat_shapes_with(batch_dim):
            # Only leaves that actually carry the batch dim get the
            # symbolic size; scalars / non-batch leaves keep their
            # static shapes.
            def leaf_shape(x):
                shape = tuple(np.shape(x))
                if shape and shape[0] == example_batch_dim:
                    shape = (batch_dim,) + shape[1:]
                return jax.ShapeDtypeStruct(shape, np.asarray(x).dtype)

            return jax.tree.map(leaf_shape, features)

        # Prefer a batch-POLYMORPHIC artifact (serves any batch size —
        # the reference's SavedModel signatures had a None batch dim);
        # fall back to the example's static batch if the model's
        # computation can't be traced with a symbolic dim.
        export_fn = jax.export.export(jax.jit(_predict_fn(model)))
        batch_polymorphic = False
        try:
            sym_b = jax.export.symbolic_shape("b")[0]
            exported = export_fn(var_shapes, feat_shapes_with(sym_b))
            batch_polymorphic = True
        except Exception as exc:
            logger.warning(
                "Batch-polymorphic export failed (%s: %s); falling back "
                "to the example's static batch size %d — the bundle "
                "serves ONLY that batch size",
                type(exc).__name__, exc, example_batch_dim,
            )
            exported = export_fn(
                var_shapes, feat_shapes_with(example_batch_dim)
            )
        with open(os.path.join(output_dir, HLO_FILE), "wb") as f:
            f.write(exported.serialize())
        hlo_written = True
        meta["batch_polymorphic"] = batch_polymorphic
        meta["batch_size"] = int(
            jax.tree.leaves(features)[0].shape[0]
            if jax.tree.leaves(features)
            else 0
        )
    meta["self_contained"] = hlo_written
    with open(os.path.join(output_dir, META_FILE), "w") as f:
        json.dump(meta, f, indent=1)
    return output_dir


def _export_row_service_bundle(
    output_dir: str, model: Any, state: Any, batch_example: Any,
    model_def: str, host_id_keys: dict,
) -> str:
    """The ``host_id_keys`` arm of ``export_serving_bundle``: trace
    predict with the per-table row blocks as extra features whose
    leading dim is SYMBOLIC, so ONE StableHLO artifact serves every
    (batch bucket, row bucket) combination the online batcher produces.
    The bundle stays standalone (no zoo code at serve time); only the
    rows live elsewhere — on the row service, pulled per request."""
    from elasticdl_tpu.embedding.host_engine import (
        HOST_ROWS_COLLECTION,
        _iter_leaves,
        _nest_rows,
        host_rows_template,
    )

    if not (isinstance(batch_example, dict)
            and "features" in batch_example):
        batch_example = {"features": batch_example}
    template = host_rows_template(model, batch_example)
    table_dims = {k: int(np.shape(v)[-1])
                  for k, v in _iter_leaves(template)}
    mismatch = set(table_dims) ^ set(host_id_keys)
    if mismatch:
        raise ValueError(
            f"host_id_keys must name exactly the model's host tables "
            f"{sorted(table_dims)}; mismatched: {sorted(mismatch)}"
        )
    variables = _variables(state)
    with open(os.path.join(output_dir, PARAMS_FILE), "wb") as f:
        f.write(serialization.to_bytes(variables))

    names = sorted(table_dims)

    def predict(variables, features):
        features = dict(features)
        flat_rows = {
            name: features.pop(HOST_ROWS_FEATURE_PREFIX + name)
            for name in names
        }
        merged = dict(variables)
        merged[HOST_ROWS_COLLECTION] = _nest_rows(template, flat_rows)
        return model.apply(merged, features, training=False)

    features = dict(batch_example["features"])
    leaves = jax.tree.leaves(features)
    example_batch_dim = (
        np.shape(leaves[0])[0] if leaves and np.ndim(leaves[0]) else 0
    )
    var_shapes = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), variables
    )

    def feat_shapes_with(batch_dim, row_dims):
        def leaf_shape(x):
            shape = tuple(np.shape(x))
            if shape and shape[0] == example_batch_dim:
                shape = (batch_dim,) + shape[1:]
            return jax.ShapeDtypeStruct(shape, np.asarray(x).dtype)

        shapes = jax.tree.map(leaf_shape, features)
        for name, row_dim in zip(names, row_dims):
            shapes[HOST_ROWS_FEATURE_PREFIX + name] = (
                jax.ShapeDtypeStruct(
                    (row_dim, table_dims[name]), np.float32
                )
            )
        return shapes

    export_fn = jax.export.export(jax.jit(predict))
    # One scope for every symbol (jax.export requires it); row-bucket
    # dims are ALWAYS symbolic (the whole point of this mode), the
    # batch dim preferably so.
    syms = jax.export.symbolic_shape(
        ", ".join(["b"] + [f"u{i}" for i in range(len(names))])
    )
    batch_polymorphic = True
    try:
        exported = export_fn(
            var_shapes, feat_shapes_with(syms[0], syms[1:])
        )
    except Exception as exc:
        logger.warning(
            "Batch-polymorphic row-service export failed (%s: %s); "
            "retrying with the example's static batch size %d",
            type(exc).__name__, exc, example_batch_dim,
        )
        syms = jax.export.symbolic_shape(
            ", ".join(f"u{i}" for i in range(len(names)))
        )
        exported = export_fn(
            var_shapes, feat_shapes_with(example_batch_dim, syms)
        )
        batch_polymorphic = False
    with open(os.path.join(output_dir, HLO_FILE), "wb") as f:
        f.write(exported.serialize())

    meta = {
        "model_version": int(state.step),
        "model_def": model_def,
        "format": 1,
        "self_contained": True,
        "batch_polymorphic": batch_polymorphic,
        "batch_size": int(example_batch_dim),
        "feature_signature": _feature_signature(
            features, example_batch_dim
        ),
        "host_serving": {
            "id_keys": dict(host_id_keys),
            "tables": table_dims,
            "rows_feature_prefix": HOST_ROWS_FEATURE_PREFIX,
        },
    }
    with open(os.path.join(output_dir, META_FILE), "w") as f:
        json.dump(meta, f, indent=1)
    return output_dir


def load_predictor(
    bundle_dir: str, model: Any = None
) -> Callable[[Any], Any]:
    """Load a bundle as ``predict(features) -> predictions``.

    With a StableHLO artifact the returned callable is fully standalone;
    otherwise ``model`` (the same flax module used at export) is required.
    """
    with open(os.path.join(bundle_dir, META_FILE)) as f:
        meta = json.load(f)
    with open(os.path.join(bundle_dir, PARAMS_FILE), "rb") as f:
        raw = f.read()
    hlo_path = os.path.join(bundle_dir, HLO_FILE)
    if meta.get("self_contained") and os.path.exists(hlo_path):
        with open(hlo_path, "rb") as f:
            exported = jax.export.deserialize(bytearray(f.read()))
        variables = serialization.msgpack_restore(raw)
        return lambda features: exported.call(variables, features)
    if model is None:
        raise ValueError(
            f"Bundle {bundle_dir} has no StableHLO artifact; pass the flax "
            "module via `model` to rebuild the predictor"
        )
    variables = serialization.msgpack_restore(raw)
    predict = jax.jit(_predict_fn(model))
    return lambda features: predict(variables, features)
