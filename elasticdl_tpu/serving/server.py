"""Online inference server: adaptive micro-batching over a bundle.

The reference handed its SavedModel to TF Serving; this is the
TPU-native equivalent over ``serving/export.py`` bundles, shaped like
the batched-actor serving loop in Podracer (arxiv 2104.06272): request
handler threads enqueue, ONE batcher thread drains, so the compiled
predict function always sees a single in-flight program.

- **Transport**: stdlib ``ThreadingHTTPServer``. ``POST /v1/predict``
  takes msgpack (``application/x-msgpack``, the framework's tensor
  serde — ndarrays ride raw buffers) or JSON (lists, coerced to the
  bundle's recorded feature signature). ``GET /v1/models`` lists
  resident versions; ``POST /v1/models/rollback`` swaps back one
  version; ``/metrics`` + ``/healthz`` expose the process registry so
  the serving families land on the SAME endpoint the rest of the
  telemetry plane uses (docs/observability.md).
- **Adaptive micro-batching**: requests accumulate until either
  ``max_batch_size`` examples are waiting or ``batch_deadline_ms`` has
  passed since the OLDEST queued request arrived — flush on size fills
  the device at load, flush on deadline bounds p99 when idle.
- **Bucketed shapes**: the combined batch pads up to a power-of-two
  bucket (clamped to ``max_batch_size``; non-polymorphic bundles pad
  to their one exported batch size), so the artifact compiles
  O(log max_batch) programs total instead of one per occupancy.
- **Backpressure**: the request queue is bounded; when it saturates,
  new requests are shed immediately with 429 (the client's signal to
  back off) rather than queued into a latency cliff, and the depth
  gauge + shed counter make the saturation visible on ``/metrics``.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("serving")

MSGPACK_CONTENT_TYPE = "application/x-msgpack"

# Batch-occupancy buckets: powers of two up to the largest batch any
# config uses (the registry default buckets are latency-shaped).
_BATCH_BUCKETS = tuple(float(2 ** i) for i in range(13))


def _tree_leaves_equal_structure(a, b) -> bool:
    import jax

    return (jax.tree.structure(a) == jax.tree.structure(b))


def _batch_dim(features) -> int:
    import jax

    leaves = jax.tree.leaves(features)
    if not leaves or np.ndim(leaves[0]) == 0:
        raise ValueError("features must carry a leading batch dim")
    n = int(np.shape(leaves[0])[0])
    for leaf in leaves:
        if np.ndim(leaf) == 0 or int(np.shape(leaf)[0]) != n:
            raise ValueError(
                "all feature leaves must share the leading batch dim"
            )
    return n


def _concat_trees(trees):
    import jax

    return jax.tree.map(
        lambda *xs: np.concatenate([np.asarray(x) for x in xs], axis=0),
        *trees,
    )


def _pad_tree(features, target: int, n: int):
    """Pad the batch dim from ``n`` to ``target`` by repeating row 0 —
    real vocabulary ids, so padding never widens the unique-id set a
    sparse resolver pulls."""
    import jax

    if target == n:
        return features

    def pad(x):
        x = np.asarray(x)
        reps = np.repeat(x[:1], target - n, axis=0)
        return np.concatenate([x, reps], axis=0)

    return jax.tree.map(pad, features)


def _slice_tree(outputs, lo: int, hi: int):
    import jax

    return jax.tree.map(lambda x: np.asarray(x)[lo:hi], outputs)


def _coerce_signature(features, signature):
    """Cast a JSON payload (nested lists) onto the bundle's recorded
    dtypes; msgpack payloads arrive typed and pass through."""
    import jax

    if signature is None:
        return jax.tree.map(np.asarray, features)

    def leaf(x, spec):
        arr = np.asarray(x)
        if isinstance(spec, dict) and "dtype" in spec:
            arr = arr.astype(spec["dtype"])
        return arr

    return jax.tree.map(
        leaf, features, signature,
        is_leaf=lambda x: not isinstance(x, dict),
    )


class _Request:
    __slots__ = ("features", "n", "event", "outputs", "error",
                 "version", "enqueued_at", "cancelled", "trace_ctx")

    def __init__(self, features, n: int):
        self.features = features
        self.n = n
        self.event = threading.Event()
        self.outputs = None
        self.error: Optional[Exception] = None
        self.version = 0
        self.enqueued_at = time.monotonic()
        # Set when the submitting handler gave up (timeout): the
        # batcher drops it instead of computing dead work — under
        # sustained overload that dead work is what keeps the server
        # from ever recovering goodput.
        self.cancelled = False
        # (trace_id, span_id) of the submitting handler's request span
        # when tracing is on — the batcher thread retro-records
        # queue-wait / batch spans against it (spans can't ride the
        # thread-local context across the handoff).
        self.trace_ctx = None


class BatchingPredictor:
    """The queue + batcher half of the server, transport-agnostic (the
    HTTP layer and tests drive it directly). ``submit`` blocks the
    calling handler thread until its slice of a flushed batch returns;
    ``QueueFullError`` is the load-shed signal (HTTP 429)."""

    class QueueFullError(RuntimeError):
        """Load-shed signal (HTTP 429). ``retry_after`` is the
        suggested client backoff in seconds (the 429's Retry-After
        header); ``tier`` names which shed tier fired."""

        def __init__(self, message: str, tier: str = "capacity",
                     retry_after: float = 1.0):
            super().__init__(message)
            self.tier = tier
            self.retry_after = float(retry_after)

    def __init__(self, store, max_batch_size: int = 64,
                 batch_deadline_ms: float = 5.0,
                 max_queue: int = 256,
                 hedge_shed_frac: float = 0.5,
                 low_shed_frac: float = 0.75,
                 metrics_registry=None):
        from elasticdl_tpu.observability import tracing

        self._store = store
        # Request / queue-wait / batch-assembly / predict spans when a
        # flight recorder is installed; free otherwise.
        self._tracer = tracing.Tracer("serving")
        self.max_batch_size = int(max_batch_size)
        self.batch_deadline = float(batch_deadline_ms) / 1e3
        self.max_queue = int(max_queue)
        # Tiered shedding (ISSUE 6): under pressure, drop the cheapest
        # traffic first — hedged retries (the router re-issues them
        # speculatively; the primary attempt is still in flight
        # elsewhere), then best-effort low-priority requests, and only
        # at a full queue everything. Fractions of max_queue.
        self.hedge_shed_frac = float(hedge_shed_frac)
        self.low_shed_frac = float(low_shed_frac)
        self._queue: List[_Request] = []
        self._cond = threading.Condition()
        self._stop = False
        # Draining (SIGTERM grace): new submits are refused with
        # QueueFullError (clients back off exactly like load shed)
        # while queued work keeps flushing. _busy marks a popped batch
        # still inside model.predict — the queue empties BEFORE the
        # predict call, so drain must wait on both.
        self._draining = False
        self._busy = False
        self._thread: Optional[threading.Thread] = None

        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self._m_requests = registry.counter(
            "serving_requests_total",
            "Predict requests by HTTP status code",
            labelnames=("code",),
        )
        self._m_latency = registry.histogram(
            "serving_request_seconds",
            "End-to-end predict latency (enqueue to reply)",
            # Observed inside the request span: slow requests carry
            # their trace id as an OpenMetrics exemplar.
            exemplars=True,
        )
        self._m_batch_seconds = registry.histogram(
            "serving_batch_seconds",
            "Predict-call latency per flushed batch",
        )
        self._m_batch_size = registry.histogram(
            "serving_batch_occupancy",
            "Real examples per flushed batch (pre-padding)",
            buckets=_BATCH_BUCKETS,
        )
        self._m_flushes = registry.counter(
            "serving_batch_flushes_total",
            "Batch flushes by trigger",
            labelnames=("reason",),
        )
        self._m_shed = registry.counter(
            "serving_load_shed_total",
            "Requests shed with 429, by tier (hedge < low < capacity)",
            labelnames=("tier",),
        )
        self._m_padded = registry.counter(
            "serving_padded_examples_total",
            "Padding examples added to reach the shape bucket",
        )
        self._m_errors = registry.counter(
            "serving_batch_errors_total",
            "Batches whose predict call raised",
        )
        # weakref: the registry is process-global and outlives
        # predictors; a strong closure would pin every discarded
        # predictor (and through its store, every resident model's
        # params) for the process life — same reasoning as the
        # host-engine rows gauge (embedding/host_engine.py).
        import weakref

        self_ref = weakref.ref(self)

        def _queue_depth() -> float:
            predictor = self_ref()
            return float(len(predictor._queue)) if predictor else 0.0

        registry.gauge(
            "serving_queue_depth",
            "Requests waiting for a batch slot",
        ).set_function(_queue_depth)

    # ---- client side ---------------------------------------------------

    def _shed_locked(self, priority: str, hedge: bool):
        """Raise QueueFullError when the queue's current depth crosses
        the tier this request belongs to. Called under ``_cond``."""
        depth = len(self._queue)
        if self._draining:
            raise self.QueueFullError(
                "server draining (SIGTERM)", tier="draining",
                retry_after=2.0,
            )
        if depth >= self.max_queue:
            raise self.QueueFullError(
                f"queue full ({self.max_queue} requests waiting)",
                tier="capacity", retry_after=2.0,
            )
        if hedge and depth >= self.hedge_shed_frac * self.max_queue:
            raise self.QueueFullError(
                f"hedge shed at queue depth {depth}", tier="hedge",
                retry_after=1.0,
            )
        if priority == "low" \
                and depth >= self.low_shed_frac * self.max_queue:
            raise self.QueueFullError(
                f"low-priority shed at queue depth {depth}",
                tier="low", retry_after=1.0,
            )

    def submit(self, features, timeout: float = 30.0,
               priority: str = "normal", hedge: bool = False):
        """Enqueue one request; returns (outputs, model_version).

        ``priority`` ("high"/"normal"/"low") and ``hedge`` (a router's
        speculative second attempt) select the shed tier: hedges shed
        first, then low-priority, then — only at a full queue —
        everything."""
        model = self._store.current()
        if model is None:
            raise RuntimeError("no model loaded")
        n = _batch_dim(features)
        limit = max(
            self._effective_limit(), model.static_batch_size or 0
        )
        if n > limit:
            raise ValueError(
                f"request batch {n} exceeds the server's max batch "
                f"size {limit}; split the request"
            )
        request = _Request(features, n)
        with self._tracer.span("request", n=n) as req_span:
            if req_span.span_id is not None:
                request.trace_ctx = (
                    req_span.trace_id, req_span.span_id
                )
            with self._cond:
                try:
                    self._shed_locked(priority, hedge)
                except self.QueueFullError as exc:
                    self._m_shed.labels(tier=exc.tier).inc()
                    raise
                self._queue.append(request)
                self._cond.notify_all()
            if not request.event.wait(timeout):
                request.cancelled = True
                raise TimeoutError("predict timed out")
            self._m_latency.observe(
                time.monotonic() - request.enqueued_at
            )
            if request.error is not None:
                raise request.error
            return request.outputs, request.version

    # ---- batcher side --------------------------------------------------

    def _effective_limit(self) -> int:
        """Flush/pad ceiling: a non-polymorphic bundle caps the batch
        at its ONE exported size regardless of the configured max."""
        model = self._store.current()
        static = model.static_batch_size if model is not None else None
        if static:
            return min(self.max_batch_size, static)
        return self.max_batch_size

    def _take_batch(self) -> List[_Request]:
        """Block until a flushable batch exists, then pop it. Flush
        when the queued examples reach the batch limit OR the oldest
        request has waited batch_deadline."""
        with self._cond:
            while True:
                if self._stop:
                    return []
                if self._queue:
                    # Purge abandoned requests first: their handlers
                    # already returned 504 and nobody reads the result.
                    self._queue = [
                        r for r in self._queue if not r.cancelled
                    ]
                    if not self._queue:
                        continue
                    limit = self._effective_limit()
                    oldest = self._queue[0].enqueued_at
                    deadline = oldest + self.batch_deadline
                    total = 0
                    take = 0
                    for request in self._queue:
                        if total + request.n > limit:
                            break
                        total += request.n
                        take += 1
                    if take == 0:
                        # Head request alone exceeds the limit (only
                        # possible when a static bundle's batch size
                        # exceeds max_batch_size): it flushes alone —
                        # the pad target is the static size anyway.
                        take, total = 1, self._queue[0].n
                    full = (
                        total >= limit
                        or (take < len(self._queue) and take > 0)
                    )
                    now = time.monotonic()
                    if full or now >= deadline:
                        batch = self._queue[:take]
                        del self._queue[:take]
                        # Atomic with the pop: drain watches
                        # (queue empty AND not busy), so the popped
                        # batch must read as busy before the lock drops.
                        self._busy = True
                        self._m_flushes.labels(
                            reason="size" if full else "deadline"
                        ).inc()
                        return batch
                    self._cond.wait(timeout=deadline - now)
                else:
                    self._cond.wait(timeout=0.1)

    @staticmethod
    def bucket_batch(n: int, limit: int) -> int:
        """Padded batch size: next power of two >= n, clamped to the
        limit (so the top bucket is the configured max, not its
        power-of-two ceiling)."""
        bucket = 1
        while bucket < n:
            bucket *= 2
        return min(bucket, max(limit, n))

    def _trace_batch(self, batch: List[_Request], record_wait: bool):
        """Retro-record queue-wait spans (enqueue → pop, per request)
        and return the ctx the shared batch spans should parent to (the
        head request's); None when tracing is off."""
        from elasticdl_tpu.observability import tracing

        if not tracing.enabled():
            return None
        now = time.monotonic()
        head_ctx = None
        for request in batch:
            if request.trace_ctx is None:
                continue
            trace_id, span_id = request.trace_ctx
            if head_ctx is None:
                head_ctx = request.trace_ctx
            if record_wait:
                tracing.record_span(
                    "queue_wait", request.enqueued_at,
                    now - request.enqueued_at,
                    trace_id=trace_id, parent_id=span_id,
                    role="serving",
                )
        return head_ctx

    def _run_batch(self, batch: List[_Request], _record_wait=True):
        from elasticdl_tpu.observability import tracing

        model = self._store.current()
        total = sum(r.n for r in batch)
        head_ctx = self._trace_batch(batch, _record_wait)
        try:
            assembly_t0 = time.monotonic()
            structure0 = batch[0].features
            for request in batch[1:]:
                if not _tree_leaves_equal_structure(
                    structure0, request.features
                ):
                    raise ValueError(
                        "requests in one batch disagree on feature "
                        "structure"
                    )
            features = _concat_trees([r.features for r in batch])
            if model.static_batch_size:
                target = model.static_batch_size
            else:
                target = self.bucket_batch(total, self._effective_limit())
            features = _pad_tree(features, target, total)
            self._m_padded.inc(target - total)
            t0 = time.monotonic()
            if head_ctx is not None:
                # Shared per-flush spans hang off the head request's
                # tree (one batch serves many requests; attrs carry the
                # occupancy so the share is readable).
                tracing.record_span(
                    "batch_assembly", assembly_t0, t0 - assembly_t0,
                    trace_id=head_ctx[0], parent_id=head_ctx[1],
                    role="serving", requests=len(batch),
                    examples=int(total), bucket=int(target),
                )
            outputs = model.predict(features)
            predict_dur = time.monotonic() - t0
            if head_ctx is not None:
                tracing.record_span(
                    "predict", t0, predict_dur,
                    trace_id=head_ctx[0], parent_id=head_ctx[1],
                    role="serving", requests=len(batch),
                    examples=int(total), bucket=int(target),
                )
            self._m_batch_seconds.observe(predict_dur)
            self._m_batch_size.observe(total)
            lo = 0
            for request in batch:
                request.outputs = _slice_tree(
                    outputs, lo, lo + request.n
                )
                request.version = model.version
                lo += request.n
        except Exception as exc:
            self._m_errors.inc()
            if len(batch) > 1:
                # Isolate the poison request: one bad payload (wrong
                # structure, stray dtype) must not 500 the innocent
                # requests sharing its flush. (Queue-wait was already
                # recorded for the shared flush — don't re-record.)
                for request in batch:
                    self._run_batch([request], _record_wait=False)
                return
            for request in batch:
                request.error = exc
        finally:
            for request in batch:
                if not request.event.is_set():
                    request.event.set()

    def _loop(self):
        while True:
            batch = self._take_batch()  # sets _busy with the pop
            if not batch:
                return
            try:
                self._run_batch(batch)
            finally:
                with self._cond:
                    self._busy = False
                    self._cond.notify_all()

    def start(self) -> "BatchingPredictor":
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="serving-batcher"
            )
            self._thread.start()
        return self

    def stop(self, join_timeout: float = 5.0) -> bool:
        """Stop the batcher; returns False if its thread (an in-flight
        predict call) outlived ``join_timeout``."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=join_timeout)
            return not thread.is_alive()
        return True

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful SIGTERM path: refuse new submits, flush every
        queued micro-batch INCLUDING the one mid-predict, then stop
        the batcher. Returns False if the work didn't finish inside
        ``timeout`` (the batcher is stopped regardless — remaining
        requests get their error when their handler times out)."""
        deadline = time.monotonic() + float(timeout)
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        drained = True
        while True:
            with self._cond:
                # The queue empties when the batcher POPS the final
                # batch; _busy covers the predict call still running
                # on it.
                if not self._queue and not self._busy:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    drained = False
                    break
                self._cond.wait(timeout=min(remaining, 0.05))
        # Whatever grace is left bounds the final thread join (a last
        # predict may still be between the busy-flag drop and loop
        # exit).
        remaining = max(0.1, deadline - time.monotonic())
        return self.stop(join_timeout=remaining) and drained

    def record_status(self, code: int):
        self._m_requests.labels(code=str(code)).inc()


class _Handler(BaseHTTPRequestHandler):
    server_ref = None  # type: Optional[InferenceServer]

    protocol_version = "HTTP/1.1"

    def _reply(self, code: int, body: bytes, content_type: str,
               headers=()):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in headers:
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _reply_error(self, code: int, message: str, as_msgpack: bool,
                     headers=()):
        srv = type(self).server_ref
        srv.predictor.record_status(code)
        if as_msgpack:
            from elasticdl_tpu.common import tensor_utils

            body = tensor_utils.dumps({"error": message})
            self._reply(code, body, MSGPACK_CONTENT_TYPE, headers)
        else:
            body = json.dumps({"error": message}).encode("utf-8")
            self._reply(code, body, "application/json", headers)

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        srv = type(self).server_ref
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            from elasticdl_tpu.observability import render_prometheus

            body = render_prometheus(srv.registry.snapshot())
            self._reply(
                200, body.encode("utf-8"),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/traces":
            # The process flight recorder (request / queue-wait /
            # batch / predict spans) for dump_metrics --traces; empty
            # until the server runs with --flight_recorder N.
            from elasticdl_tpu.observability import tracing

            body = json.dumps(
                {"spans": tracing.recorder_spans()}
            ).encode("utf-8")
            self._reply(200, body, "application/json")
        elif path == "/healthz":
            ok = srv.store.current() is not None
            self._reply(
                200 if ok else 503,
                b"ok\n" if ok else b"no model\n",
                "text/plain; charset=utf-8",
            )
        elif path == "/v1/models":
            current = srv.store.current()
            body = json.dumps({
                "versions": srv.store.versions(),
                "current": current.version if current else None,
                "meta": current.meta if current else None,
            }).encode("utf-8")
            self._reply(200, body, "application/json")
        else:
            self.send_error(404, "try /v1/predict, /v1/models, /metrics")

    def do_POST(self):  # noqa: N802
        srv = type(self).server_ref
        path = self.path.split("?", 1)[0]
        if path == "/v1/models/rollback":
            try:
                model = srv.store.rollback()
            except RuntimeError as exc:
                self._reply_error(409, str(exc), as_msgpack=False)
                return
            self._reply(
                200,
                json.dumps({"current": model.version}).encode("utf-8"),
                "application/json",
            )
            return
        if path != "/v1/predict":
            self.send_error(404, "POST /v1/predict")
            return
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length)
        as_msgpack = self.headers.get(
            "Content-Type", ""
        ).startswith(MSGPACK_CONTENT_TYPE)
        try:
            if as_msgpack:
                from elasticdl_tpu.common import tensor_utils

                payload = tensor_utils.loads(raw)
            else:
                payload = json.loads(raw.decode("utf-8"))
            features = payload["features"]
            model = srv.store.current()
            if model is not None:
                # Coerce BOTH transports onto the recorded signature:
                # JSON arrives as lists, and a msgpack client's stray
                # float64/int64 leaf would otherwise promote the whole
                # concatenated batch and fail the artifact's aval check.
                features = _coerce_signature(
                    features, model.meta.get("feature_signature")
                )
        except Exception as exc:
            self._reply_error(
                400, f"bad request: {exc}", as_msgpack=as_msgpack
            )
            return
        # Router-set scheduling hints: X-Priority selects the shed
        # tier, X-Hedge marks a speculative second attempt (shed
        # first under pressure — its primary is in flight elsewhere).
        priority = self.headers.get("X-Priority", "normal").lower()
        if priority not in ("high", "normal", "low"):
            priority = "normal"
        hedge = self.headers.get("X-Hedge", "") == "1"
        try:
            outputs, version = srv.predictor.submit(
                features, timeout=srv.request_timeout,
                priority=priority, hedge=hedge,
            )
        except BatchingPredictor.QueueFullError as exc:
            self._reply_error(
                429, str(exc), as_msgpack=as_msgpack,
                headers=(
                    ("Retry-After",
                     str(max(1, int(round(exc.retry_after))))),
                    ("X-Shed-Tier", exc.tier),
                ),
            )
            return
        except TimeoutError as exc:
            self._reply_error(504, str(exc), as_msgpack=as_msgpack)
            return
        except (ValueError, TypeError) as exc:
            self._reply_error(400, str(exc), as_msgpack=as_msgpack)
            return
        except RuntimeError as exc:
            self._reply_error(503, str(exc), as_msgpack=as_msgpack)
            return
        except Exception as exc:
            self._reply_error(
                500, f"{type(exc).__name__}: {exc}", as_msgpack=as_msgpack
            )
            return
        srv.predictor.record_status(200)
        if as_msgpack:
            from elasticdl_tpu.common import tensor_utils

            body = tensor_utils.dumps(
                {"predictions": outputs, "model_version": version}
            )
            self._reply(200, body, MSGPACK_CONTENT_TYPE)
        else:
            import jax

            body = json.dumps({
                "predictions": jax.tree.map(
                    lambda x: np.asarray(x).tolist(), outputs
                ),
                "model_version": version,
            }).encode("utf-8")
            self._reply(200, body, "application/json")

    def log_message(self, fmt, *args):
        logger.debug("serving http: " + fmt, *args)


class InferenceServer:
    """The assembled serving process: store + batcher + HTTP front.

    ``port=0`` binds an ephemeral port (tests/bench); ``start()``
    returns immediately (daemon threads), ``wait()`` blocks for a
    process-main lifetime."""

    def __init__(self, store, max_batch_size: int = 64,
                 batch_deadline_ms: float = 5.0, max_queue: int = 256,
                 port: int = 8500, host: str = "",
                 request_timeout: float = 30.0,
                 hedge_shed_frac: float = 0.5,
                 low_shed_frac: float = 0.75,
                 metrics_registry=None):
        from elasticdl_tpu.observability import default_registry

        self.store = store
        self.registry = metrics_registry or default_registry()
        self.predictor = BatchingPredictor(
            store, max_batch_size=max_batch_size,
            batch_deadline_ms=batch_deadline_ms, max_queue=max_queue,
            hedge_shed_frac=hedge_shed_frac,
            low_shed_frac=low_shed_frac,
            metrics_registry=self.registry,
        )
        self.request_timeout = float(request_timeout)
        self._host = host
        self._requested_port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else 0

    def start(self) -> "InferenceServer":
        self.predictor.start()
        handler = type("_BoundHandler", (_Handler,), {
            "server_ref": self,
        })
        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), handler,
            bind_and_activate=False,
        )
        # socketserver's default listen backlog (5) SYN-drops a
        # client fleet connecting at once — each drop is a ~1s
        # retransmit stall that reads as a fake p99 cliff.
        self._httpd.request_queue_size = 128
        self._httpd.server_bind()
        self._httpd.server_activate()
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="serving-http",
        )
        self._thread.start()
        logger.info("Inference server on port %d", self.port)
        return self

    def wait(self):
        self._thread.join()

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.predictor.stop()
        self.store.stop()

    def drain(self, grace: float = 25.0) -> bool:
        """Graceful SIGTERM shutdown: stop accepting connections,
        flush in-flight micro-batches (new submits shed with 429 so
        the balancer retries elsewhere), then tear down. k8s default
        termination grace is 30s — keep ``grace`` under it so exit
        beats the KILL."""
        logger.info("draining inference server (grace %.1fs)", grace)
        if self._httpd is not None:
            # Stop the accept loop; handler threads for already-
            # accepted requests keep running and block in submit().
            self._httpd.shutdown()
        drained = self.predictor.drain(timeout=grace)
        if self._httpd is not None:
            self._httpd.server_close()
            self._httpd = None
        self.store.stop()
        logger.info(
            "inference server drained (%s)",
            "clean" if drained else "grace expired with queued work",
        )
        return drained


def main(argv=None) -> int:
    """``elasticdl_tpu serve`` entry: serve an export directory.

    The minimal deployment is one process per replica behind any HTTP
    load balancer; the bundle directory is the handoff from training
    (``SavedModelExporter`` / ``export_serving_bundle``)."""
    import argparse

    parser = argparse.ArgumentParser("elasticdl_tpu-serve")
    parser.add_argument(
        "--model_dir", required=True,
        help="A bundle directory, or a directory of versioned bundle "
             "subdirectories (hot reload polls it)",
    )
    parser.add_argument("--port", type=int, default=8500)
    parser.add_argument("--max_batch_size", type=int, default=64)
    parser.add_argument(
        "--batch_deadline_ms", type=float, default=5.0,
        help="Max time the oldest queued request waits before a "
             "partial batch flushes",
    )
    parser.add_argument("--max_queue", type=int, default=256,
                        help="Queued requests beyond this shed with 429")
    parser.add_argument(
        "--row_service_addr", default="",
        help="Comma list of HostRowService shard addresses — required "
             "for bundles exported in row-service mode (host_id_keys)",
    )
    parser.add_argument(
        "--model_zoo", default="",
        help="Zoo path for non-self-contained bundles (params-only "
             "fallback re-applies the flax module)",
    )
    parser.add_argument("--poll_seconds", type=float, default=2.0)
    parser.add_argument("--retain_versions", type=int, default=1)
    parser.add_argument("--request_timeout", type=float, default=30.0)
    parser.add_argument(
        "--row_cache_capacity", type=int, default=0,
        help="Hot-row LRU size (rows) for row-service bundles: caches "
             "(table, id) -> row so warm sparse predicts skip the "
             "row-service round trip. 0 (default) = no cache",
    )
    parser.add_argument(
        "--row_cache_version_check_ms", type=float, default=50.0,
        help="How often the cache re-checks the row service's "
             "per-table update counters (bounded staleness). 0 = "
             "check every request (read-your-writes); negative = "
             "never (immutable tables)",
    )
    parser.add_argument(
        "--hedge_shed_frac", type=float, default=0.5,
        help="Queue fraction past which hedged (speculative) requests "
             "shed with 429",
    )
    parser.add_argument(
        "--low_shed_frac", type=float, default=0.75,
        help="Queue fraction past which low-priority requests shed "
             "with 429",
    )
    parser.add_argument(
        "--drain_grace", type=float, default=25.0,
        help="SIGTERM drain budget for in-flight micro-batches; keep "
             "under the pod's terminationGracePeriodSeconds",
    )
    parser.add_argument(
        "--flight_recorder", type=int, default=0,
        help="Install a span flight recorder of this many entries "
             "(request / queue-wait / batch-assembly / predict spans, "
             "served on /traces next to /metrics; "
             "tools/dump_metrics.py --traces). 0 (default) = off",
    )
    parser.add_argument(
        "--master_addr", default="",
        help="Training master host:port — fold this replica's "
             "serving_* / row_freshness telemetry into the master's "
             "cluster view and time-series store (how the master-side "
             "row-freshness SLO rule sees serving reads; "
             "docs/observability.md). Empty (default) = standalone",
    )
    parser.add_argument(
        "--replica_id", type=int, default=0,
        help="This replica's id in the master's cluster view "
             "(series label worker=\"serving-<id>\")",
    )
    parser.add_argument(
        "--metrics_report_secs", type=float, default=15.0,
        help="Master telemetry report interval (with --master_addr)",
    )
    parser.add_argument(
        "--profile_hz", type=float, default=0.0,
        help="Always-on sampling profiler rate (Hz); flame windows "
             "piggyback to the master with --master_addr and serve "
             "on the master's /profile as serving-<id>. 0 = off",
    )
    parser.add_argument(
        "--profile_window_secs", type=float, default=10.0,
        help="Sampling-profiler window length (secs)",
    )
    args = parser.parse_args(argv)

    # Workload attribution: this replica's row pulls (hot-row reads
    # through make_row_service_tables) and telemetry meter fleet-wide
    # as serving reads, split from training pushes at the row tier.
    import os as _os

    from elasticdl_tpu.observability import principal as _principal

    _principal.set_process_principal(
        job=_os.environ.get("ELASTICDL_JOB_NAME", ""),
        component="serving", purpose="serving_read",
    )
    if args.flight_recorder > 0:
        from elasticdl_tpu.observability import tracing

        tracing.set_process_role("serving")
        tracing.install_recorder(
            tracing.FlightRecorder(args.flight_recorder)
        )
    from elasticdl_tpu.observability import profiler as _profiler

    _profiler.maybe_start_from_args(
        args, "serving", str(args.replica_id)
    )

    from elasticdl_tpu.serving.model_store import ModelStore

    model = None
    if args.model_zoo:
        import os

        from elasticdl_tpu.core.model_spec import load_model_zoo_module
        from elasticdl_tpu.serving.export import META_FILE

        meta_path = os.path.join(args.model_dir, META_FILE)
        model_def = ""
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                model_def = json.load(f).get("model_def", "")
        if model_def:
            module, model_fn_name = load_model_zoo_module(
                args.model_zoo, model_def
            )
            model = getattr(module, model_fn_name)()
    store = ModelStore(
        args.model_dir, model=model,
        row_service_addr=args.row_service_addr,
        retain=args.retain_versions,
        poll_seconds=args.poll_seconds,
        row_cache_capacity=args.row_cache_capacity,
        row_cache_version_check_secs=(
            args.row_cache_version_check_ms / 1e3
            if args.row_cache_version_check_ms >= 0 else -1.0
        ),
    )
    store.load_initial()
    store.start_polling()
    server = InferenceServer(
        store,
        max_batch_size=args.max_batch_size,
        batch_deadline_ms=args.batch_deadline_ms,
        max_queue=args.max_queue,
        port=args.port,
        request_timeout=args.request_timeout,
        hedge_shed_frac=args.hedge_shed_frac,
        low_shed_frac=args.low_shed_frac,
    ).start()
    logger.info(
        "Serving %s on :%d (max_batch=%d, deadline=%.1fms)",
        args.model_dir, server.port, args.max_batch_size,
        args.batch_deadline_ms,
    )
    reporter = None
    if args.master_addr:
        from elasticdl_tpu.observability.reporter import (
            ComponentMetricsReporter,
        )

        reporter = ComponentMetricsReporter(
            args.master_addr, "serving", args.replica_id,
            interval_secs=args.metrics_report_secs,
        )
        reporter.start()
    # Graceful pod eviction: SIGTERM stops the accept loop, flushes
    # in-flight micro-batches, then exits well inside the k8s
    # termination grace — without this, eviction drops every queued
    # request on the floor mid-predict.
    import signal

    stop_evt = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *_: stop_evt.set())
        signal.signal(signal.SIGINT, lambda *_: stop_evt.set())
    except ValueError:
        # Not the main thread (embedded/test use): callers drive
        # server.drain() themselves.
        server.wait()
        return 0
    stop_evt.wait()
    if reporter is not None:
        reporter.stop()
    server.drain(grace=args.drain_grace)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
