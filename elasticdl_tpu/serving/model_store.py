"""Multi-version bundle management for the online serving plane.

The reference's serving story ended at a SavedModel directory handed to
TF Serving — which owns version polling, atomic swap, and rollback
(SURVEY §2.5's export path). Here the equivalent lifecycle is native:

- ``ModelStore`` watches an export root. Two layouts are accepted:
  a directory of versioned bundle subdirectories (what a training job's
  periodic ``SavedModelExporter`` produces when pointed at
  ``root/v<step>``) or a single bundle directory. A bundle is eligible
  only once ``metadata.json`` exists — the exporter writes it LAST, so
  presence == complete bundle (no partial-read races with the writer).
- New versions load on the store's poll thread, NEVER on the serving
  thread: the batcher keeps draining on the old version while the new
  one deserializes/compiles, then one atomic reference swap publishes
  it. The previous ``retain`` versions stay resident for instant
  ``rollback()`` (which also pins the rolled-back version so the
  poller doesn't immediately re-promote it).
- ``ServedModel.predict`` is the single entry the server calls. For
  row-service bundles (``metadata.host_serving``, exported via
  ``export_serving_bundle(host_id_keys=...)``) it resolves host-tier
  sparse features first: dedup the batch's raw ids, pull unique rows
  from the live ``HostRowService`` (embedding/row_service.py — the
  same pull plane training uses), bucket-pad to a power of two, and
  hand the row block to the StableHLO artifact through its symbolic
  row dim. Dense bundles pass features straight through.
"""

import json
import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.serving.export import (
    META_FILE,
    load_predictor,
)

logger = get_logger("model_store")


def _np_features(features):
    import jax

    return jax.tree.map(np.asarray, features)


class HotRowCache:
    """Per-replica LRU over ``(table, id) -> row`` with version-based
    invalidation — the layer that takes the row-service round trip off
    the hot sparse-predict path.

    The row service stays the single source of truth (Elastic Model
    Aggregation's parameter-service-centric shape, arXiv 2204.03211);
    this cache only memoizes reads in front of it. Freshness contract:
    every ``version_check_secs`` each table's monotonic update counter
    (``table_versions`` RPC — one tiny reply, no row payload) is
    compared against the counter recorded when the cache was filled; a
    changed counter drops ALL of that table's entries, so the next
    resolve re-pulls. The periodic probe runs on a BACKGROUND thread —
    the serving batcher never blocks on an invalidation RPC, so a warm
    resolve touches no socket at all. ``version_check_secs=0`` probes
    inline on every resolve instead — read-your-writes at the price of
    one small RPC per request (still far cheaper than re-pulling row
    blocks); ``version_check_secs<0`` disables checking (pure LRU, for
    immutable/offline tables).

    Thread-safe: the serving batcher is single-threaded today, but the
    cache is also probed by ``/metrics`` pull-gauges and shared across
    bundle versions (ModelStore hands ONE cache to every loader, so a
    hot reload keeps the warm rows)."""

    def __init__(self, capacity: int = 100_000,
                 version_check_secs: float = 0.05,
                 metrics_registry=None):
        self.capacity = int(capacity)
        self.version_check_secs = float(version_check_secs)
        self._lock = threading.Lock()
        self._rows: "OrderedDict[Tuple[str, int], np.ndarray]" = \
            OrderedDict()
        self._versions: Dict[str, int] = {}
        # Per-table invalidation epoch: bumped whenever the update
        # counter moves. Fills are epoch-guarded (see put_many) — a
        # pull that STRADDLES a push must not insert its stale rows
        # after the probe already invalidated, or they would outlive
        # the bounded-staleness contract (until the NEXT push).
        self._epochs: Dict[str, int] = {}
        # Per-table applied-push stamp (row-service wall clock) carried
        # by the pull that filled the cache: a cache-hit read still
        # knows how fresh its rows are — see HostRowResolver's
        # edl_tpu_row_freshness_seconds observation.
        self._applied_at: Dict[str, float] = {}
        self._probe_tables: Dict = {}
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None

        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self._m_hits = registry.counter(
            "serving_row_cache_hits_total",
            "Unique rows served from the hot-row cache",
        )
        self._m_misses = registry.counter(
            "serving_row_cache_misses_total",
            "Unique rows pulled from the row service on cache miss",
        )
        self._m_evictions = registry.counter(
            "serving_row_cache_evictions_total",
            "Rows evicted by LRU capacity pressure",
        )
        self._m_invalidations = registry.counter(
            "serving_row_cache_invalidations_total",
            "Rows dropped because a table's update counter moved",
        )
        import weakref

        self_ref = weakref.ref(self)
        registry.gauge(
            "serving_row_cache_rows",
            "Rows currently resident in the hot-row cache",
        ).set_function(
            lambda: float(len(self_ref()._rows)) if self_ref() else 0.0
        )

    def __len__(self) -> int:
        return len(self._rows)

    # ---- invalidation --------------------------------------------------

    def maybe_check_versions(self, tables: Dict):
        """Freshness hook, called by the resolver per resolve. With a
        positive interval it only (re)arms the background probe thread
        against the CURRENT table set and returns — the hot path never
        blocks on an invalidation RPC. With interval 0 it probes
        inline (read-your-writes)."""
        if self.version_check_secs < 0:
            return
        if self.version_check_secs == 0:
            self._check_versions(tables)
            return
        self._probe_tables = tables
        if self._probe_thread is None:
            with self._lock:
                if self._probe_thread is not None:
                    return
                self._probe_thread = threading.Thread(
                    target=self._probe_loop, daemon=True,
                    name="row-cache-versions",
                )
            self._probe_thread.start()

    def _probe_loop(self):
        while not self._probe_stop.wait(self.version_check_secs):
            try:
                self._check_versions(self._probe_tables)
            except Exception:
                logger.exception("row cache version probe loop failed")

    def stop(self):
        self._probe_stop.set()
        thread = self._probe_thread
        if thread is not None:
            thread.join(timeout=5)

    def _check_versions(self, tables: Dict):
        """Poll each table's update counter; drop a table's entries
        when its counter moved. Tables without a ``pull_version``
        (in-process fakes) never invalidate. A failed probe
        invalidates too — when the row plane is unreachable we cannot
        prove freshness, and the subsequent pull will surface the real
        error through the existing retry path."""
        for name, table in tables.items():
            probe = getattr(table, "pull_version", None)
            if probe is None:
                continue
            try:
                version = int(probe())
            except Exception:
                logger.warning(
                    "row cache version probe failed for table %s; "
                    "invalidating", name, exc_info=True,
                )
                version = None
            with self._lock:
                if name in self._versions \
                        and self._versions.get(name) != version:
                    self._epochs[name] = \
                        self._epochs.get(name, 0) + 1
                    dropped = [
                        key for key in self._rows if key[0] == name
                    ]
                    for key in dropped:
                        del self._rows[key]
                    if dropped:
                        self._m_invalidations.inc(len(dropped))
                if version is None:
                    self._versions.pop(name, None)
                else:
                    self._versions[name] = version

    # ---- lookup / fill -------------------------------------------------

    def get_many(self, table: str, ids: np.ndarray,
                 out: np.ndarray) -> np.ndarray:
        """Fill ``out[i]`` for every cached id; returns the boolean
        miss mask. Hits are refreshed to MRU."""
        miss = np.zeros(len(ids), bool)
        with self._lock:
            for i, raw_id in enumerate(ids):
                key = (table, int(raw_id))
                row = self._rows.get(key)
                if row is None:
                    miss[i] = True
                else:
                    self._rows.move_to_end(key)
                    out[i] = row
        hits = int(len(ids) - miss.sum())
        if hits:
            self._m_hits.inc(hits)
        if miss.any():
            self._m_misses.inc(int(miss.sum()))
        return miss

    def table_epoch(self, table: str) -> int:
        """Read BEFORE pulling rows; pass to ``put_many`` so a fill
        whose pull straddled an invalidation is dropped."""
        with self._lock:
            return self._epochs.get(table, 0)

    def applied_at(self, table: str) -> float:
        """Row-service applied-push stamp as of the pull that last
        filled this table's cache entries (0.0 = unknown)."""
        with self._lock:
            return self._applied_at.get(table, 0.0)

    def put_many(self, table: str, ids: np.ndarray, rows: np.ndarray,
                 epoch: Optional[int] = None,
                 applied_at: Optional[float] = None):
        if self.capacity <= 0:
            return
        evicted = 0
        with self._lock:
            if applied_at:
                self._applied_at[table] = max(
                    self._applied_at.get(table, 0.0), float(applied_at)
                )
            if epoch is not None \
                    and self._epochs.get(table, 0) != epoch:
                # The rows were pulled before an invalidation landed:
                # they may predate the push that caused it. Dropping
                # the fill costs one re-pull; caching stale rows
                # would cost correctness until the NEXT push.
                return
            for raw_id, row in zip(ids, rows):
                # Copy: the caller's block is a mutable scratch buffer.
                self._rows[(table, int(raw_id))] = np.array(
                    row, np.float32
                )
                self._rows.move_to_end((table, int(raw_id)))
            while len(self._rows) > self.capacity:
                self._rows.popitem(last=False)
                evicted += 1
        if evicted:
            self._m_evictions.inc(evicted)

    def stats(self) -> dict:
        with self._lock:
            return {
                "rows": len(self._rows),
                "capacity": self.capacity,
                "versions": dict(self._versions),
            }


class HostRowResolver:
    """Inference-time sparse-feature resolution against the row plane.

    Rewrites a combined batch's raw-id features into (inverse map,
    bucket-padded row block) pairs — the same dedup/bucket discipline
    ``HostEmbeddingEngine.prepare_batch`` applies in training, so the
    compiled-shape count stays O(log unique-ids) per table. Rows come
    from ``embedding/row_service.py`` remote tables (or any table-like
    with ``get(ids) -> (n, dim)``), which is what makes host-partitioned
    DeepFM-style models servable without materializing the vocab."""

    def __init__(self, host_serving: dict, tables: Dict,
                 feature_signature: Optional[dict] = None,
                 row_cache: Optional[HotRowCache] = None,
                 metrics_registry=None):
        self._id_keys = dict(host_serving["id_keys"])
        self._dims = {k: int(v)
                      for k, v in host_serving["tables"].items()}
        self._prefix = host_serving.get(
            "rows_feature_prefix", "__host_rows__:"
        )
        missing = set(self._id_keys) - set(tables)
        if missing:
            raise ValueError(
                f"row source serves no table(s) {sorted(missing)} "
                f"required by the bundle"
            )
        self._tables = tables
        # Inverse maps must be emitted in the DTYPE the artifact was
        # traced with (jax.export validates input avals strictly; an
        # int64-id example would otherwise reject every int32 inverse).
        self._id_dtypes = {}
        signature = feature_signature or {}
        for table_name, key in self._id_keys.items():
            spec = signature.get(key) if isinstance(signature, dict) \
                else None
            self._id_dtypes[table_name] = np.dtype(
                spec["dtype"] if isinstance(spec, dict)
                and "dtype" in spec else np.int32
            )
        self._cache = row_cache

        # The per-request round trip this resolver pays was invisible
        # on /metrics until ISSUE 6 — these attribute it (and the
        # cache's win) directly.
        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self._m_resolve_seconds = registry.histogram(
            "serving_row_resolve_seconds",
            "Sparse-feature resolution latency per predict batch "
            "(dedup + row fetch + bucket-pad)",
        )
        self._m_resolve_rows = registry.counter(
            "serving_row_resolve_rows_total",
            "Unique rows resolved, by source",
            labelnames=("source",),
        )
        # The ROADMAP's push-to-servable freshness signal: how long
        # after a gradient push was applied could a serving read still
        # be using it un-refreshed. Observed per resolved table read —
        # pulls use the applied-push stamp riding the pull response,
        # cache hits the stamp recorded when the cache was filled. The
        # default SLO ruleset alerts on its p99 (docs/observability.md).
        self._m_freshness = registry.histogram(
            "row_freshness_seconds",
            "Push-to-servable latency: age of the row service's last "
            "applied push at serving-read time",
            # Observed inside the row_resolve span: a stale read's
            # exemplar names the serving request that saw it.
            exemplars=True,
        )

    def resolve(self, features: dict) -> dict:
        from elasticdl_tpu.embedding.host_engine import bucket_size
        from elasticdl_tpu.observability import tracing

        if not isinstance(features, dict):
            raise TypeError(
                "row-service bundles need dict features carrying the "
                f"id keys {sorted(self._id_keys.values())}"
            )
        t0 = time.monotonic()
        cache_hits = 0
        pulled = 0
        out = dict(features)
        with tracing.span("row_resolve") as resolve_span:
            if self._cache is not None:
                self._cache.maybe_check_versions(self._tables)
            for table_name, key in self._id_keys.items():
                raw = np.asarray(out[key])
                uniq, inverse = np.unique(raw, return_inverse=True)
                bucket = bucket_size(len(uniq))
                dim = self._dims[table_name]
                rows = np.zeros((bucket, dim), np.float32)
                table = self._tables[table_name]
                applied_at = 0.0
                if self._cache is not None:
                    block = rows[: len(uniq)]
                    miss = self._cache.get_many(table_name, uniq, block)
                    if miss.any():
                        epoch = self._cache.table_epoch(table_name)
                        fetched = np.asarray(
                            table.get(uniq[miss]), np.float32,
                        )
                        block[miss] = fetched
                        applied_at = float(getattr(
                            table, "last_applied_at", 0.0
                        ) or 0.0)
                        self._cache.put_many(
                            table_name, uniq[miss], fetched,
                            epoch=epoch, applied_at=applied_at,
                        )
                    else:
                        # Pure cache hit: freshness bound comes from
                        # the pull that filled the cache.
                        applied_at = self._cache.applied_at(table_name)
                    cache_hits += int(len(uniq) - miss.sum())
                    pulled += int(miss.sum())
                else:
                    rows[: len(uniq)] = np.asarray(
                        table.get(uniq), np.float32
                    )
                    applied_at = float(getattr(
                        table, "last_applied_at", 0.0
                    ) or 0.0)
                    pulled += len(uniq)
                if applied_at > 0:
                    self._m_freshness.observe(
                        max(0.0, time.time() - applied_at)
                    )
                out[key] = inverse.reshape(raw.shape).astype(
                    self._id_dtypes[table_name]
                )
                out[self._prefix + table_name] = rows
            resolve_span.set(
                cache_hits=cache_hits, pulled=pulled,
                tables=len(self._id_keys),
            )
        if cache_hits:
            self._m_resolve_rows.labels(source="cache").inc(cache_hits)
        if pulled:
            self._m_resolve_rows.labels(source="pull").inc(pulled)
        self._m_resolve_seconds.observe(time.monotonic() - t0)
        return out


def make_row_service_tables(addr: str, host_serving: dict,
                            retries: int = 12,
                            backoff_secs: float = 0.5) -> Dict:
    """Remote pull-only tables over running row-service shard(s) —
    the serving-side counterpart of ``make_remote_engine`` (no
    optimizer: inference never pushes)."""
    from elasticdl_tpu.embedding.row_service import make_remote_engine

    engine = make_remote_engine(
        addr,
        id_keys=dict(host_serving["id_keys"]),
        retries=retries, backoff_secs=backoff_secs,
    )
    return engine.tables


class ServedModel:
    """One loaded, callable bundle version."""

    def __init__(self, path: str, version: int, meta: dict,
                 predictor: Callable,
                 resolver: Optional[HostRowResolver] = None):
        self.path = path
        self.version = int(version)
        self.meta = meta
        self._predictor = predictor
        self._resolver = resolver

    @property
    def batch_polymorphic(self) -> bool:
        return bool(self.meta.get("batch_polymorphic", False))

    @property
    def static_batch_size(self) -> Optional[int]:
        """The one batch size a non-polymorphic artifact serves."""
        if self.batch_polymorphic:
            return None
        return int(self.meta.get("batch_size", 0)) or None

    def predict(self, features):
        if self._resolver is not None:
            features = self._resolver.resolve(features)
        return _np_features(self._predictor(features))


def load_served_model(bundle_dir: str, model=None,
                      row_tables: Optional[Dict] = None,
                      row_service_addr: str = "",
                      row_cache: Optional[HotRowCache] = None,
                      metrics_registry=None) -> ServedModel:
    """Load one bundle directory into a ``ServedModel``.

    ``row_tables`` / ``row_service_addr``: the row source for bundles
    exported in row-service mode (``metadata.host_serving``); exactly
    one is required for those, ignored for dense bundles. ``model`` is
    the flax-module fallback for non-self-contained dense bundles.
    ``row_cache``: an optional shared ``HotRowCache`` the resolver
    consults before pulling rows."""
    with open(os.path.join(bundle_dir, META_FILE)) as f:
        meta = json.load(f)
    resolver = None
    host_serving = meta.get("host_serving")
    if host_serving:
        if row_tables is None:
            if not row_service_addr:
                raise ValueError(
                    f"bundle {bundle_dir} was exported in row-service "
                    "mode; pass --row_service_addr (or row_tables) so "
                    "the server can pull rows at inference time"
                )
            row_tables = make_row_service_tables(
                row_service_addr, host_serving
            )
        resolver = HostRowResolver(
            host_serving, row_tables,
            feature_signature=meta.get("feature_signature"),
            row_cache=row_cache,
            metrics_registry=metrics_registry,
        )
    predictor = load_predictor(bundle_dir, model=model)
    return ServedModel(
        bundle_dir, meta.get("model_version", 0), meta, predictor,
        resolver,
    )


class ModelStore:
    """Version discovery + atomic hot reload + N-version rollback.

    ``root`` is either a directory of bundle subdirectories or itself a
    bundle. ``loader`` maps a bundle path to a ``ServedModel`` (the
    default binds ``load_served_model`` with this store's row source /
    fallback module). ``start_polling`` swaps in newer versions as the
    exporter publishes them; ``current()`` is what the serving thread
    reads — one attribute load, no lock on the hot path."""

    def __init__(self, root: str, model=None,
                 row_tables: Optional[Dict] = None,
                 row_service_addr: str = "",
                 retain: int = 1,
                 poll_seconds: float = 2.0,
                 loader: Optional[Callable[[str], ServedModel]] = None,
                 row_cache_capacity: int = 0,
                 row_cache_version_check_secs: float = 0.05,
                 metrics_registry=None):
        self.root = root
        self._retain = max(0, int(retain))
        self._poll_seconds = float(poll_seconds)
        # ONE cache shared across every version this store loads: a
        # hot reload must not cold-start the row working set.
        self.row_cache: Optional[HotRowCache] = None
        if row_cache_capacity > 0:
            self.row_cache = HotRowCache(
                row_cache_capacity,
                version_check_secs=row_cache_version_check_secs,
                metrics_registry=metrics_registry,
            )
        if loader is None:
            def loader(path):
                return load_served_model(
                    path, model=model, row_tables=row_tables,
                    row_service_addr=row_service_addr,
                    row_cache=self.row_cache,
                    metrics_registry=metrics_registry,
                )
        self._loader = loader
        self._lock = threading.Lock()
        self._current: Optional[ServedModel] = None
        self._previous: List[ServedModel] = []  # newest last
        self._rejected = set()  # rolled-back versions (operator pin)
        # Load failures back off instead of pinning: a row-service
        # bundle can fail to load while its row plane restarts, and
        # re-exporting the same checkpoint reuses the same version
        # number — permanent rejection would wedge until a server
        # restart. {version: (consecutive failures, next retry time)}.
        self._load_failures: Dict[int, tuple] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self._m_version = registry.gauge(
            "serving_model_version",
            "Model version currently served",
        )
        self._m_reloads = registry.counter(
            "serving_model_reloads_total",
            "Successful hot reloads", labelnames=("result",),
        )
        self._m_load_seconds = registry.histogram(
            "serving_model_load_seconds",
            "Bundle load (deserialize + warm) latency",
        )

    # ---- discovery -----------------------------------------------------

    def _candidates(self) -> List[str]:
        """Complete bundle dirs under root (root itself counts)."""
        if os.path.exists(os.path.join(self.root, META_FILE)):
            return [self.root]
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in sorted(os.listdir(self.root)):
            path = os.path.join(self.root, name)
            if os.path.exists(os.path.join(path, META_FILE)):
                out.append(path)
        return out

    @staticmethod
    def _bundle_version(path: str) -> int:
        try:
            with open(os.path.join(path, META_FILE)) as f:
                return int(json.load(f).get("model_version", 0))
        except (OSError, ValueError, json.JSONDecodeError):
            return -1

    def newest_available(self):
        """(version, path) of the newest complete bundle that is
        neither rolled back nor inside its failure backoff window."""
        best = None
        for path in self._candidates():
            version = self._bundle_version(path)
            if version < 0 or version in self._rejected:
                continue
            next_retry = self._load_failures.get(
                version, (0, 0.0, 0.0)
            )[1]
            if time.monotonic() < next_retry:
                continue
            if best is None or version > best[0]:
                best = (version, path)
        return best

    # ---- load / swap / rollback ---------------------------------------

    def current(self) -> Optional[ServedModel]:
        return self._current

    def versions(self) -> List[int]:
        """Resident versions, current last."""
        with self._lock:
            out = [m.version for m in self._previous]
            if self._current is not None:
                out.append(self._current.version)
            return out

    def load_initial(self) -> ServedModel:
        """Blocking first load (the server refuses to start empty)."""
        found = self.newest_available()
        if found is None:
            raise FileNotFoundError(
                f"no complete serving bundle under {self.root}"
            )
        self._swap(self._load(found[1]))
        return self._current

    def _load(self, path: str) -> ServedModel:
        t0 = time.monotonic()
        model = self._loader(path)
        self._m_load_seconds.observe(time.monotonic() - t0)
        return model

    def _swap(self, model: ServedModel):
        with self._lock:
            if self._current is not None:
                self._previous.append(self._current)
                if self._retain:
                    del self._previous[:-self._retain]
                else:
                    self._previous.clear()
            self._current = model
        self._m_version.set(model.version)
        logger.info(
            "Serving model version %d from %s", model.version, model.path
        )

    def rollback(self) -> ServedModel:
        """Swap back to the most recent retained version; the dropped
        version is pinned out of future polls until a NEWER export
        appears (a fixed re-export gets a new version number)."""
        with self._lock:
            if not self._previous:
                raise RuntimeError("no previous version retained")
            bad = self._current
            self._current = self._previous.pop()
            self._rejected.add(bad.version)
            current = self._current
        self._m_version.set(current.version)
        self._m_reloads.labels(result="rollback").inc()
        logger.warning(
            "Rolled back serving model %d -> %d",
            bad.version, current.version,
        )
        return current

    def poll_once(self) -> bool:
        """One discovery+reload cycle; True if a new version went live."""
        found = self.newest_available()
        if found is None:
            return False
        version, path = found
        current = self._current
        if current is not None and version <= current.version:
            return False
        try:
            model = self._load(path)
        except Exception:
            failures, _, prev = self._load_failures.get(
                version, (0, 0.0, 0.0)
            )
            failures += 1
            # Decorrelated jitter (comm/rpc.py), not plain doubling:
            # many replicas watching one bad bundle directory would
            # otherwise re-load it in lockstep forever.
            from elasticdl_tpu.comm import overload
            from elasticdl_tpu.comm.rpc import decorrelated_jitter

            backoff = decorrelated_jitter(
                prev, base=self._poll_seconds, cap=300.0
            )
            if (overload.controls_enabled()
                    and not overload.retry_budget_for(
                        "ModelStore:load"
                    ).try_spend()):
                # Budget-denied: rate-cap further with the shared
                # serving retry budget instead of abandoning (the
                # next bundle version clears the failure entirely).
                backoff = max(backoff, self._poll_seconds * 4)
            self._load_failures[version] = (
                failures, time.monotonic() + backoff, backoff
            )
            logger.exception(
                "Failed to load bundle %s (version %d, attempt %d); "
                "retrying in %.0fs",
                path, version, failures, backoff,
            )
            self._m_reloads.labels(result="error").inc()
            return False
        if version in self._load_failures:
            from elasticdl_tpu.comm import overload

            if overload.controls_enabled():
                overload.retry_budget_for("ModelStore:load").on_success()
        self._load_failures.pop(version, None)
        self._swap(model)
        self._m_reloads.labels(result="ok").inc()
        return True

    def _poll_loop(self):
        while not self._stop.wait(self._poll_seconds):
            try:
                self.poll_once()
            except Exception:
                logger.exception("model store poll failed")

    def start_polling(self) -> "ModelStore":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._poll_loop, daemon=True,
                name="model-store-poll",
            )
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.row_cache is not None:
            self.row_cache.stop()
