"""Multi-version bundle management for the online serving plane.

The reference's serving story ended at a SavedModel directory handed to
TF Serving — which owns version polling, atomic swap, and rollback
(SURVEY §2.5's export path). Here the equivalent lifecycle is native:

- ``ModelStore`` watches an export root. Two layouts are accepted:
  a directory of versioned bundle subdirectories (what a training job's
  periodic ``SavedModelExporter`` produces when pointed at
  ``root/v<step>``) or a single bundle directory. A bundle is eligible
  only once ``metadata.json`` exists — the exporter writes it LAST, so
  presence == complete bundle (no partial-read races with the writer).
- New versions load on the store's poll thread, NEVER on the serving
  thread: the batcher keeps draining on the old version while the new
  one deserializes/compiles, then one atomic reference swap publishes
  it. The previous ``retain`` versions stay resident for instant
  ``rollback()`` (which also pins the rolled-back version so the
  poller doesn't immediately re-promote it).
- ``ServedModel.predict`` is the single entry the server calls. For
  row-service bundles (``metadata.host_serving``, exported via
  ``export_serving_bundle(host_id_keys=...)``) it resolves host-tier
  sparse features first: dedup the batch's raw ids, pull unique rows
  from the live ``HostRowService`` (embedding/row_service.py — the
  same pull plane training uses), bucket-pad to a power of two, and
  hand the row block to the StableHLO artifact through its symbolic
  row dim. Dense bundles pass features straight through.
"""

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.serving.export import (
    META_FILE,
    load_predictor,
)

logger = get_logger("model_store")


def _np_features(features):
    import jax

    return jax.tree.map(np.asarray, features)


class HostRowResolver:
    """Inference-time sparse-feature resolution against the row plane.

    Rewrites a combined batch's raw-id features into (inverse map,
    bucket-padded row block) pairs — the same dedup/bucket discipline
    ``HostEmbeddingEngine.prepare_batch`` applies in training, so the
    compiled-shape count stays O(log unique-ids) per table. Rows come
    from ``embedding/row_service.py`` remote tables (or any table-like
    with ``get(ids) -> (n, dim)``), which is what makes host-partitioned
    DeepFM-style models servable without materializing the vocab."""

    def __init__(self, host_serving: dict, tables: Dict,
                 feature_signature: Optional[dict] = None):
        self._id_keys = dict(host_serving["id_keys"])
        self._dims = {k: int(v)
                      for k, v in host_serving["tables"].items()}
        self._prefix = host_serving.get(
            "rows_feature_prefix", "__host_rows__:"
        )
        missing = set(self._id_keys) - set(tables)
        if missing:
            raise ValueError(
                f"row source serves no table(s) {sorted(missing)} "
                f"required by the bundle"
            )
        self._tables = tables
        # Inverse maps must be emitted in the DTYPE the artifact was
        # traced with (jax.export validates input avals strictly; an
        # int64-id example would otherwise reject every int32 inverse).
        self._id_dtypes = {}
        signature = feature_signature or {}
        for table_name, key in self._id_keys.items():
            spec = signature.get(key) if isinstance(signature, dict) \
                else None
            self._id_dtypes[table_name] = np.dtype(
                spec["dtype"] if isinstance(spec, dict)
                and "dtype" in spec else np.int32
            )

    def resolve(self, features: dict) -> dict:
        from elasticdl_tpu.embedding.host_engine import bucket_size

        if not isinstance(features, dict):
            raise TypeError(
                "row-service bundles need dict features carrying the "
                f"id keys {sorted(self._id_keys.values())}"
            )
        out = dict(features)
        for table_name, key in self._id_keys.items():
            raw = np.asarray(out[key])
            uniq, inverse = np.unique(raw, return_inverse=True)
            bucket = bucket_size(len(uniq))
            dim = self._dims[table_name]
            rows = np.zeros((bucket, dim), np.float32)
            rows[: len(uniq)] = np.asarray(
                self._tables[table_name].get(uniq), np.float32
            )
            out[key] = inverse.reshape(raw.shape).astype(
                self._id_dtypes[table_name]
            )
            out[self._prefix + table_name] = rows
        return out


def make_row_service_tables(addr: str, host_serving: dict,
                            retries: int = 12,
                            backoff_secs: float = 0.5) -> Dict:
    """Remote pull-only tables over running row-service shard(s) —
    the serving-side counterpart of ``make_remote_engine`` (no
    optimizer: inference never pushes)."""
    from elasticdl_tpu.embedding.row_service import make_remote_engine

    engine = make_remote_engine(
        addr,
        id_keys=dict(host_serving["id_keys"]),
        retries=retries, backoff_secs=backoff_secs,
    )
    return engine.tables


class ServedModel:
    """One loaded, callable bundle version."""

    def __init__(self, path: str, version: int, meta: dict,
                 predictor: Callable,
                 resolver: Optional[HostRowResolver] = None):
        self.path = path
        self.version = int(version)
        self.meta = meta
        self._predictor = predictor
        self._resolver = resolver

    @property
    def batch_polymorphic(self) -> bool:
        return bool(self.meta.get("batch_polymorphic", False))

    @property
    def static_batch_size(self) -> Optional[int]:
        """The one batch size a non-polymorphic artifact serves."""
        if self.batch_polymorphic:
            return None
        return int(self.meta.get("batch_size", 0)) or None

    def predict(self, features):
        if self._resolver is not None:
            features = self._resolver.resolve(features)
        return _np_features(self._predictor(features))


def load_served_model(bundle_dir: str, model=None,
                      row_tables: Optional[Dict] = None,
                      row_service_addr: str = "") -> ServedModel:
    """Load one bundle directory into a ``ServedModel``.

    ``row_tables`` / ``row_service_addr``: the row source for bundles
    exported in row-service mode (``metadata.host_serving``); exactly
    one is required for those, ignored for dense bundles. ``model`` is
    the flax-module fallback for non-self-contained dense bundles."""
    with open(os.path.join(bundle_dir, META_FILE)) as f:
        meta = json.load(f)
    resolver = None
    host_serving = meta.get("host_serving")
    if host_serving:
        if row_tables is None:
            if not row_service_addr:
                raise ValueError(
                    f"bundle {bundle_dir} was exported in row-service "
                    "mode; pass --row_service_addr (or row_tables) so "
                    "the server can pull rows at inference time"
                )
            row_tables = make_row_service_tables(
                row_service_addr, host_serving
            )
        resolver = HostRowResolver(
            host_serving, row_tables,
            feature_signature=meta.get("feature_signature"),
        )
    predictor = load_predictor(bundle_dir, model=model)
    return ServedModel(
        bundle_dir, meta.get("model_version", 0), meta, predictor,
        resolver,
    )


class ModelStore:
    """Version discovery + atomic hot reload + N-version rollback.

    ``root`` is either a directory of bundle subdirectories or itself a
    bundle. ``loader`` maps a bundle path to a ``ServedModel`` (the
    default binds ``load_served_model`` with this store's row source /
    fallback module). ``start_polling`` swaps in newer versions as the
    exporter publishes them; ``current()`` is what the serving thread
    reads — one attribute load, no lock on the hot path."""

    def __init__(self, root: str, model=None,
                 row_tables: Optional[Dict] = None,
                 row_service_addr: str = "",
                 retain: int = 1,
                 poll_seconds: float = 2.0,
                 loader: Optional[Callable[[str], ServedModel]] = None,
                 metrics_registry=None):
        self.root = root
        self._retain = max(0, int(retain))
        self._poll_seconds = float(poll_seconds)
        if loader is None:
            def loader(path):
                return load_served_model(
                    path, model=model, row_tables=row_tables,
                    row_service_addr=row_service_addr,
                )
        self._loader = loader
        self._lock = threading.Lock()
        self._current: Optional[ServedModel] = None
        self._previous: List[ServedModel] = []  # newest last
        self._rejected = set()  # rolled-back versions (operator pin)
        # Load failures back off instead of pinning: a row-service
        # bundle can fail to load while its row plane restarts, and
        # re-exporting the same checkpoint reuses the same version
        # number — permanent rejection would wedge until a server
        # restart. {version: (consecutive failures, next retry time)}.
        self._load_failures: Dict[int, tuple] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self._m_version = registry.gauge(
            "serving_model_version",
            "Model version currently served",
        )
        self._m_reloads = registry.counter(
            "serving_model_reloads_total",
            "Successful hot reloads", labelnames=("result",),
        )
        self._m_load_seconds = registry.histogram(
            "serving_model_load_seconds",
            "Bundle load (deserialize + warm) latency",
        )

    # ---- discovery -----------------------------------------------------

    def _candidates(self) -> List[str]:
        """Complete bundle dirs under root (root itself counts)."""
        if os.path.exists(os.path.join(self.root, META_FILE)):
            return [self.root]
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in sorted(os.listdir(self.root)):
            path = os.path.join(self.root, name)
            if os.path.exists(os.path.join(path, META_FILE)):
                out.append(path)
        return out

    @staticmethod
    def _bundle_version(path: str) -> int:
        try:
            with open(os.path.join(path, META_FILE)) as f:
                return int(json.load(f).get("model_version", 0))
        except (OSError, ValueError, json.JSONDecodeError):
            return -1

    def newest_available(self):
        """(version, path) of the newest complete bundle that is
        neither rolled back nor inside its failure backoff window."""
        best = None
        for path in self._candidates():
            version = self._bundle_version(path)
            if version < 0 or version in self._rejected:
                continue
            _, next_retry = self._load_failures.get(version, (0, 0.0))
            if time.monotonic() < next_retry:
                continue
            if best is None or version > best[0]:
                best = (version, path)
        return best

    # ---- load / swap / rollback ---------------------------------------

    def current(self) -> Optional[ServedModel]:
        return self._current

    def versions(self) -> List[int]:
        """Resident versions, current last."""
        with self._lock:
            out = [m.version for m in self._previous]
            if self._current is not None:
                out.append(self._current.version)
            return out

    def load_initial(self) -> ServedModel:
        """Blocking first load (the server refuses to start empty)."""
        found = self.newest_available()
        if found is None:
            raise FileNotFoundError(
                f"no complete serving bundle under {self.root}"
            )
        self._swap(self._load(found[1]))
        return self._current

    def _load(self, path: str) -> ServedModel:
        t0 = time.monotonic()
        model = self._loader(path)
        self._m_load_seconds.observe(time.monotonic() - t0)
        return model

    def _swap(self, model: ServedModel):
        with self._lock:
            if self._current is not None:
                self._previous.append(self._current)
                if self._retain:
                    del self._previous[:-self._retain]
                else:
                    self._previous.clear()
            self._current = model
        self._m_version.set(model.version)
        logger.info(
            "Serving model version %d from %s", model.version, model.path
        )

    def rollback(self) -> ServedModel:
        """Swap back to the most recent retained version; the dropped
        version is pinned out of future polls until a NEWER export
        appears (a fixed re-export gets a new version number)."""
        with self._lock:
            if not self._previous:
                raise RuntimeError("no previous version retained")
            bad = self._current
            self._current = self._previous.pop()
            self._rejected.add(bad.version)
            current = self._current
        self._m_version.set(current.version)
        self._m_reloads.labels(result="rollback").inc()
        logger.warning(
            "Rolled back serving model %d -> %d",
            bad.version, current.version,
        )
        return current

    def poll_once(self) -> bool:
        """One discovery+reload cycle; True if a new version went live."""
        found = self.newest_available()
        if found is None:
            return False
        version, path = found
        current = self._current
        if current is not None and version <= current.version:
            return False
        try:
            model = self._load(path)
        except Exception:
            failures, _ = self._load_failures.get(version, (0, 0.0))
            failures += 1
            backoff = min(
                self._poll_seconds * (2 ** failures), 300.0
            )
            self._load_failures[version] = (
                failures, time.monotonic() + backoff
            )
            logger.exception(
                "Failed to load bundle %s (version %d, attempt %d); "
                "retrying in %.0fs",
                path, version, failures, backoff,
            )
            self._m_reloads.labels(result="error").inc()
            return False
        self._load_failures.pop(version, None)
        self._swap(model)
        self._m_reloads.labels(result="ok").inc()
        return True

    def _poll_loop(self):
        while not self._stop.wait(self._poll_seconds):
            try:
                self.poll_once()
            except Exception:
                logger.exception("model store poll failed")

    def start_polling(self) -> "ModelStore":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._poll_loop, daemon=True,
                name="model-store-poll",
            )
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
