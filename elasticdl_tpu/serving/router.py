"""Serving fleet front-end: route, hedge, and shed across replicas.

PR 2's serving plane is one ``serving/server.py`` process; millions of
users need a fleet. This router is the Podracer shape (arXiv
2104.06272): decoupled fleets scaled independently around shared state
— N stateless predict replicas in front of the ONE row service that
remains the source of truth (each replica's hot-row cache,
``serving/model_store.py``, only memoizes reads of it).

- **Routing policies**: ``least_loaded`` (default) picks the healthy
  replica with the fewest router-tracked in-flight requests,
  round-robin among ties. ``hash`` is an opt-in consistent-hash ring
  over a routing key (``X-User-Id`` header, else a digest of the
  request body): one user's ids keep landing on one replica, so that
  replica's hot-row LRU holds their rows — higher cache hit rate,
  bought with worse load balance (docs/serving.md "Fleet").
  Removing a replica from the ring only remaps the keys that lived on
  it; everyone else's affinity (and cache) survives.
- **Request hedging**: after an adaptive delay (p95 of recent attempt
  latencies, clamped to [hedge_min_ms, hedge_max_ms]) a straggling
  request is re-issued to a DIFFERENT replica with ``X-Hedge: 1``;
  first answer wins, the loser's connection is closed (its replica
  sheds hedges first under pressure, so speculation never compounds an
  overload). The tracing plane's ``route``/``attempt`` spans land on a
  ``router`` track next to the replicas' ``queue_wait``/``predict``
  spans, so hedge wins are attributable end to end.
- **Tiered shedding**: the router tracks fleet load (in-flight /
  (healthy replicas x replica_concurrency)) and sheds in tiers —
  hedging stops first, then low-priority traffic 429s with
  ``Retry-After``, then everything. Replicas keep their own queue-depth
  tiers (serving/server.py) as the second line of defense.
- **Health**: a connection failure marks a replica unhealthy
  immediately (routing skips it — the chaos drill kills a replica
  mid-load and availability holds); a background prober restores it
  when ``/healthz`` answers again.
"""

import hashlib
import http.client
import json
import threading
import time
from bisect import bisect_right
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional, Tuple

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import tracing

logger = get_logger("router")


def _hash64(key: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(),
        "big",
    )


class Replica:
    """One backend ``serving/server.py`` process as the router sees it:
    address, router-tracked in-flight count, health, and a small
    keep-alive connection pool (per-request TCP setup would double the
    router's latency floor)."""

    def __init__(self, addr: str, index: int, pool_size: int = 16,
                 timeout: float = 30.0):
        self.addr = addr
        self.index = index
        self.inflight = 0  # guarded by the router core's lock
        self.healthy = True
        self.consecutive_failures = 0
        self._timeout = float(timeout)
        self._pool: List[http.client.HTTPConnection] = []
        self._pool_size = int(pool_size)
        self._pool_lock = threading.Lock()

    def _new_conn(self) -> http.client.HTTPConnection:
        host, _, port = self.addr.partition(":")
        return http.client.HTTPConnection(
            host, int(port or 80), timeout=self._timeout
        )

    def acquire_conn(self) -> http.client.HTTPConnection:
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return self._new_conn()

    def release_conn(self, conn: http.client.HTTPConnection):
        with self._pool_lock:
            if len(self._pool) < self._pool_size:
                self._pool.append(conn)
                return
        conn.close()

    def close_pool(self):
        with self._pool_lock:
            pool, self._pool = self._pool, []
        for conn in pool:
            conn.close()

    def state(self) -> dict:
        return {
            "addr": self.addr,
            "index": self.index,
            "healthy": self.healthy,
            "inflight": self.inflight,
            "consecutive_failures": self.consecutive_failures,
        }


class LeastLoadedPolicy:
    """Pick the healthy replica with the fewest in-flight requests;
    rotate among ties so an idle fleet still spreads."""

    name = "least_loaded"

    def __init__(self):
        self._tick = 0
        self._lock = threading.Lock()

    def pick(self, replicas: List[Replica], key: Optional[str] = None,
             exclude: Tuple[Replica, ...] = ()) -> Optional[Replica]:
        candidates = [
            r for r in replicas if r.healthy and r not in exclude
        ]
        if not candidates:
            # Everyone looks down: try any non-excluded replica —
            # the prober may lag a recovery, and a failed attempt
            # re-confirms unhealth anyway.
            candidates = [r for r in replicas if r not in exclude]
        if not candidates:
            return None
        with self._lock:
            self._tick += 1
            offset = self._tick
        n = len(replicas)
        return min(
            candidates,
            key=lambda r: (r.inflight, (r.index + offset) % n),
        )


class ConsistentHashPolicy:
    """Consistent-hash ring over a routing key, ``vnodes`` virtual
    nodes per replica. ``pick`` walks clockwise from the key's point,
    skipping unhealthy/excluded replicas — removing a replica only
    remaps the keys that lived on it (cache affinity elsewhere
    survives), which is the property the per-replica hot-row cache
    buys hit rate with."""

    name = "hash"

    def __init__(self, replicas: List[Replica], vnodes: int = 64):
        self._ring: List[Tuple[int, int]] = []  # (point, replica idx)
        for replica in replicas:
            for v in range(vnodes):
                self._ring.append(
                    (_hash64(f"{replica.addr}#{v}"), replica.index)
                )
        self._ring.sort()
        self._fallback = LeastLoadedPolicy()

    def pick(self, replicas: List[Replica], key: Optional[str] = None,
             exclude: Tuple[Replica, ...] = ()) -> Optional[Replica]:
        if key is None or not self._ring:
            return self._fallback.pick(replicas, exclude=exclude)
        by_index = {r.index: r for r in replicas}
        start = bisect_right(self._ring, (_hash64(key), len(replicas)))
        seen = set()
        for i in range(len(self._ring)):
            _, index = self._ring[(start + i) % len(self._ring)]
            if index in seen:
                continue
            seen.add(index)
            replica = by_index.get(index)
            if replica is None or replica in exclude:
                continue
            if replica.healthy:
                return replica
        # Ring exhausted healthy options; last resort like least-loaded.
        return self._fallback.pick(replicas, exclude=exclude)


class AdaptiveHedge:
    """Hedge-delay controller: fire the second attempt once a request
    has outlived the p95 of recent attempt latencies (clamped). Until
    ``min_samples`` attempts are observed the delay pins to the max —
    hedging stays shy until it knows what 'slow' means."""

    def __init__(self, min_ms: float = 5.0, max_ms: float = 1000.0,
                 window: int = 512, min_samples: int = 20):
        self.min_secs = float(min_ms) / 1e3
        self.max_secs = float(max_ms) / 1e3
        self._window = deque(maxlen=int(window))
        self._min_samples = int(min_samples)
        self._lock = threading.Lock()

    def observe(self, secs: float):
        with self._lock:
            self._window.append(float(secs))

    def delay_secs(self) -> float:
        with self._lock:
            if len(self._window) < self._min_samples:
                return self.max_secs
            ordered = sorted(self._window)
            p95 = ordered[min(
                len(ordered) - 1, int(0.95 * len(ordered))
            )]
        return min(self.max_secs, max(self.min_secs, p95))


class _Attempt:
    """One forwarded try of one request against one replica, run on
    its own thread so the router can race a hedge against it."""

    def __init__(self, core: "RouterCore", replica: Replica,
                 body: bytes, content_type: str, priority: str,
                 hedge: bool):
        self.core = core
        self.replica = replica
        self.body = body
        self.content_type = content_type
        self.priority = priority
        self.hedge = hedge
        self.outcome = None  # (status, raw, content_type, retry_after)
        self.error: Optional[Exception] = None
        self.elapsed = 0.0
        self.fired_at = 0.0
        self.done = threading.Event()
        # Invoked in run()'s finally BEFORE done is set: a hedge's
        # race.offer must be visible to anyone done.wait() wakes, or
        # the waiter can read winner=None and discard a good answer.
        self.on_done = None
        self._conn: Optional[http.client.HTTPConnection] = None
        self._cancelled = False
        self._lock = threading.Lock()

    def cancel(self):
        """Loser teardown: closing the socket aborts the blocked
        ``getresponse`` on the attempt thread — the replica-side
        handler finishes its batch slot, but this router thread stops
        waiting and the response bytes are discarded."""
        with self._lock:
            self._cancelled = True
            conn = self._conn
        if conn is not None:
            conn.close()

    def run(self):
        t0 = time.monotonic()
        conn = self.replica.acquire_conn()
        with self._lock:
            if self._cancelled:
                conn.close()
                self.error = RuntimeError("cancelled before send")
                self.core._finish_attempt(self)
                self.done.set()
                return
            self._conn = conn
        headers = {"Content-Type": self.content_type,
                   "X-Priority": self.priority}
        if self.hedge:
            headers["X-Hedge"] = "1"
        try:
            conn.request("POST", "/v1/predict", body=self.body,
                         headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            self.outcome = (
                resp.status, raw,
                resp.getheader("Content-Type", "application/json"),
                resp.getheader("Retry-After"),
            )
            self.elapsed = time.monotonic() - t0
            with self._lock:
                self._conn = None
                cancelled = self._cancelled
            if cancelled:
                conn.close()
            else:
                self.replica.release_conn(conn)
        except Exception as exc:  # transport failure or cancel
            self.elapsed = time.monotonic() - t0
            self.error = exc
            with self._lock:
                self._conn = None
            conn.close()
        finally:
            self.core._finish_attempt(self)
            if self.on_done is not None:
                try:
                    self.on_done()
                except Exception:
                    logger.exception("attempt on_done callback failed")
            self.done.set()


class _Race:
    """First-usable-answer-wins arbitration between a request's
    attempts."""

    __slots__ = ("winner", "lock", "done")

    def __init__(self):
        self.winner: Optional[_Attempt] = None
        self.lock = threading.Lock()
        self.done = threading.Event()

    def offer(self, attempt: _Attempt) -> bool:
        with self.lock:
            if self.winner is None:
                self.winner = attempt
                self.done.set()
                return True
            return False


class _HedgeScheduler:
    """ONE timer thread arming every pending hedge: the primary
    attempt runs INLINE on its handler thread (the fast path is a
    plain proxy — no thread handoff, no wakeup round trips), so
    something else must watch the clock. Entries fire in deadline
    order; cancellation is a flag (lazy removal)."""

    def __init__(self):
        import heapq

        self._heapq = heapq
        self._heap = []  # (fire_at, seq, entry)
        self._seq = 0
        self._cond = threading.Condition()
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="router-hedge",
            )
            self._thread.start()

    def stop(self):
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def schedule(self, fire_at: float, fn) -> dict:
        entry = {"fn": fn, "cancelled": False}
        with self._cond:
            self._seq += 1
            self._heapq.heappush(
                self._heap, (fire_at, self._seq, entry)
            )
            self._cond.notify_all()
        return entry

    @staticmethod
    def cancel(entry: dict):
        entry["cancelled"] = True

    def _loop(self):
        while True:
            with self._cond:
                while not self._stop:
                    if not self._heap:
                        self._cond.wait()
                        continue
                    delay = self._heap[0][0] - time.monotonic()
                    if delay <= 0:
                        break
                    self._cond.wait(timeout=delay)
                if self._stop:
                    return
                _, _, entry = self._heapq.heappop(self._heap)
            if entry["cancelled"]:
                continue
            try:
                entry["fn"]()
            except Exception:
                logger.exception("hedge fire failed")


class RouterCore:
    """Transport-agnostic routing brain (the HTTP front and the tests
    drive it directly): policy pick + hedging + tiered shedding +
    health bookkeeping."""

    class ShedError(RuntimeError):
        def __init__(self, message: str, tier: str,
                     retry_after: float = 1.0):
            super().__init__(message)
            self.tier = tier
            self.retry_after = retry_after

    class NoReplicaError(RuntimeError):
        pass

    def __init__(self, replica_addrs: List[str],
                 policy: str = "least_loaded",
                 replica_concurrency: int = 32,
                 hedge: bool = True,
                 hedge_min_ms: float = 5.0,
                 hedge_max_ms: float = 1000.0,
                 hedge_shed_frac: float = 0.5,
                 low_shed_frac: float = 0.75,
                 unhealthy_after: int = 1,
                 probe_secs: float = 1.0,
                 replica_timeout: float = 30.0,
                 slo_window_secs: float = 60.0,
                 slo_p95_ms: float = 500.0,
                 slo_error_ratio: float = 0.05,
                 metrics_registry=None):
        if not replica_addrs:
            raise ValueError("router needs at least one replica")
        self.replicas = [
            Replica(addr, i, timeout=replica_timeout)
            for i, addr in enumerate(replica_addrs)
        ]
        # Per-replica SLO status (the /v1/replicas "slo" field): a
        # rolling window of attempt outcomes per replica, judged
        # against a p95-latency + error-ratio objective — the
        # router-local sibling of the master's SLO engine
        # (observability/slo.py; full rules run master-side on the
        # piggybacked router_* families).
        from elasticdl_tpu.observability.slo import RollingWindow

        self.slo_p95_ms = float(slo_p95_ms)
        self.slo_error_ratio = float(slo_error_ratio)
        self._slo_windows = [
            RollingWindow(window_secs=slo_window_secs)
            for _ in self.replicas
        ]
        if policy == "hash":
            self.policy = ConsistentHashPolicy(self.replicas)
        elif policy == "least_loaded":
            self.policy = LeastLoadedPolicy()
        else:
            raise ValueError(
                f"unknown routing policy {policy!r} "
                "(least_loaded | hash)"
            )
        self.replica_concurrency = int(replica_concurrency)
        self.hedge_enabled = bool(hedge)
        self.hedge = AdaptiveHedge(hedge_min_ms, hedge_max_ms)
        self.hedge_shed_frac = float(hedge_shed_frac)
        self.low_shed_frac = float(low_shed_frac)
        self.unhealthy_after = max(1, int(unhealthy_after))
        self.probe_secs = float(probe_secs)
        self._lock = threading.Lock()
        self._inflight_requests = 0
        self._idle = threading.Condition(self._lock)
        self._tracer = tracing.Tracer("router")
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        # The PRIMARY attempt runs inline on the handler thread (the
        # fast path is a plain proxy); this pool only runs fired
        # hedges, and the scheduler thread is the only clock watcher.
        from concurrent.futures import ThreadPoolExecutor

        self._executor = ThreadPoolExecutor(
            max_workers=max(
                4, min(64, self.replica_concurrency)
            ),
            thread_name_prefix="router-hedge-attempt",
        )
        self._scheduler = _HedgeScheduler()

        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self.registry = registry
        self._m_requests = registry.counter(
            "router_requests_total",
            "Routed predict requests by final HTTP status",
            labelnames=("code",),
        )
        self._m_seconds = registry.histogram(
            "router_request_seconds",
            "Route latency (receive to winning reply)",
        )
        self._m_attempts = registry.counter(
            "router_attempts_total",
            "Forwarded attempts per replica",
            labelnames=("replica",),
        )
        self._m_retries = registry.counter(
            "router_failovers_total",
            "Attempts re-routed after a replica transport failure",
        )
        self._m_hedges = registry.counter(
            "router_hedges_total",
            "Hedged second attempts by outcome "
            "(fired / won / cancelled)",
            labelnames=("event",),
        )
        self._m_shed = registry.counter(
            "router_shed_total",
            "Requests shed at the router by tier",
            labelnames=("tier",),
        )
        self._m_unhealthy = registry.counter(
            "router_replica_unhealthy_total",
            "Replica transitions to unhealthy",
        )
        import weakref

        self_ref = weakref.ref(self)
        registry.gauge(
            "router_inflight",
            "Requests currently being routed",
        ).set_function(
            lambda: float(self_ref()._inflight_requests)
            if self_ref() else 0.0
        )
        registry.gauge(
            "router_replicas_healthy",
            "Replicas currently believed healthy",
        ).set_function(
            lambda: float(
                sum(r.healthy for r in self_ref().replicas)
            ) if self_ref() else 0.0
        )
        registry.gauge(
            "router_hedge_delay_seconds",
            "Current adaptive hedge delay (p95-based)",
        ).set_function(
            lambda: self_ref().hedge.delay_secs() if self_ref() else 0.0
        )

    # ---- health --------------------------------------------------------

    def _note_result(self, replica: Replica, ok: bool):
        with self._lock:
            if ok:
                replica.consecutive_failures = 0
                if not replica.healthy:
                    replica.healthy = True
                    logger.info(
                        "replica %s healthy again (request succeeded)",
                        replica.addr,
                    )
                return
            replica.consecutive_failures += 1
            if (replica.healthy
                    and replica.consecutive_failures
                    >= self.unhealthy_after):
                replica.healthy = False
                self._m_unhealthy.inc()
                logger.warning(
                    "replica %s marked unhealthy after %d failures",
                    replica.addr, replica.consecutive_failures,
                )
        if not replica.healthy:
            # Stale keep-alive conns to a dead process HANG (the
            # listener is gone but the kernel keeps the socket);
            # restore with fresh connections after /healthz answers.
            replica.close_pool()

    def _probe_once(self):
        for replica in self.replicas:
            if replica.healthy:
                continue
            try:
                conn = replica._new_conn()
                try:
                    conn.request("GET", "/healthz")
                    status = conn.getresponse().status
                finally:
                    conn.close()
            except Exception:
                continue
            if status == 200:
                with self._lock:
                    replica.healthy = True
                    replica.consecutive_failures = 0
                logger.info("replica %s healthy again (probe)",
                            replica.addr)

    def _probe_loop(self):
        while not self._stop.wait(self.probe_secs):
            try:
                self._probe_once()
            except Exception:
                logger.exception("replica probe failed")

    def start(self) -> "RouterCore":
        self._scheduler.start()
        if self._prober is None:
            self._prober = threading.Thread(
                target=self._probe_loop, daemon=True,
                name="router-probe",
            )
            self._prober.start()
        return self

    def stop(self):
        self._stop.set()
        self._scheduler.stop()
        if self._prober is not None:
            self._prober.join(timeout=5)
            self._prober = None
        self._executor.shutdown(wait=False)
        for replica in self.replicas:
            replica.close_pool()

    # ---- shedding ------------------------------------------------------

    def load_factor(self) -> float:
        healthy = sum(r.healthy for r in self.replicas)
        capacity = max(1, healthy) * self.replica_concurrency
        return self._inflight_requests / capacity

    def _admit(self, priority: str):
        """Tiered admission: everything sheds at capacity, low
        priority earlier; hedging is suppressed separately in
        ``handle`` (tier 'hedge' = speculation stops first)."""
        load = self.load_factor()
        if load >= 1.0:
            raise self.ShedError(
                f"router at capacity (load {load:.2f})",
                tier="capacity", retry_after=2.0,
            )
        if priority == "low" and load >= self.low_shed_frac:
            raise self.ShedError(
                f"low-priority shed (load {load:.2f})",
                tier="low", retry_after=1.0,
            )

    # ---- routing -------------------------------------------------------

    def _finish_attempt(self, attempt: _Attempt):
        with self._lock:
            attempt.replica.inflight -= 1
        if attempt.error is None and attempt.outcome is not None \
                and attempt.outcome[0] == 200:
            # Only served answers are service-time samples: a replica
            # shedding 429s answers in ~1ms, and feeding those into
            # the p95 window would collapse the hedge delay to its
            # floor exactly when the fleet is overloaded — doubling
            # attempt volume with zero headroom.
            self.hedge.observe(attempt.elapsed)
        if not attempt._cancelled:
            # A cancelled loser says nothing about replica health.
            ok = attempt.error is None
            self._note_result(attempt.replica, ok)
            # SLO sample: transport failures and 5xx count against the
            # replica. Sheds (429) are EXCLUDED entirely — same
            # discipline as the hedge window above: an overloaded
            # replica answering fast 429s would otherwise report a
            # collapsed p95 and a clean error ratio (ok=true) exactly
            # during the overload /v1/replicas exists to surface.
            if attempt.outcome is not None \
                    and attempt.outcome[0] == 429:
                return
            served_ok = ok and attempt.outcome is not None \
                and attempt.outcome[0] < 500
            self._slo_windows[attempt.replica.index].record(
                served_ok, attempt.elapsed
            )

    def _make_attempt(self, replica: Replica, body, content_type,
                      priority, hedge: bool) -> _Attempt:
        attempt = _Attempt(
            self, replica, body, content_type, priority, hedge
        )
        with self._lock:
            replica.inflight += 1
        self._m_attempts.labels(replica=str(replica.index)).inc()
        attempt.fired_at = time.monotonic()
        return attempt

    def _fire_hedge(self, race: _Race, primary: _Attempt, body,
                    content_type, priority, routing_key, hedge_box):
        """Scheduler callback at the hedge deadline: if the primary is
        still out and the fleet has headroom, race a second attempt on
        another replica. The winner cancels the loser — closing the
        primary's socket is what unblocks its inline handler thread."""
        if primary.done.is_set() or race.winner is not None:
            return
        if self.load_factor() >= self.hedge_shed_frac:
            return
        second = self.policy.pick(
            self.replicas, key=routing_key,
            exclude=(primary.replica,),
        )
        if second is None:
            return
        attempt = self._make_attempt(
            second, body, content_type, priority, hedge=True
        )

        def settle():
            if self._usable(attempt) and race.offer(attempt):
                primary.cancel()

        attempt.on_done = settle
        hedge_box.append(attempt)
        self._m_hedges.labels(event="fired").inc()
        self._executor.submit(attempt.run)

    def handle(self, body: bytes, content_type: str,
               priority: str = "normal",
               routing_key: Optional[str] = None,
               timeout: float = 30.0):
        """Route one predict request; returns (status, raw_body,
        content_type, headers). Raises ShedError / NoReplicaError."""
        t0 = time.monotonic()
        with self._lock:
            self._admit(priority)  # reads inflight under the lock
            self._inflight_requests += 1
        try:
            with self._tracer.span(
                "route", priority=priority,
                policy=self.policy.name,
            ) as route_span:
                result = self._handle_inner(
                    body, content_type, priority, routing_key,
                    timeout, route_span,
                )
            self._m_seconds.observe(time.monotonic() - t0)
            self._m_requests.labels(code=str(result[0])).inc()
            return result
        finally:
            with self._idle:  # same lock as self._lock
                self._inflight_requests -= 1
                self._idle.notify_all()

    @staticmethod
    def _usable(attempt: _Attempt) -> bool:
        """An answer the client can have. A hedge's own 429 is NOT
        one — that's the replica shedding the speculation (tier
        'hedge') while the primary still works."""
        return attempt.outcome is not None and not (
            attempt.hedge and attempt.outcome[0] == 429
        )

    def _record_attempt_span(self, route_span, attempt: _Attempt):
        if route_span.span_id is None:
            return
        tracing.record_span(
            "attempt",
            time.monotonic() - attempt.elapsed, attempt.elapsed,
            trace_id=route_span.trace_id,
            parent_id=route_span.span_id,
            role="router",
            replica=attempt.replica.index,
            hedge=attempt.hedge,
            status=(attempt.outcome[0]
                    if attempt.outcome else "error"),
        )

    def _handle_inner(self, body, content_type, priority, routing_key,
                      timeout, route_span):
        deadline = time.monotonic() + timeout
        primary_replica = self.policy.pick(self.replicas,
                                           key=routing_key)
        if primary_replica is None:
            raise self.NoReplicaError("no replica available")
        race = _Race()
        hedge_box: List[_Attempt] = []  # appended by the scheduler
        primary = self._make_attempt(
            primary_replica, body, content_type, priority, hedge=False
        )
        hedge_token = None
        if (self.hedge_enabled and len(self.replicas) > 1
                and self.load_factor() < self.hedge_shed_frac):
            hedge_token = self._scheduler.schedule(
                time.monotonic() + self.hedge.delay_secs(),
                lambda: self._fire_hedge(
                    race, primary, body, content_type, priority,
                    routing_key, hedge_box,
                ),
            )
        # The primary runs INLINE: the fast path is one proxied HTTP
        # round trip on this very thread. A winning hedge closes the
        # primary's socket, which is what unblocks this call early.
        primary.run()
        if hedge_token is not None:
            self._scheduler.cancel(hedge_token)
        if self._usable(primary):
            race.offer(primary)
        winner = race.winner
        if winner is None and hedge_box:
            # Primary failed (or returned a discarded answer) with a
            # hedge in flight: its result is the next best hope.
            hedge_box[0].done.wait(
                max(0.0, deadline - time.monotonic())
            )
            winner = race.winner
        if winner is None:
            # Nothing usable yet: one inline failover onto an
            # untried replica.
            tried = (primary.replica,) + tuple(
                a.replica for a in hedge_box
            )
            fallback = self.policy.pick(
                self.replicas, key=routing_key, exclude=tried
            )
            if fallback is not None \
                    and time.monotonic() < deadline:
                self._m_retries.inc()
                failover = self._make_attempt(
                    fallback, body, content_type, priority,
                    hedge=False,
                )
                failover.run()
                if self._usable(failover):
                    race.offer(failover)
                winner = race.winner
        if winner is None:
            for attempt in [primary] + hedge_box:
                if not attempt.done.is_set():
                    attempt.cancel()
            errors = [
                a.error for a in [primary] + hedge_box
                if a.error is not None
            ]
            if errors:
                raise errors[0]
            raise RuntimeError("no usable replica response")
        # Settle the race: cancel the in-flight loser, account wins.
        for attempt in [primary] + hedge_box:
            if attempt is winner:
                continue
            if not attempt.done.is_set():
                attempt.cancel()
                if attempt.hedge:
                    self._m_hedges.labels(event="cancelled").inc()
        if winner.hedge:
            self._m_hedges.labels(event="won").inc()
            if primary._cancelled:
                # A primary a hedge had to rescue is suspect: count a
                # failure so repeat offenders go unhealthy and the
                # /healthz prober must clear them (a merely slow
                # replica answers the probe and comes right back; a
                # dead one stays out instead of burning a hedge per
                # request until its socket timeout).
                self._note_result(primary.replica, ok=False)
        self._record_attempt_span(route_span, winner)
        route_span.set(
            replica=winner.replica.index, hedged=winner.hedge,
            status=winner.outcome[0],
        )
        status, raw, ctype, retry_after = winner.outcome
        headers = []
        if retry_after:
            headers.append(("Retry-After", retry_after))
        return status, raw, ctype, headers

    # ---- drain ---------------------------------------------------------

    def wait_idle(self, timeout: float) -> bool:
        """Block until no request is in flight (hedges included —
        every attempt decrements before its route returns)."""
        deadline = time.monotonic() + timeout
        with self._idle:
            while self._inflight_requests > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._idle.wait(timeout=min(remaining, 0.05))
        return True

    def replica_slo(self, index: int) -> dict:
        """Windowed per-replica SLO status: request count, error
        ratio, p95, and the ok verdict against the configured
        objective. ``ok`` is None (unknown) on an empty window — a
        just-started or idle replica has no evidence either way."""
        status = self._slo_windows[index].status()
        if status["requests"] == 0:
            status["ok"] = None
            return status
        status["ok"] = bool(
            status["error_ratio"] <= self.slo_error_ratio
            and (self.slo_p95_ms <= 0
                 or status["p95_ms"] <= self.slo_p95_ms)
        )
        return status

    def states(self) -> List[dict]:
        with self._lock:
            states = [r.state() for r in self.replicas]
        for state in states:
            state["slo"] = self.replica_slo(state["index"])
        return states


class _RouterHandler(BaseHTTPRequestHandler):
    server_ref = None  # type: Optional[RouterServer]

    protocol_version = "HTTP/1.1"

    def _reply(self, code: int, body: bytes, content_type: str,
               headers=()):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for name, value in headers:
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(body)

    def _reply_json_error(self, code: int, message: str, headers=()):
        self._reply(
            code, json.dumps({"error": message}).encode("utf-8"),
            "application/json", headers,
        )

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        srv = type(self).server_ref
        core = srv.core
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            from elasticdl_tpu.observability import render_prometheus

            body = render_prometheus(core.registry.snapshot())
            self._reply(
                200, body.encode("utf-8"),
                "text/plain; version=0.0.4; charset=utf-8",
            )
        elif path == "/traces":
            body = json.dumps(
                {"spans": tracing.recorder_spans()}
            ).encode("utf-8")
            self._reply(200, body, "application/json")
        elif path == "/healthz":
            ok = any(r.healthy for r in core.replicas)
            self._reply(
                200 if ok else 503,
                b"ok\n" if ok else b"no healthy replica\n",
                "text/plain; charset=utf-8",
            )
        elif path == "/v1/replicas":
            body = json.dumps({
                "policy": core.policy.name,
                "load_factor": round(core.load_factor(), 4),
                "hedge_delay_ms": round(
                    core.hedge.delay_secs() * 1e3, 3
                ),
                "replicas": core.states(),
            }).encode("utf-8")
            self._reply(200, body, "application/json")
        elif path == "/v1/models":
            # Pass through to a healthy replica so clients discover
            # the feature signature through the router unchanged.
            replica = core.policy.pick(core.replicas)
            if replica is None:
                self._reply_json_error(503, "no replica available")
                return
            try:
                conn = replica.acquire_conn()
                try:
                    conn.request("GET", "/v1/models")
                    resp = conn.getresponse()
                    raw = resp.read()
                    self._reply(
                        resp.status, raw,
                        resp.getheader(
                            "Content-Type", "application/json"
                        ),
                    )
                finally:
                    replica.release_conn(conn)
            except Exception as exc:
                self._reply_json_error(502, f"replica error: {exc}")
        else:
            self.send_error(
                404, "try /v1/predict, /v1/replicas, /metrics"
            )

    def do_POST(self):  # noqa: N802
        srv = type(self).server_ref
        core = srv.core
        path = self.path.split("?", 1)[0]
        if path != "/v1/predict":
            self.send_error(404, "POST /v1/predict")
            return
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        content_type = self.headers.get(
            "Content-Type", "application/json"
        )
        priority = self.headers.get("X-Priority", "normal").lower()
        if priority not in ("high", "normal", "low"):
            priority = "normal"
        routing_key = self.headers.get(srv.routing_key_header)
        if routing_key is None and core.policy.name == "hash":
            # No explicit user id: key on the payload itself — the
            # same ids still land on the same replica's cache.
            routing_key = hashlib.blake2b(
                body, digest_size=8
            ).hexdigest()
        if srv.draining:
            core._m_shed.labels(tier="draining").inc()
            core._m_requests.labels(code="429").inc()
            self._reply_json_error(
                429, "router draining (SIGTERM)",
                headers=(("Retry-After", "2"),),
            )
            return
        try:
            status, raw, ctype, headers = core.handle(
                body, content_type, priority=priority,
                routing_key=routing_key, timeout=srv.request_timeout,
            )
        except RouterCore.ShedError as exc:
            core._m_shed.labels(tier=exc.tier).inc()
            core._m_requests.labels(code="429").inc()
            self._reply_json_error(
                429, str(exc),
                headers=(
                    ("Retry-After",
                     str(max(1, int(round(exc.retry_after))))),
                    ("X-Shed-Tier", exc.tier),
                ),
            )
            return
        except RouterCore.NoReplicaError as exc:
            core._m_requests.labels(code="503").inc()
            self._reply_json_error(503, str(exc))
            return
        except TimeoutError as exc:
            core._m_requests.labels(code="504").inc()
            self._reply_json_error(504, str(exc))
            return
        except Exception as exc:
            core._m_requests.labels(code="502").inc()
            self._reply_json_error(
                502, f"{type(exc).__name__}: {exc}"
            )
            return
        self._reply(status, raw, ctype, headers)

    def log_message(self, fmt, *args):
        logger.debug("router http: " + fmt, *args)


class RouterServer:
    """The assembled router process: core + HTTP front + drain."""

    def __init__(self, replica_addrs: List[str], port: int = 8600,
                 host: str = "", request_timeout: float = 30.0,
                 routing_key_header: str = "X-User-Id",
                 master_addr: str = "", router_id: int = 0,
                 metrics_report_secs: float = 15.0,
                 **core_kwargs):
        self.core = RouterCore(replica_addrs, **core_kwargs)
        self.request_timeout = float(request_timeout)
        self.routing_key_header = routing_key_header
        self.draining = False
        self._host = host
        self._requested_port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # Fold this router's telemetry into the training master's
        # cluster view (keyed router-<id>; same TTL aging and
        # time-series sampling as a worker's piggybacked snapshots).
        self._reporter = None
        if master_addr:
            from elasticdl_tpu.observability.reporter import (
                ComponentMetricsReporter,
            )

            self._reporter = ComponentMetricsReporter(
                master_addr, "router", router_id,
                interval_secs=metrics_report_secs,
                registry=self.core.registry,
            )

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else 0

    def start(self) -> "RouterServer":
        self.core.start()
        handler = type("_BoundRouterHandler", (_RouterHandler,), {
            "server_ref": self,
        })
        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), handler,
            bind_and_activate=False,
        )
        # Same rationale as serving/server.py: the default backlog (5)
        # SYN-drops a client fleet connecting at once.
        self._httpd.request_queue_size = 128
        self._httpd.server_bind()
        self._httpd.server_activate()
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="router-http",
        )
        self._thread.start()
        if self._reporter is not None:
            self._reporter.start()
        logger.info(
            "Router on port %d over %d replica(s), policy=%s",
            self.port, len(self.core.replicas), self.core.policy.name,
        )
        return self

    def wait(self):
        self._thread.join()

    def stop(self):
        if self._reporter is not None:
            self._reporter.stop()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.core.stop()

    def drain(self, grace: float = 25.0) -> bool:
        """Graceful SIGTERM shutdown mirroring serving/server.py:
        stop accepting, let in-flight (hedged) requests settle inside
        ``grace``, then tear down. The router must not be the fleet's
        new hard-kill point."""
        logger.info("draining router (grace %.1fs)", grace)
        self.draining = True
        if self._reporter is not None:
            self._reporter.stop()
        if self._httpd is not None:
            # Stop the accept loop; handler threads for accepted
            # requests keep running and block in core.handle().
            self._httpd.shutdown()
        settled = self.core.wait_idle(timeout=grace)
        if self._httpd is not None:
            self._httpd.server_close()
            self._httpd = None
        self.core.stop()
        logger.info(
            "router drained (%s)",
            "clean" if settled
            else "grace expired with requests in flight",
        )
        return settled


def main(argv=None) -> int:
    """``elasticdl_tpu route`` entry: front a replica fleet.

    Minimal deployment: N ``elasticdl_tpu serve`` replicas (each with
    ``--row_cache_capacity`` for sparse bundles) + one router:

        python -m elasticdl_tpu route \\
            --replicas host1:8500,host2:8500 --port 8600
    """
    import argparse
    import signal

    parser = argparse.ArgumentParser("elasticdl_tpu-route")
    parser.add_argument(
        "--replicas", required=True,
        help="Comma list of serving replica host:port addresses",
    )
    parser.add_argument("--port", type=int, default=8600)
    parser.add_argument(
        "--policy", default="least_loaded",
        choices=("least_loaded", "hash"),
        help="least_loaded balances; hash (consistent hash on "
             "X-User-Id, else a body digest) trades balance for "
             "per-replica row-cache hit rate",
    )
    parser.add_argument(
        "--routing_key_header", default="X-User-Id",
        help="Header carrying the consistent-hash routing key",
    )
    parser.add_argument("--request_timeout", type=float, default=30.0)
    parser.add_argument(
        "--replica_concurrency", type=int, default=32,
        help="Assumed per-replica in-flight capacity; fleet load "
             "factor = inflight / (healthy x this)",
    )
    parser.add_argument(
        "--no_hedge", action="store_true",
        help="Disable speculative second attempts",
    )
    parser.add_argument("--hedge_min_ms", type=float, default=5.0)
    parser.add_argument("--hedge_max_ms", type=float, default=1000.0)
    parser.add_argument(
        "--hedge_shed_frac", type=float, default=0.5,
        help="Load factor past which hedging stops (shed tier 1)",
    )
    parser.add_argument(
        "--low_shed_frac", type=float, default=0.75,
        help="Load factor past which low-priority sheds (tier 2)",
    )
    parser.add_argument(
        "--probe_secs", type=float, default=1.0,
        help="Unhealthy-replica /healthz probe interval",
    )
    parser.add_argument(
        "--drain_grace", type=float, default=25.0,
        help="SIGTERM drain budget for in-flight hedged requests; "
             "keep under the pod's terminationGracePeriodSeconds",
    )
    parser.add_argument(
        "--flight_recorder", type=int, default=0,
        help="Install a span flight recorder of this many entries "
             "(route/attempt spans on the router track, served on "
             "/traces). 0 (default) = off",
    )
    parser.add_argument(
        "--master_addr", default="",
        help="Training master host:port — fold this router's "
             "router_* telemetry into the master's cluster view "
             "(/metrics and the time-series store) via the same "
             "snapshot piggyback workers use; empty (default) = "
             "standalone",
    )
    parser.add_argument(
        "--router_id", type=int, default=0,
        help="This router's id in the master's cluster view "
             "(series label worker=\"router-<id>\")",
    )
    parser.add_argument(
        "--metrics_report_secs", type=float, default=15.0,
        help="Master telemetry report interval (with --master_addr)",
    )
    parser.add_argument(
        "--replica_slo_window_secs", type=float, default=60.0,
        help="Rolling window for the per-replica SLO status on "
             "/v1/replicas",
    )
    parser.add_argument(
        "--replica_slo_p95_ms", type=float, default=500.0,
        help="Per-replica p95 latency objective (ms); <=0 disables "
             "the latency clause",
    )
    parser.add_argument(
        "--replica_slo_error_ratio", type=float, default=0.05,
        help="Per-replica windowed error-ratio objective",
    )
    parser.add_argument(
        "--profile_hz", type=float, default=0.0,
        help="Always-on sampling profiler rate (Hz); flame windows "
             "piggyback to the master with --master_addr and serve "
             "on the master's /profile as router-<id>. 0 = off",
    )
    parser.add_argument(
        "--profile_window_secs", type=float, default=10.0,
        help="Sampling-profiler window length (secs)",
    )
    args = parser.parse_args(argv)

    # Workload attribution: the router's control-plane RPCs (metrics
    # reports) tag as serving traffic for this job.
    import os as _os

    from elasticdl_tpu.observability import principal as _principal

    _principal.set_process_principal(
        job=_os.environ.get("ELASTICDL_JOB_NAME", ""),
        component="router", purpose="serving_read",
    )
    if args.flight_recorder > 0:
        tracing.set_process_role("router")
        tracing.install_recorder(
            tracing.FlightRecorder(args.flight_recorder)
        )
    from elasticdl_tpu.observability import profiler as _profiler

    _profiler.maybe_start_from_args(args, "router", str(args.router_id))

    addrs = [a.strip() for a in args.replicas.split(",") if a.strip()]
    server = RouterServer(
        addrs, port=args.port,
        request_timeout=args.request_timeout,
        routing_key_header=args.routing_key_header,
        policy=args.policy,
        replica_concurrency=args.replica_concurrency,
        hedge=not args.no_hedge,
        hedge_min_ms=args.hedge_min_ms,
        hedge_max_ms=args.hedge_max_ms,
        hedge_shed_frac=args.hedge_shed_frac,
        low_shed_frac=args.low_shed_frac,
        probe_secs=args.probe_secs,
        master_addr=args.master_addr,
        router_id=args.router_id,
        metrics_report_secs=args.metrics_report_secs,
        slo_window_secs=args.replica_slo_window_secs,
        slo_p95_ms=args.replica_slo_p95_ms,
        slo_error_ratio=args.replica_slo_error_ratio,
    ).start()
    logger.info(
        "Routing :%d -> %s (policy=%s, hedge=%s)",
        server.port, ",".join(addrs), args.policy,
        "off" if args.no_hedge else "adaptive-p95",
    )
    stop_evt = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *_: stop_evt.set())
        signal.signal(signal.SIGINT, lambda *_: stop_evt.set())
    except ValueError:
        server.wait()
        return 0
    stop_evt.wait()
    server.drain(grace=args.drain_grace)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
