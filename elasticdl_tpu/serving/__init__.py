from elasticdl_tpu.serving.export import (  # noqa: F401
    export_serving_bundle,
    load_predictor,
)
