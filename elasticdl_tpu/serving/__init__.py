"""Serving plane: bundle export + the online inference server.

``export.py`` writes the training-side artifact (the SavedModel
equivalent); ``model_store.py`` manages versions at serve time (hot
reload, rollback, host-row resolution); ``server.py`` is the batched
HTTP front (``elasticdl_tpu serve``). See docs/serving.md.
"""

from elasticdl_tpu.serving.export import (  # noqa: F401
    HOST_ROWS_FEATURE_PREFIX,
    export_serving_bundle,
    load_predictor,
)
from elasticdl_tpu.serving.model_store import (  # noqa: F401
    HostRowResolver,
    ModelStore,
    ServedModel,
    load_served_model,
)
