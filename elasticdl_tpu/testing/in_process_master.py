"""In-process master: servicer methods called directly, no network.

Counterpart of the reference's ``tests/in_process_master.py:5-33`` — the
worker's master client becomes direct calls into ``MasterServicer``, with
optional test callbacks interposed per RPC.
"""

from typing import Optional, Tuple

import numpy as np

from elasticdl_tpu.common.task import Task


class InProcessMaster:
    def __init__(self, servicer, worker_id: int = 0, callbacks=None):
        """``callbacks``: dict rpc_name -> fn(request_dict) invoked before
        the real handler (used by tests to inject faults/asserts)."""
        self._servicer = servicer
        self._worker_id = worker_id
        self._callbacks = callbacks or {}

    def _call(self, name: str, request: dict) -> dict:
        if name in self._callbacks:
            self._callbacks[name](request)
        return self._servicer.handlers()[name](request)

    def get_task(self, metrics=None) -> Tuple[Optional[Task], bool]:
        request = {"worker_id": self._worker_id}
        if metrics:
            request["metrics"] = metrics
        resp = self._call("get_task", request)
        task = Task.from_dict(resp["task"]) if resp.get("task") else None
        return task, bool(resp.get("finished"))

    def report_task_result(self, task_id: int, err_reason: str = "",
                           metrics=None) -> bool:
        request = {
            "task_id": task_id,
            "err_reason": err_reason,
            "worker_id": self._worker_id,
        }
        if metrics:
            request["metrics"] = metrics
        resp = self._call("report_task_result", request)
        return bool(resp.get("accepted"))

    def report_evaluation_metrics(self, model_outputs, labels) -> bool:
        resp = self._call(
            "report_evaluation_metrics",
            {
                "model_outputs": np.asarray(model_outputs),
                "labels": np.asarray(labels),
            },
        )
        return bool(resp.get("accepted"))

    def report_version(self, model_version: int, metrics=None) -> None:
        request = {
            "model_version": int(model_version),
            "worker_id": self._worker_id,
        }
        if metrics:
            request["metrics"] = metrics
        self._call("report_version", request)

    def close(self):
        pass
