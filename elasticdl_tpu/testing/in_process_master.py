"""In-process master: servicer methods called directly, no network.

Counterpart of the reference's ``tests/in_process_master.py:5-33`` — the
worker's master client becomes direct calls into ``MasterServicer``, with
optional test callbacks interposed per RPC.

Transport parity with ``comm/rpc.RpcStub``: retryable ``RpcError``s
(UNAVAILABLE / DEADLINE_EXCEEDED — here only ever raised by chaos
callbacks) get the same bounded re-send the stub gives real transport
blips, minus the backoff sleeps (determinism); and the master's
``generation`` stamp is tracked/echoed exactly like ``MasterClient``
does, so the chaos master-restart drill exercises the same re-attach
protocol on both transports. ``rebind`` is the restart seam: the chaos
runner swaps in a recovered servicer mid-job, standing in for the
worker's channel reconnecting to the relaunched master pod.
"""

from typing import Optional, Tuple

import numpy as np

from elasticdl_tpu.common.task import Task

_MAX_RETRIES = 2  # mirrors RpcStub.call's default attempt cap


class InProcessMaster:
    def __init__(self, servicer, worker_id: int = 0, callbacks=None):
        """``callbacks``: dict rpc_name -> fn(request_dict) invoked before
        the real handler (used by tests to inject faults/asserts)."""
        self._servicer = servicer
        self._worker_id = worker_id
        self._callbacks = callbacks or {}
        self.last_generation = -1
        # Resize-directive passthrough, same contract as MasterClient.
        self.pending_resize = None
        # Job-scoped lease echo, same contract as MasterClient.
        self.last_job = ""

    def rebind(self, servicer):
        """Point at a recovered master (chaos master-kill restart seam
        — the in-process analogue of the gRPC channel reconnecting to
        the relaunched master on the same address)."""
        self._servicer = servicer

    def _call(self, name: str, request: dict) -> dict:
        from elasticdl_tpu.comm.rpc import RETRYABLE_CODES, RpcError

        attempt = 0
        while True:
            try:
                if name in self._callbacks:
                    self._callbacks[name](request)
                resp = self._servicer.handlers()[name](request)
                break
            except RpcError as exc:
                if exc.code not in RETRYABLE_CODES or (
                    attempt >= _MAX_RETRIES
                ):
                    raise
                attempt += 1
        if isinstance(resp, dict) and resp.get("stale_master"):
            # Transport parity with MasterClient: a fenced zombie's
            # answer is surfaced as a retryable failure, never
            # trusted (the caller's ride-out/rebind takes over).
            raise RpcError(
                "master is fenced (superseded by a hot-standby "
                "takeover)", code="UNAVAILABLE",
            )
        gen = resp.get("generation") if isinstance(resp, dict) else None
        if gen is not None:
            self.last_generation = max(self.last_generation, int(gen))
        return resp

    def get_task(self, metrics=None) -> Tuple[Optional[Task], bool]:
        request = {
            "worker_id": self._worker_id,
            "generation": self.last_generation,
        }
        if metrics:
            request["metrics"] = metrics
        resp = self._call("get_task", request)
        self.pending_resize = resp.get("resize")
        task = Task.from_dict(resp["task"]) if resp.get("task") else None
        if task is not None:
            self.last_job = str(resp.get("job", "") or "")
        return task, bool(resp.get("finished"))

    def report_task_result(self, task_id: int, err_reason: str = "",
                           metrics=None, job=None) -> bool:
        request = {
            "task_id": task_id,
            "err_reason": err_reason,
            "worker_id": self._worker_id,
            "generation": self.last_generation,
            "job": self.last_job if job is None else str(job),
        }
        if metrics:
            request["metrics"] = metrics
        resp = self._call("report_task_result", request)
        return bool(resp.get("accepted"))

    def report_evaluation_metrics(self, model_outputs, labels,
                                  task_id: int = -1) -> bool:
        resp = self._call(
            "report_evaluation_metrics",
            {
                "model_outputs": np.asarray(model_outputs),
                "labels": np.asarray(labels),
                "task_id": int(task_id),
            },
        )
        return bool(resp.get("accepted"))

    def report_version(self, model_version: int, metrics=None) -> None:
        request = {
            "model_version": int(model_version),
            "worker_id": self._worker_id,
        }
        if metrics:
            request["metrics"] = metrics
        self._call("report_version", request)

    def report_resize(self, resize_id: int,
                      status: str = "applied") -> bool:
        resp = self._call(
            "report_resize",
            {
                "worker_id": self._worker_id,
                "resize_id": int(resize_id),
                "status": str(status),
                "generation": self.last_generation,
            },
        )
        self.pending_resize = None
        return bool(resp.get("accepted"))

    def close(self):
        pass
