"""A minimal ``prepare(fileobj, filename)`` for distributed_gen tests
(the user-module contract of tools/record_gen/distributed_gen.py — the
reference's spark job loaded the same hook from a model-zoo module)."""

import csv
import io


def prepare(fileobj, filename):
    text = io.TextIOWrapper(fileobj, newline="")
    reader = csv.reader(text)
    columns = next(reader)
    for row in reader:
        yield {c: v for c, v in zip(columns, row)}
