"""In-process distributed job harness.

Counterpart of the reference's ``tests/test_utils.py:271-426``
(``distributed_train_and_evaluate``): assemble a real TaskDispatcher +
EvaluationService + MasterServicer, then drive one or more Workers against
it — either with direct in-process calls or over a real localhost gRPC
server — and assert the job drains. This is how every elastic/distributed
path stays testable without a cluster (SURVEY.md §4 lesson).
"""

import threading
from typing import Dict, List, Optional

from elasticdl_tpu.comm.rpc import RpcServer
from elasticdl_tpu.core.model_spec import get_model_spec
from elasticdl_tpu.data.factory import create_data_reader
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.servicer import SERVICE_NAME, MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.testing.in_process_master import InProcessMaster
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker


class MiniCluster:
    """A master + N workers in one process."""

    def __init__(
        self,
        model_zoo: str,
        model_def: str,
        training_data: str = "",
        validation_data: str = "",
        prediction_data: str = "",
        num_workers: int = 1,
        minibatch_size: int = 16,
        num_minibatches_per_task: int = 2,
        num_epochs: int = 1,
        eval_steps: int = 0,
        use_rpc: bool = False,
        step_runner_factory=None,
        worker_callbacks: Optional[Dict[str, callable]] = None,
        shuffle: bool = False,
        checkpoint_dir: str = "",
        checkpoint_steps: int = 0,
        checkpoint_dir_for_init: str = "",
        mesh=None,
        fuse_task_steps: bool = False,
        metrics_port: Optional[int] = None,
        metrics_report_secs: float = 0.0,
        metrics_ttl_secs: float = 600.0,
        fault_injector=None,
        checkpoint_async: bool = True,
        checkpoint_delta_chain: int = 0,
        journal_dir: str = "",
        host_prefetch_depth: int = 2,
        version_report_steps: int = 1,
    ):
        # Chaos plane (chaos/interceptors.FaultInjector): over RPC the
        # injector's process-global hooks cover every call already; on
        # the direct-call path its per-RPC callbacks are merged into
        # worker_callbacks below so both transports inject the same
        # plan. checkpoint_async=False forces synchronous checkpoint
        # writes — chaos replay needs corrupt-at-save events ordered
        # deterministically against worker progress.
        self.fault_injector = fault_injector
        if fault_injector is not None and not use_rpc:
            chaos_cbs = fault_injector.in_process_callbacks()
            merged = dict(chaos_cbs)
            for name, cb in (worker_callbacks or {}).items():
                if name in merged:
                    chaos_cb = merged[name]

                    def both(request, _user=cb, _chaos=chaos_cb):
                        _chaos(request)
                        _user(request)

                    merged[name] = both
                else:
                    merged[name] = cb
            worker_callbacks = merged
        self.spec = get_model_spec(model_zoo, model_def)
        if mesh is not None:
            # Same wiring as worker/main.py MESH strategy: mesh-aware
            # model + spec-driven param/batch layout.
            from elasticdl_tpu.parallel.mesh_runner import (
                make_runner_for_spec,
            )

            self.spec.model = self.spec.make_model(mesh)
            if step_runner_factory is None:
                step_runner_factory = lambda: make_runner_for_spec(  # noqa: E731
                    self.spec, mesh
                )
        reader_of = lambda origin: create_data_reader(
            data_origin=origin, custom_reader=self.spec.custom_data_reader
        )
        self.train_reader = (
            reader_of(training_data) if training_data else None
        )
        self.eval_reader = (
            reader_of(validation_data) if validation_data else None
        )
        self.predict_reader = (
            reader_of(prediction_data) if prediction_data else None
        )
        # Kept for restart_master: a recovered dispatcher must be born
        # from the IDENTICAL config (shards, sizing, seed) before the
        # journal replays events into it.
        self._dispatcher_config = dict(
            training_shards=(
                self.train_reader.create_shards()
                if self.train_reader else {}
            ),
            evaluation_shards=(
                self.eval_reader.create_shards()
                if self.eval_reader else {}
            ),
            prediction_shards=(
                self.predict_reader.create_shards()
                if self.predict_reader else {}
            ),
            records_per_task=minibatch_size * num_minibatches_per_task,
            num_epochs=num_epochs,
            shuffle=shuffle,
        )
        self._eval_config = dict(
            eval_steps=eval_steps,
            eval_only=bool(validation_data and not training_data),
        )
        self.dispatcher = TaskDispatcher(**self._dispatcher_config)
        # Master write-ahead journal (master/journal.py): dispatch /
        # report events write through; restart_master() below replays
        # them into a recovered master (the chaos master-kill seam).
        self.journal_dir = journal_dir
        self._journal = None
        if journal_dir:
            from elasticdl_tpu.master.journal import MasterJournal

            self._journal = MasterJournal(journal_dir)
            self._journal.open_generation()
            self.dispatcher.attach_journal(self._journal)
        metrics_fns = (
            self.spec.eval_metrics_fn() if self.spec.eval_metrics_fn else {}
        )
        self.eval_service = EvaluationService(
            self.dispatcher, metrics_fns, **self._eval_config
        )
        if self._journal is not None:
            # Eval rounds are event-sourced onto the same journal
            # (open/fold/task_done/close records) so restart_master
            # recovers an open round intact.
            self.eval_service.attach_journal(self._journal)
        # Telemetry: in-process tests share ONE process registry across
        # master and workers (production is one worker per process);
        # per-worker keying comes from each client's worker_id at report
        # time. metrics_report_secs=0 → workers attach a snapshot to
        # every report so short jobs still populate the cluster view.
        from elasticdl_tpu.observability import MetricsPlane

        self.metrics_plane = MetricsPlane(ttl_secs=metrics_ttl_secs)
        self.servicer = MasterServicer(
            self.dispatcher, self.eval_service,
            metrics_plane=self.metrics_plane,
            journal=self._journal,
            generation=(
                self._journal.generation if self._journal else 0
            ),
        )
        self.metrics_http = (
            self.metrics_plane.serve(port=metrics_port)
            if metrics_port is not None else None
        )

        self._server = None
        self._use_rpc = use_rpc
        # Every InProcessMaster handed out (constructor workers AND
        # chaos replacement workers) registers here so restart_master
        # can rebind them all to a recovered servicer — a client bound
        # to the discarded one would keep mutating dead state.
        self._inprocess_clients: List[InProcessMaster] = []
        if use_rpc:
            self._server = RpcServer(
                "localhost:0", {SERVICE_NAME: self.servicer.handlers()}
            ).start()

        if step_runner_factory is None and self.spec.make_host_runner:
            # Host-tier default: ONE runner shared by every worker so all
            # threads train the same row stores (the PS-sharing shape);
            # a per-worker factory would silently fork the tables.
            shared_runner = self.spec.make_host_runner()
            step_runner_factory = lambda: shared_runner  # noqa: E731
        elif step_runner_factory is None and self.spec.make_sparse_runner:
            # Device-tier sparse models: tables ride the TrainState, so
            # a per-worker runner is only step-builder config — but the
            # single-device in-process cluster still shares one (the
            # state itself is worker-owned).
            sparse_runner = self.spec.make_sparse_runner()
            step_runner_factory = lambda: sparse_runner  # noqa: E731
        task_reader = (
            self.train_reader or self.eval_reader or self.predict_reader
        )
        self.workers: List[Worker] = []
        hook = None
        for wid in range(num_workers):
            if use_rpc:
                client = MasterClient(
                    f"localhost:{self._server.port}", worker_id=wid,
                    connect_timeout=10, retries=1,
                )
            else:
                client = self.make_inprocess_client(
                    wid, callbacks=worker_callbacks
                )
            runner = (
                step_runner_factory() if step_runner_factory else None
            )
            if wid == 0 and checkpoint_dir:
                from elasticdl_tpu.checkpoint import CheckpointHook

                # Built once worker 0's runner exists so host-tier
                # tables (HostStepRunner) checkpoint alongside the state.
                hook = CheckpointHook(
                    checkpoint_dir=checkpoint_dir,
                    checkpoint_steps=checkpoint_steps,
                    host_tables=getattr(runner, "host_tables", None),
                    async_save=checkpoint_async,
                    delta_chain_max=checkpoint_delta_chain,
                )
            self.workers.append(
                Worker(
                    worker_id=wid,
                    master_client=client,
                    model_spec=self.spec,
                    data_reader=task_reader,
                    minibatch_size=minibatch_size,
                    step_runner=runner,
                    prediction_outputs_processor=(
                        self.spec.prediction_outputs_processor
                    ),
                    callbacks=(
                        self.spec.callbacks_fn()
                        if self.spec.callbacks_fn else []
                    ),
                    # One writer: worker 0 (state is shared/replicated).
                    checkpoint_hook=hook if wid == 0 else None,
                    checkpoint_dir_for_init=checkpoint_dir_for_init,
                    fuse_task_steps=fuse_task_steps,
                    metrics_report_secs=metrics_report_secs,
                    host_prefetch_depth=host_prefetch_depth,
                    # SSP mapping (--get_model_steps): the master
                    # observes every N-th version only.
                    version_report_steps=version_report_steps,
                )
            )

    def make_inprocess_client(self, worker_id: int,
                              callbacks=None) -> InProcessMaster:
        """An InProcessMaster bound to the CURRENT servicer and
        registered for restart_master rebinding. Replacement workers
        (chaos relaunch) must use this instead of constructing one
        directly, or a later master restart leaves them calling the
        discarded servicer."""
        client = InProcessMaster(
            self.servicer, worker_id=worker_id, callbacks=callbacks
        )
        self._inprocess_clients.append(client)
        return client

    def restart_master(self):
        """Simulated master crash + journal-replay recovery (the chaos
        ``master_kill`` seam; requires ``journal_dir``).

        The old dispatcher/servicer are DISCARDED exactly as a dead
        process would lose them — recovery may only use what the
        journal holds. A fresh dispatcher is built from the identical
        config, ``recover_master_state`` replays snapshot + tail into
        it (the same code path ``master/main.py`` runs on a real
        restart), and the transport re-points: the gRPC server rebinds
        the same port (the workers' channels reconnect, as they would
        to a relaunched master pod behind a stable Service), while
        in-process clients are rebound explicitly. Returns the replay
        stats dict."""
        from elasticdl_tpu.master.journal import recover_master_state

        if self._journal is None:
            raise RuntimeError(
                "restart_master needs MiniCluster(journal_dir=...)"
            )
        port = self._server.port if self._server is not None else None
        if self._server is not None:
            self._server.stop(0)
            self._server = None
        self._journal.close()
        dispatcher = TaskDispatcher(**self._dispatcher_config)
        metrics_fns = (
            self.spec.eval_metrics_fn()
            if self.spec.eval_metrics_fn else {}
        )
        eval_service = EvaluationService(
            dispatcher, metrics_fns, **self._eval_config
        )
        servicer = MasterServicer(
            dispatcher, eval_service,
            metrics_plane=self.metrics_plane,
            journal=self._journal,
        )
        stats = recover_master_state(
            self._journal, dispatcher, servicer=servicer,
            eval_service=eval_service,
        )
        self.dispatcher = dispatcher
        self.eval_service = eval_service
        self.servicer = servicer
        if self._use_rpc:
            self._server = RpcServer(
                f"localhost:{port}",
                {SERVICE_NAME: self.servicer.handlers()},
            ).start()
        else:
            for client in self._inprocess_clients:
                client.rebind(self.servicer)
        return stats

    def begin_resize(self, mesh, direction: str = "resize") -> int:
        """Open a live-resize barrier offering ``mesh`` to every
        worker (master/servicer.py; applied checkpointlessly via
        parallel/reshard.py at each worker's next task boundary)."""
        from elasticdl_tpu.parallel import reshard

        return self.servicer.begin_resize(
            reshard.mesh_spec(mesh), direction=direction
        )

    def run(self) -> List[dict]:
        """Run all workers (threads if >1) to completion."""
        results = [None] * len(self.workers)
        if len(self.workers) == 1:
            results[0] = self.workers[0].run()
        else:
            threads = []
            for i, worker in enumerate(self.workers):
                def _run(i=i, worker=worker):
                    results[i] = worker.run()
                t = threading.Thread(target=_run, daemon=True)
                threads.append(t)
                t.start()
            for t in threads:
                t.join(timeout=300)
        if self._server is not None:
            self._server.stop(0)
        return results

    def stop(self):
        """Release the metrics endpoint (its daemon thread and bound
        port outlive run() on purpose, so tests can scrape the final
        cluster state first)."""
        self.metrics_plane.stop()

    @property
    def finished(self) -> bool:
        return self.dispatcher.finished()
