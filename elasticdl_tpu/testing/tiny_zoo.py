"""Shrink zoo models to test/bench shapes, reversibly.

The recsys zoo's production table is 1M x 256 (1 GB f32) — CPU smoke
tests, the multichip dryrun, and the resize elasticity bench all need
the same model at toy vocab. The override has three coupled parts
(module globals read at ``custom_model()`` call time, the TABLE_SPECS
tuple, and a ``model_spec.load_module`` route so ``get_model_spec``'s
by-path re-import resolves to the patched module instance); keeping
them in one context manager stops the recipes drifting apart across
call sites and guarantees restoration — a bench that leaves the zoo
shrunk would silently poison any later in-process job.
"""

from contextlib import contextmanager


@contextmanager
def tiny_recsys_zoo(vocab: int = 64, dim: int = 16):
    """Patch the recsys zoo to ``vocab`` x ``dim`` and route
    ``model_spec.load_module`` at it; yields the patched module and
    restores everything on exit."""
    import elasticdl_tpu.core.model_spec as ms
    from elasticdl_tpu.embedding.device_sparse import TableSpec
    from model_zoo.recsys import recsys_sparse as zoo

    saved = (zoo.VOCAB, zoo.DIM, zoo.TABLE_SPECS, ms.load_module)
    real_load = ms.load_module
    zoo.VOCAB, zoo.DIM = int(vocab), int(dim)
    zoo.TABLE_SPECS = (TableSpec(
        name=zoo.TABLE_NAME, vocab=zoo.VOCAB, dim=zoo.DIM,
        combiner="sum", feature_key=zoo.FEATURE_KEY,
    ),)
    ms.load_module = lambda path: (
        zoo if path.endswith("recsys_sparse.py") else real_load(path)
    )
    try:
        yield zoo
    finally:
        zoo.VOCAB, zoo.DIM, zoo.TABLE_SPECS, ms.load_module = saved
