"""Synthetic dataset fixtures (reference tests/test_utils.py:92-243).

``create_record_file`` writes RecordFiles for the dataset shapes the test
suite and bench harness need: mnist-like images, cifar-like images, frappe
sparse id rows (deepfm), census-style mixed rows, iris CSV.
"""

import csv
import os

import numpy as np

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.data.record_file import RecordFileWriter


def create_mnist_record_file(path, num_records, seed=0, image_hw=28,
                             num_classes=10, learnable=True):
    """MNIST-shaped records. If ``learnable``, the label's block of pixels is
    brightened (orthogonal class signals) so tests can assert the model
    actually learns."""
    rng = np.random.RandomState(seed)
    with RecordFileWriter(path) as writer:
        for _ in range(num_records):
            label = int(rng.randint(num_classes))
            image = rng.rand(image_hw * image_hw) * 32.0
            if learnable:
                block = image.shape[0] // num_classes
                image[label * block:(label + 1) * block] += 192.0
            image = image.reshape(image_hw, image_hw)
            writer.write(
                tensor_utils.dumps(
                    {"image": image.astype(np.float32), "label": label}
                )
            )
    return path


def create_frappe_record_file(path, num_records, seed=0, input_length=10,
                              max_id=5383):
    """Frappe-style rows for DeepFM: fixed-length sparse feature ids + click
    label (reference create_recordio_file 'frappe' shape)."""
    rng = np.random.RandomState(seed)
    with RecordFileWriter(path) as writer:
        for _ in range(num_records):
            ids = rng.randint(0, max_id, size=(input_length,))
            label = int(ids.sum() % 2)
            writer.write(
                tensor_utils.dumps(
                    {"feature_ids": ids.astype(np.int64), "label": label}
                )
            )
    return path


def create_lm_record_file(path, num_records, seed=0, seq_len=32,
                          vocab=256):
    """Byte-token LM sequences for the transformer zoo model. Each record
    is a +1-chain (tokens[i+1] = tokens[i]+1 mod vocab) so next-token
    prediction is fully learnable."""
    rng = np.random.RandomState(seed)
    with RecordFileWriter(path) as writer:
        for _ in range(num_records):
            start = int(rng.randint(vocab))
            tokens = (start + np.arange(seq_len + 1)) % vocab
            writer.write(
                tensor_utils.dumps({"tokens": tokens.astype(np.int64)})
            )
    return path


def create_census_record_file(path, num_records, seed=0):
    """Census-style mixed dense+categorical rows (wide&deep workload)."""
    rng = np.random.RandomState(seed)
    education = ["Bachelors", "HS-grad", "Masters", "Doctorate", "Some-college"]
    workclass = ["Private", "Self-emp", "Federal-gov", "Local-gov"]
    with RecordFileWriter(path) as writer:
        for _ in range(num_records):
            age = float(rng.randint(17, 90))
            hours = float(rng.randint(1, 99))
            edu = education[rng.randint(len(education))]
            work = workclass[rng.randint(len(workclass))]
            label = int((age > 40) ^ (edu in ("Masters", "Doctorate")))
            writer.write(
                tensor_utils.dumps(
                    {
                        "age": age,
                        "hours_per_week": hours,
                        "education": edu,
                        "workclass": work,
                        "label": label,
                    }
                )
            )
    return path


def create_heart_record_file(path, num_records, seed=0):
    """Heart-disease-style mixed rows (reference heart_functional_api
    feature schema: numerics + age + string thal + binary target)."""
    rng = np.random.RandomState(seed)
    thal_values = ["fixed", "normal", "reversible"]
    with RecordFileWriter(path) as writer:
        for _ in range(num_records):
            thal = thal_values[rng.randint(len(thal_values))]
            age = float(rng.randint(29, 77))
            oldpeak = float(rng.rand() * 4)
            label = int((age > 55) ^ (thal == "normal"))
            writer.write(
                tensor_utils.dumps(
                    {
                        "age": age,
                        "trestbps": float(rng.randint(94, 200)),
                        "chol": float(rng.randint(126, 400)),
                        "thalach": float(rng.randint(71, 202)),
                        "oldpeak": oldpeak,
                        "slope": float(rng.randint(1, 4)),
                        "ca": float(rng.randint(0, 4)),
                        "thal": thal,
                        "target": label,
                    }
                )
            )
    return path


def create_iris_csv(path, num_records, seed=0):
    rng = np.random.RandomState(seed)
    with open(path, "w", newline="") as f:
        writer = csv.writer(f)
        writer.writerow(
            ["sepal_length", "sepal_width", "petal_length", "petal_width",
             "class"]
        )
        for _ in range(num_records):
            label = int(rng.randint(3))
            row = (rng.rand(4) + label).round(3)
            writer.writerow(list(row) + [label])
    return path


def create_cifar_record_file(path, num_records, seed=0):
    rng = np.random.RandomState(seed)
    with RecordFileWriter(path) as writer:
        for _ in range(num_records):
            label = int(rng.randint(10))
            image = (rng.rand(32, 32, 3) * 127 + label * 12).astype(np.float32)
            writer.write(
                tensor_utils.dumps({"image": image, "label": label})
            )
    return path


def make_local_args(model_zoo, model_def, training_data, tmpdir,
                    validation_data="", minibatch_size=16, num_epochs=1,
                    extra=None):
    """Parse a Local-strategy arg namespace for tests."""
    from elasticdl_tpu.common.args import build_parser

    argv = [
        "--model_zoo", model_zoo,
        "--model_def", model_def,
        "--training_data", training_data,
        "--minibatch_size", str(minibatch_size),
        "--num_epochs", str(num_epochs),
        "--job_name", "test-job",
        "--checkpoint_dir", os.path.join(str(tmpdir), "ckpt"),
    ]
    if validation_data:
        argv += ["--validation_data", validation_data]
    if extra:
        argv += list(extra)
    return build_parser("train").parse_args(argv)


def model_zoo_dir():
    """Path of the repo's model_zoo directory."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "model_zoo")


def create_adult_csv(path, num_records, seed=0):
    """Raw UCI-Adult-format CSV (15 comma-separated columns, no header,
    '>50K'/'<=50K' labels) with a learnable age+education signal — the
    input fixture for tools/record_gen/census_gen.py. One generator so
    the converter tests and the raw-data e2e script can't drift from
    census_gen's expected 15-column schema."""
    import csv

    rng = np.random.RandomState(seed)
    education = ["Bachelors", "HS-grad", "Masters", "Doctorate"]
    workclass = ["Private", "Self-emp", "Federal-gov", "Local-gov"]
    with open(path, "w", newline="") as f:
        out = csv.writer(f)
        for _ in range(num_records):
            e = int(rng.randint(len(education)))
            age = 20 + rng.rand() * 50
            label = ">50K" if age + 10 * e > 55 else "<=50K"
            out.writerow([
                f"{age:.1f}", workclass[int(rng.randint(len(workclass)))],
                "77516", education[e], "13", "Never-married",
                "Tech-support", "Own-child", "White", "Female", "0", "0",
                f"{10 + rng.rand() * 60:.1f}", "United-States", label,
            ])
    return path
