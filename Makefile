# Developer entry points (reference elasticdl/Makefile builds protos +
# C++ kernels; here the native pieces build lazily on import, so make
# mostly drives tests/bench).

PY ?= python

.PHONY: test test-fast native bench dryrun clean lint

test:
	$(PY) -m pytest tests/ -q

test-fast:
	$(PY) -m pytest tests/ -q \
	  --ignore=tests/test_example_zoo.py \
	  --ignore=tests/test_multihost_job.py \
	  --ignore=tests/test_multihost_2proc.py

# Force-rebuild the native components (row store + record reader).
native:
	rm -f elasticdl_tpu/native/_librowstore.so \
	      elasticdl_tpu/native/_record_ext.so
	$(PY) -c "from elasticdl_tpu.native import native_available, \
	get_record_ext; assert native_available(); assert get_record_ext()"

bench:
	$(PY) bench.py

# Multi-chip sharding dry run on a virtual 8-device CPU mesh.
dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PY) __graft_entry__.py 8

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null; \
	rm -f elasticdl_tpu/native/_librowstore.so \
	      elasticdl_tpu/native/_record_ext.so
