# Developer entry points (reference elasticdl/Makefile builds protos +
# C++ kernels; here the native pieces build lazily on import, so make
# mostly drives tests/bench).

PY ?= python

.PHONY: test test-all test-tpu test-k8s native bench serve-bench dryrun \
	clean lint metrics chaos-smoke chaos-soak chaos-master-smoke \
	trace-smoke serve-fleet-smoke sparse-smoke sparse-bench \
	autoscale-smoke autoscale-bench slo-smoke ckpt-bench ckpt-smoke \
	tiered-smoke tiered-bench reshard-smoke reshard-bench \
	profile-smoke failover-smoke failover-bench quake-smoke \
	usage-smoke sched-smoke sched-bench stream-smoke probe-smoke \
	brownout-smoke fsck

# Scrape-and-pretty-print a master's /metrics (docs/observability.md).
METRICS_ADDR ?= localhost:8080
metrics:
	$(PY) tools/dump_metrics.py $(METRICS_ADDR)

# Fast lane (<4 min): everything not marked slow. conftest.py
# auto-marks the heavy zoo/multi-process/bench suites. The tracing
# smoke (trace-smoke below) runs inside this lane too, as
# tests/test_tracing.py::test_trace_smoke_end_to_end.
test:
	$(PY) -m pytest tests/ -q -m "not slow"

# Distributed-tracing smoke: 2-worker in-process job with the flight
# recorder on → Perfetto trace_event JSON, schema-checked (one task
# tree must cross master → worker → row-service). docs/observability.md.
TRACE_OUT ?= TRACE.json
trace-smoke:
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu trace \
		--out $(TRACE_OUT) --records 32 --num_workers 2
	$(PY) tools/check_trace.py $(TRACE_OUT)

# Full suite (what the driver/judge runs).
test-all:
	$(PY) -m pytest tests/ -q

# Kernel-correctness lane on the real chip (compiled, non-interpret);
# run before benching. Uses the default (axon/TPU) platform, NOT the
# conftest CPU mesh.
test-tpu:
	ELASTICDL_TPU_TESTS=1 $(PY) -m pytest tests/ -q -m tpu

# Live-cluster lane (reference K8S_TESTS minikube gating): skipped
# unless ELASTICDL_K8S_TESTS=1 and a cluster is reachable.
test-k8s:
	ELASTICDL_K8S_TESTS=1 $(PY) -m pytest tests/test_k8s_live.py -q -m k8s

# Force-rebuild the native components (row store + record reader).
native:
	rm -f elasticdl_tpu/native/_librowstore.so \
	      elasticdl_tpu/native/_record_ext.so
	$(PY) -c "from elasticdl_tpu.native import native_available, \
	get_record_ext; assert native_available(); assert get_record_ext()"

# Kernel correctness on the chip gates the bench (VERDICT r1 #3).
bench: test-tpu
	$(PY) bench.py

# Serving-plane latency/throughput vs batch deadline (docs/serving.md);
# writes BENCH_SERVING.json.
serve-bench:
	$(PY) bench_serving.py

# Serving-fleet chaos drill (docs/serving.md "Fleet"): in-process
# router + 2 replicas (hot-row caches) + live row service under
# seeded mixed-priority load; one replica is hard-killed mid-run.
# Exits nonzero unless availability holds across the kill, the
# caches served rows, the router detected the dead replica, and the
# drain settled clean.
serve-fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.serving_drill \
		--seed $(CHAOS_SEED) --report SERVE_FLEET_DRILL.json

# Sparse-pipeline overlap pin (docs/sparse_path.md): run a pipelined
# deepfm-host job over a real localhost row service with injected RPC
# latency, then assert >=1 row_pull span overlaps a device_step span
# wall-clock — a refactor that silently re-serializes the sparse path
# fails here. Fast-lane equivalent:
# tests/test_sparse_path.py::test_pipelined_job_overlaps_row_pulls.
SPARSE_TRACE ?= TRACE_sparse.json
sparse-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/bench_sparse_path.py --smoke \
		--trace_out $(SPARSE_TRACE)
	$(PY) tools/check_overlap.py $(SPARSE_TRACE)

# Full serialized-vs-pipelined measurement (writes BENCH_SPARSE_PATH.json;
# gate: pipelined per-batch p50 <= 0.7x serialized).
sparse-bench:
	JAX_PLATFORMS=cpu $(PY) tools/bench_sparse_path.py

# Autoscale chaos drill (docs/elasticity.md): a job shrinks dp4->dp2 by
# checkpointless live reshard, grows back, and loses its worker to a
# hard kill while the grow barrier is pending. Exits nonzero unless
# loss-trajectory equivalence vs a checkpoint-restart control, exactly-
# once task accounting, and barrier liveness all hold. Fast-lane
# equivalent: tests/test_autoscale.py::test_autoscale_drill_passes.
autoscale-smoke:
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.autoscale_drill \
		--report AUTOSCALE_DRILL.json

# Live-reshard vs checkpoint-restart resize downtime (writes
# BENCH_AUTOSCALE.json; gate: live reshard >= 5x lower downtime per
# direction on the in-process virtual CPU mesh).
autoscale-bench:
	JAX_PLATFORMS=cpu $(PY) bench_elasticity.py --scenario autoscale

# SLO-engine drill (docs/observability.md "SLOs & alerting"): a
# MiniCluster job with every row pull stalled 120ms must trip the
# latency burn-rate rule and leave an incident bundle that
# check_incident.py accepts (Perfetto-loadable trace, non-empty series
# window, journal tail); the fault-free twin run must fire NOTHING.
# Fast-lane equivalent: tests/test_slo.py::test_slo_drill_passes.
slo-smoke:
	workdir=$$(mktemp -d /tmp/edl_slo.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.slo_drill \
		--workdir $$workdir --report SLO_DRILL.json \
	&& $(PY) tools/check_incident.py $$workdir/incidents; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Continuous-profiling drill (docs/observability.md "Continuous
# profiling & exemplars"): a REAL row-service subprocess with an
# injected named hot function runs --profile_hz 67; its flame windows,
# spans, and exemplar-stamped push histogram piggyback back over real
# gRPC. Exits nonzero unless the hot function dominates the captured
# flame table, the SLO rule fires, the incident bundle passes
# check_incident.py --require-profile --require-exemplars (profile
# snapshot valid per check_profile.py, >=1 exemplar trace id resolving
# in trace.json), and the profiler-overhead pin (<=1% of a busy loop
# at the default hz) holds. Fast-lane equivalent:
# tests/test_profile_plane.py::test_profile_drill_fast_lane.
profile-smoke:
	workdir=$$(mktemp -d /tmp/edl_profile.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.profile_drill \
		--workdir $$workdir --report PROFILE_DRILL.json; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Checkpoint-plane bench (docs/fault_tolerance.md "Checkpoint
# format"): async capture/write + dirty-row deltas vs the inline
# full-snapshot path over identical push schedules; writes
# BENCH_CHECKPOINT.json. Gates: p99 push stall >=5x lower async,
# delta bytes <=0.2x a full base on the hot-working-set workload.
ckpt-bench:
	JAX_PLATFORMS=cpu $(PY) tools/bench_checkpoint.py

# Fast checkpoint smoke: tiny bench config (report to the scratch dir,
# the committed BENCH_CHECKPOINT.json stays put), then fsck both
# checkpoint dirs it produced — framing, chain linkage,
# slowest-shard-wins validity, reclaimable garbage. Fast-lane
# equivalent: tests/test_checkpoint.py::TestDeltaChain +
# ::TestCheckpointFsck.
ckpt-smoke:
	workdir=$$(mktemp -d /tmp/edl_ckpt.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) tools/bench_checkpoint.py --smoke \
		--workdir $$workdir --out $$workdir/BENCH_CHECKPOINT.json \
	&& $(PY) tools/check_checkpoint.py $$workdir/inline/ckpt \
	&& $(PY) tools/check_checkpoint.py $$workdir/async_delta/ckpt; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Tiered-storage chaos drill (docs/sparse_path.md "Tiered storage"):
# kills mid-eviction and mid-compaction against a tiered row service,
# relaunch + replay must land byte-equal to a fault-free twin (rows,
# slots, step counters — across both tiers), and a cold store crashed
# mid-compaction must reopen to pre-crash bytes. Every cold dir the
# drill leaves (dead incarnations included) is then fsck'd by
# check_store.py. Fast-lane equivalent:
# tests/test_tiered_store.py::test_tiered_drill_passes.
tiered-smoke:
	workdir=$$(mktemp -d /tmp/edl_tiered.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.tiered_drill \
		--seed $(CHAOS_SEED) --workdir $$workdir \
		--report TIERED_DRILL.json \
	&& $(PY) tools/check_store.py $$workdir/cold; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Tiered-storage bench (docs/sparse_path.md): train + serve a table
# ~10x the hot-tier row budget on a hot-working-set workload, tiered
# vs all-in-memory; writes BENCH_TIERED.json. Gates: tiered p99 step
# <=1.5x the in-memory baseline, and a mid-run checkpoint restores
# byte-equal rows across both tiers.
tiered-bench:
	JAX_PLATFORMS=cpu $(PY) tools/bench_tiered_store.py

# Live-reshard chaos drill (docs/sparse_path.md "Live resharding &
# hot-row replication"): a 2-shard fleet under a seeded push schedule
# splits live twice; the source shard is killed mid-migration and the
# authority mid-cutover. Relaunch + resume must converge to ONE
# consistent shard map, byte-equal rows+slots vs a fault-free twin,
# no row lost or double-homed (replica copies included), and the
# authority state file passes check_reshard.py at every kill point.
# Fast-lane equivalent: tests/test_reshard.py::test_reshard_drill_passes.
reshard-smoke:
	workdir=$$(mktemp -d /tmp/edl_reshard.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.reshard_drill \
		--seed $(CHAOS_SEED) --workdir $$workdir \
		--report RESHARD_DRILL.json; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Live-reshard + hot-row-replica bench (writes BENCH_ROW_RESHARD.json).
# Gates: live 2->3 split downtime >=5x lower than checkpoint-restart
# repartition under continuous pull/push load, zipf(1.1) replicated
# read throughput >=1.5x single-home, p99 replica staleness under the
# default freshness SLO.
reshard-bench:
	JAX_PLATFORMS=cpu $(PY) tools/bench_row_reshard.py

# Workload-attribution drill (docs/observability.md "Workload
# attribution"): the same seeded push schedule through a live 2->3
# split runs twice — attribution off (principal kill-switch) and on.
# Gates: migration/replica-refresh bytes metered ONLY under their own
# purposes, >=95% of handler time attributed to a non-unknown
# purpose, attributed p99 push <=1.05x the attribution-off baseline.
# The committed USAGE_DRILL.json is validated by check_usage.py
# (also under the fsck umbrella as the "usage" kind).
usage-smoke:
	workdir=$$(mktemp -d /tmp/edl_usage.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.usage_drill \
		--seed $(CHAOS_SEED) --workdir $$workdir \
		--report USAGE_DRILL.json \
	&& $(PY) tools/check_usage.py USAGE_DRILL.json; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Gang-scheduler drill (docs/scheduler.md): two jobs on one fleet, a
# live priority preemption (checkpoint-now + lease handback), resume,
# and BOTH jobs' final dense + row state byte-equal to solo control
# runs — with the journal and every shard WAL fsck'd in-drill. The
# report is then schema-checked by check_sched.py (and fsck's sched
# kind on every push via the committed SCHED_DRILL.json).
sched-smoke:
	workdir=$$(mktemp -d /tmp/edl_sched.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.sched_drill \
		--seed $(CHAOS_SEED) --workdir $$workdir \
		--report SCHED_DRILL.json \
	&& $(PY) tools/check_sched.py SCHED_DRILL.json; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Streaming-ingestion drill (docs/online_learning.md): a live
# file-tail stream trains through real workers into the real 2-shard
# row fleet while a worker SIGKILL + row-shard SIGKILL + master crash
# land in ONE window. Gates: resume from the journaled watermark
# (never re-ack), read-your-writes for every committed offset across
# both kills, final rows byte-equal to a kill-free twin, and the
# streaming tenant surviving a gang-scheduler preemption with a
# monotone watermark. Report schema-checked by check_stream.py (and
# fsck's stream kind on every push via the committed
# STREAM_DRILL.json).
stream-smoke:
	workdir=$$(mktemp -d /tmp/edl_stream.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.stream_drill run \
		--seed $(CHAOS_SEED) --workdir $$workdir \
		--report STREAM_DRILL.json \
	&& $(PY) tools/check_stream.py STREAM_DRILL.json; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Synthetic-probe drill (docs/observability.md "Synthetic probing"):
# kill a row shard, SIGSTOP the serving replica, and crash the master
# in separate windows — each must red the MATCHING black-box probe
# within the tick bound while a kill-free twin stays 100% green.
probe-smoke:
	workdir=$$(mktemp -d /tmp/edl_probe.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.probe_drill \
		--seed $(CHAOS_SEED) --workdir $$workdir \
		--report PROBE_DRILL.json \
	&& $(PY) tools/check_probe.py PROBE_DRILL.json; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Brownout drill (docs/fault_tolerance.md "Graceful degradation"):
# an fsync_stall fault plan slows every WAL group commit on a real
# 2-shard row fleet under a mixed principal-tagged workload. With the
# overload controls on, serving p99 must hold near baseline while the
# admission gate sheds background purposes and retry budgets cap
# amplification; a twin run with every control off must show the
# inversion (no sheds, unbudgeted retry storms, serving starved).
brownout-smoke:
	workdir=$$(mktemp -d /tmp/edl_brownout.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.brownout_drill \
		run --seed $(CHAOS_SEED) --workdir $$workdir \
		--report BROWNOUT_DRILL.json \
	&& $(PY) tools/check_overload.py BROWNOUT_DRILL.json; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Gang-vs-static utilization + pod-closing autoscale round-trip
# (docs/scheduler.md "Benchmarks"): one shared arbiter must beat two
# static fleet halves on the same job mix, and the pod scaler must
# really spawn then drain a row-service pod around a live
# split/merge. Gates evaluated in-bench; report BENCH_SCHED.json.
sched-bench:
	workdir=$$(mktemp -d /tmp/edl_schedb.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) tools/bench_sched.py \
		--workdir $$workdir --out BENCH_SCHED.json; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Deterministic chaos plan (kill + stall-row-shard + corrupt-checkpoint)
# against the in-process cluster; exits nonzero if any recovery
# invariant fails — the schedule includes a worker kill landing
# between a row-service delta save and its base compaction, and the
# end-of-run shard relaunch restores across the base+delta chain.
# The row checkpoint dir the drill leaves behind is then fsck'd.
# Runs the tiered-storage drill first (tiered-smoke) so the chaos
# lane also fsck's cold-tier segment stores via check_store.py, the
# master-kill drill (chaos-master-smoke) so the journal fsck —
# including the eval-round / relaunch / fence record kinds — runs in
# this lane too, and the zero-RPO quake drill (quake-smoke) so
# check_pushlog.py audits real SIGKILLed incarnations' write-ahead
# push logs, and the workload-attribution drill (usage-smoke) so
# principal purity survives a live split under the chaos lane too.
# docs/chaos.md.
CHAOS_SEED ?= 7
chaos-smoke: tiered-smoke chaos-master-smoke quake-smoke usage-smoke \
		sched-smoke stream-smoke probe-smoke brownout-smoke
	workdir=$$(mktemp -d /tmp/edl_chaos.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu chaos run \
		--seed $(CHAOS_SEED) --workdir $$workdir \
		--report CHAOS_r01.json \
	&& $(PY) tools/check_checkpoint.py $$workdir/r0/faulted/rows/s0; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Master-crash drill (docs/fault_tolerance.md): two master kills
# recovered by write-ahead journal replay, workers riding the outage
# out and re-attaching under the bumped generation; all five
# invariants (incl. master-restart equivalence) must pass, then fsck
# audits the journal the run left behind. Fast-lane equivalent:
# tests/test_chaos.py::test_master_kill_drill_all_invariants_pass.
chaos-master-smoke:
	workdir=$$(mktemp -d /tmp/edl_chaos_master.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu chaos run \
		--seed $(CHAOS_SEED) --master_kill \
		--workdir $$workdir \
		--report CHAOS_master_r01.json \
	&& $(PY) tools/check_journal.py $$workdir/r0/faulted/journal; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Hot-standby failover drill (docs/fault_tolerance.md "Hot standby &
# failover"): REAL master processes over a shared journal — a warm
# standby SIGKILLed into service mid-lease, mid-eval-round, and
# mid-resize-barrier, plus a partitioned zombie primary that must be
# provably fenced (stale_master on RPC, appends rejected under the
# journal flock). Gates: takeover downtime >=5x lower than a
# restart-and-replay baseline on the same kill schedule (median over
# the kills), sub-second, zero task loss/duplication, the open eval
# round surviving, final dispatcher state field-equal to a fault-free
# twin, and the journal fsck'ing clean. Fast-lane equivalent:
# tests/test_failover.py (in-process); the standby-mode process drill
# runs as tests/test_failover.py::test_failover_drill_standby_mode
# (slow lane).
failover-smoke:
	workdir=$$(mktemp -d /tmp/edl_failover.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.failover_drill run \
		--workdir $$workdir --report $$workdir/FAILOVER_DRILL.json; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Same drill, committing the report with the downtime gates
# (FAILOVER_DRILL.json at the repo root).
failover-bench:
	workdir=$$(mktemp -d /tmp/edl_failover.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.failover_drill run \
		--workdir $$workdir --report FAILOVER_DRILL.json; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Zero-RPO quake drill (docs/fault_tolerance.md "Zero-RPO row
# plane"): REAL row-service processes with the write-ahead push log —
# a shard is SIGKILLed mid-push-storm and the relaunched fleet must
# converge byte-equal (rows + slots + step counters) to a fault-free
# twin with NO external replay (acked-push RPO = 0); a composed
# scenario SIGKILLs the master AND a migration source in the same
# window and requires standby takeover, WAL replay, and the resume()d
# migration to all converge; durable-ack p99 push must stay <=1.5x a
# no-log baseline at the default group window. Every dead
# incarnation's log is fsck'd by check_pushlog.py (in-drill and again
# here over the tree), then the umbrella fsck audits the whole
# workdir. Fast-lane equivalent:
# tests/test_pushlog.py::test_quake_drill_fast_lane +
# tests/test_failover.py::test_composed_master_and_shard_kill.
quake-smoke:
	workdir=$$(mktemp -d /tmp/edl_quake.XXXXXX); \
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu.chaos.quake_drill run \
		--workdir $$workdir --report QUAKE_DRILL.json \
	&& $(PY) tools/check_pushlog.py $$workdir \
	&& $(PY) tools/fsck.py $$workdir; \
	rc=$$?; rm -rf $$workdir; exit $$rc

# Umbrella fsck: discover every auditable artifact (master journals,
# checkpoint chains, cold stores, push logs, incident bundles,
# shard-map state files) under FSCK_DIR and run the matching
# tools/check_*.py validator — until this target, each drill wired
# its own subset. CI runs it over the repo tree on every push.
FSCK_DIR ?= .
fsck:
	$(PY) tools/fsck.py $(FSCK_DIR)

# Randomized soak: N seed-derived plans; a failure prints the seed
# that reproduces it (slow lane — not part of tier-1).
CHAOS_ROUNDS ?= 5
chaos-soak:
	JAX_PLATFORMS=cpu $(PY) -m elasticdl_tpu chaos soak \
		--seed $(CHAOS_SEED) --rounds $(CHAOS_ROUNDS) \
		--report CHAOS_soak.json

# Multi-chip sharding dry run on a virtual 8-device CPU mesh.
dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	$(PY) __graft_entry__.py 8

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null; \
	rm -f elasticdl_tpu/native/_librowstore.so \
	      elasticdl_tpu/native/_record_ext.so
