"""Benchmark: examples/sec/chip on the MNIST CNN training step.

Measures the task-granular execution mode (core/step.build_multi_step):
the framework's unit of work is a task of N minibatches (reference
task_dispatcher records_per_task), and fusing those N optimizer steps
into one XLA program via lax.scan removes N-1 host dispatches per task —
the dominant cost for small models. Distinct batches are stacked on
device; per-step losses remain observable.

Prints ONE JSON line {"metric","value","unit","vs_baseline"}. The
reference publishes no numbers (BASELINE.md), so the regression floor is
this repo's own first TPU run, recorded in BENCH_FLOOR.json; until that
file exists vs_baseline is 1.0 and the floor is written on a TPU run.
"""

import json
import os
import time

import numpy as np

BATCH = 512
STEPS_PER_TASK = 16   # reference num_minibatches_per_task granularity
WARMUP_TASKS = 2
MEASURE_TASKS = 4
MEASURE_ROUNDS = 5    # median over rounds (tunnel throughput varies)
FLOOR_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "BENCH_FLOOR.json")


def main():
    import jax

    from elasticdl_tpu.core.model_spec import get_model_spec
    from elasticdl_tpu.core.step import build_multi_step, stack_batches
    from elasticdl_tpu.core.train_state import init_train_state
    from elasticdl_tpu.testing.data import model_zoo_dir

    platform = jax.devices()[0].platform
    spec = get_model_spec(
        model_zoo_dir(), "mnist.mnist_functional.custom_model"
    )
    rng = np.random.RandomState(0)

    def make_batch():
        # Learnable label-correlated pixels (same scheme as
        # testing.data.create_mnist_record_file) so the measured steps
        # are healthy training, not divergence to inf/nan.
        labels = rng.randint(0, 10, BATCH).astype(np.int32)
        images = rng.rand(BATCH, 28 * 28).astype(np.float32) * 0.125
        block = (28 * 28) // 10
        for i, label in enumerate(labels):
            images[i, label * block:(label + 1) * block] += 0.75
        return {
            "features": images.reshape(BATCH, 28, 28),
            "labels": labels,
            "mask": np.ones((BATCH,), np.float32),
        }

    task = jax.device_put(
        stack_batches([make_batch() for _ in range(STEPS_PER_TASK)])
    )
    state = init_train_state(
        spec.model, spec.make_optimizer(),
        jax.tree.map(lambda x: x[0], task), seed=0,
    )
    multi_step = build_multi_step(spec.loss)

    def sync(metrics):
        # Host transfer of the last step's loss: a hard sync even where
        # block_until_ready returns early (tunnel'd device backends).
        return float(np.asarray(metrics["loss"][-1]))

    for _ in range(WARMUP_TASKS):
        state, metrics = multi_step(state, task)
    sync(metrics)

    # Median of repeated rounds: the device tunnel's throughput varies
    # run to run, and a single window makes vs_baseline noise.
    rounds = []
    final_loss = 0.0
    for _ in range(MEASURE_ROUNDS):
        start = time.perf_counter()
        for _ in range(MEASURE_TASKS):
            state, metrics = multi_step(state, task)
        final_loss = sync(metrics)
        rounds.append(time.perf_counter() - start)
    elapsed = float(np.median(rounds))
    assert np.isfinite(final_loss), f"bench diverged: loss={final_loss}"

    examples_per_sec = (
        BATCH * STEPS_PER_TASK * MEASURE_TASKS / elapsed
    )
    vs_baseline = 1.0
    floor = None
    if os.path.exists(FLOOR_FILE):
        try:
            with open(FLOOR_FILE) as f:
                floor = json.load(f).get("examples_per_sec")
        except Exception:
            floor = None
    if floor:
        vs_baseline = examples_per_sec / floor
    elif platform != "cpu":
        with open(FLOOR_FILE, "w") as f:
            json.dump(
                {"examples_per_sec": examples_per_sec,
                 "platform": platform, "batch": BATCH},
                f,
            )
    print(json.dumps({
        "metric": f"mnist_cnn_train_examples_per_sec_per_chip[{platform}]",
        "value": round(examples_per_sec, 2),
        "unit": "examples/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
