"""Benchmark: examples/sec/chip on the MNIST CNN training step.

Prints ONE JSON line {"metric","value","unit","vs_baseline"}. The reference
publishes no numbers (BASELINE.md), so the regression floor is this repo's
own first TPU run, recorded in BENCH_FLOOR.json; until that file exists
vs_baseline is 1.0 and the floor is written on a TPU run.
"""

import json
import os
import time

import numpy as np

BATCH = 512
WARMUP_STEPS = 5
MEASURE_STEPS = 30
FLOOR_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "BENCH_FLOOR.json")


def main():
    import jax

    from elasticdl_tpu.core.model_spec import get_model_spec
    from elasticdl_tpu.core.step import build_train_step
    from elasticdl_tpu.core.train_state import init_train_state
    from elasticdl_tpu.testing.data import model_zoo_dir

    platform = jax.devices()[0].platform
    spec = get_model_spec(
        model_zoo_dir(), "mnist.mnist_functional.custom_model"
    )
    rng = np.random.RandomState(0)
    batch = {
        "features": rng.rand(BATCH, 28, 28).astype(np.float32) * 255.0,
        "labels": rng.randint(0, 10, BATCH).astype(np.int32),
        "mask": np.ones((BATCH,), np.float32),
    }
    state = init_train_state(
        spec.model, spec.make_optimizer(), batch, seed=0
    )
    step = build_train_step(spec.loss)

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, batch)
    jax.block_until_ready(state.params)

    start = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = step(state, batch)
    jax.block_until_ready(state.params)
    elapsed = time.perf_counter() - start

    examples_per_sec = BATCH * MEASURE_STEPS / elapsed
    vs_baseline = 1.0
    floor = None
    if os.path.exists(FLOOR_FILE):
        try:
            with open(FLOOR_FILE) as f:
                floor = json.load(f).get("examples_per_sec")
        except Exception:
            floor = None
    if floor:
        vs_baseline = examples_per_sec / floor
    elif platform != "cpu":
        with open(FLOOR_FILE, "w") as f:
            json.dump(
                {"examples_per_sec": examples_per_sec,
                 "platform": platform, "batch": BATCH},
                f,
            )
    print(json.dumps({
        "metric": f"mnist_cnn_train_examples_per_sec_per_chip[{platform}]",
        "value": round(examples_per_sec, 2),
        "unit": "examples/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
