"""Benchmark: examples/sec/chip on the MNIST CNN training step.

Measures the task-granular execution mode (core/step.build_multi_step):
the framework's unit of work is a task of N minibatches (reference
task_dispatcher records_per_task), and fusing those N optimizer steps
into one XLA program via lax.scan removes N-1 host dispatches per task —
the dominant cost for small models. Distinct batches are stacked on
device; per-step losses remain observable.

Prints ONE JSON line {"metric","value","unit","vs_baseline"}. The
reference publishes no numbers (BASELINE.md), so the regression floor is
this repo's own first TPU run, recorded in BENCH_FLOOR.json; until that
file exists vs_baseline is 1.0 and the floor is written on a TPU run.

The measurement harness lives in benchlib.py (shared with the breadth
suite bench_suite.py).
"""

import json
import os

import numpy as np

from benchlib import load_json, make_mnist_batch, measure_multi_step

BATCH = 512
STEPS_PER_TASK = 16   # reference num_minibatches_per_task granularity
MEASURE_TASKS = 4
MEASURE_ROUNDS = 5    # median over rounds (tunnel throughput varies)
FLOOR_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "BENCH_FLOOR.json")


def main():
    import jax

    from elasticdl_tpu.core.model_spec import get_model_spec
    from elasticdl_tpu.core.step import stack_batches
    from elasticdl_tpu.testing.data import model_zoo_dir

    platform = jax.devices()[0].platform
    spec = get_model_spec(
        model_zoo_dir(), "mnist.mnist_functional.custom_model"
    )
    rng = np.random.RandomState(0)
    task = jax.device_put(
        stack_batches(
            [make_mnist_batch(BATCH, rng) for _ in range(STEPS_PER_TASK)]
        )
    )
    examples_per_sec = measure_multi_step(
        spec, task, BATCH, STEPS_PER_TASK, MEASURE_TASKS,
        measure_rounds=MEASURE_ROUNDS,
    )

    floor = load_json(FLOOR_FILE, {}).get("examples_per_sec")
    vs_baseline = examples_per_sec / floor if floor else 1.0
    if not floor and platform != "cpu":
        with open(FLOOR_FILE, "w") as f:
            json.dump(
                {"examples_per_sec": examples_per_sec,
                 "platform": platform, "batch": BATCH},
                f,
            )
    print(json.dumps({
        "metric": f"mnist_cnn_train_examples_per_sec_per_chip[{platform}]",
        "value": round(examples_per_sec, 2),
        "unit": "examples/sec/chip",
        "vs_baseline": round(vs_baseline, 4),
    }))


if __name__ == "__main__":
    main()
