"""Driver benchmark entry: the WHOLE perf surface in one artifact.

Runs, as subprocesses (one TPU client at a time):
  1. bench_suite.py --check-floors — all six BASELINE.md configs
     (mnist / cifar10 / resnet50 / deepfm / census / transformer LM),
     each reporting examples-or-tokens/sec/chip, vs_floor, and MFU
     (achieved FLOPs/sec from XLA cost analysis over the chip's bf16
     peak — benchlib.program_flops).
  2. bench_elasticity.py — job throughput under a mid-task worker kill
     (baseline/preempted records/sec, recovery seconds).

Prints one human-readable JSON line per sub-metric, then ONE final
summary line {"metric","value","unit","vs_baseline","configs",
"elasticity"} — the driver parses the last line, so regressions in ANY
config surface in BENCH_r{N}.json: the headline value is the WORST
vs_floor across configs (the regression gate; >= 1.0 means every config
is at or above its recorded floor).

The reference's analogue is scripts/client_test.sh — the e2e job matrix
every change must keep green; here the matrix is perf-gated too.
"""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


class _Failed:
    returncode = 1
    stdout = ""


def _run(script, *args):
    """Run a bench subprocess, echoing its output; return the proc (a
    stub with returncode=1 on timeout, so the summary line still
    prints)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, script), *args],
            capture_output=True, text=True, timeout=3600,
        )
    except subprocess.TimeoutExpired as exc:
        sys.stderr.write(f"{script} timed out after {exc.timeout}s\n")
        return _Failed()
    for line in proc.stdout.splitlines():
        print(line)
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr[-4000:])
    return proc


def _parse_metric_lines(stdout):
    for line in stdout.splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if "metric" in rec:
            yield rec


def main():
    # Summary is built from THIS run's printed lines, not the merged
    # BENCH_SUITE.json — a partially-crashed run must not present stale
    # (or CPU-smoke) entries as current measurements.
    suite = _run("bench_suite.py", "--check-floors")
    configs = {}
    platform = "unknown"
    for rec in _parse_metric_lines(suite.stdout):
        metric = rec["metric"]
        name = metric.split("_train_")[0]
        if "[" in metric:
            platform = metric.rsplit("[", 1)[1].rstrip("]")
        configs[name] = {
            "rate": rec["value"], "unit": rec["unit"],
            "vs_floor": rec["vs_baseline"], "mfu": rec.get("mfu"),
            "hbm_frac": rec.get("hbm_frac"),
            "rate_device": rec.get("rate_device"),
            "gate": rec.get("gate"),
            "platform": platform,
        }

    elasticity = {}
    elastic = _run("bench_elasticity.py")
    # Mesh-resize under load (dp4 -> dp2 -> dp4 on a virtual CPU mesh;
    # sets its own JAX_PLATFORMS=cpu so it never contends for the chip).
    resize = _run("bench_elasticity.py", "--scenario", "resize")
    # Same resizes with the row-sharded device-sparse recsys model LIVE
    # through every transition — the sparse × elasticity composition.
    resize_sparse = _run(
        "bench_elasticity.py", "--scenario", "resize", "--model", "sparse"
    )
    for proc in (elastic, resize, resize_sparse):
        for rec in _parse_metric_lines(proc.stdout):
            name, _, tag = rec["metric"].partition("[")
            if not name.startswith("elastic_"):
                continue
            key = name[len("elastic_"):]
            if "sparse" in tag:
                key += "_sparse"
            elasticity[key] = {
                "value": rec["value"], "unit": rec["unit"],
                "vs_baseline": rec["vs_baseline"],
            }

    worst = min(
        (c["vs_floor"] for c in configs.values()), default=0.0
    )
    print(json.dumps({
        "metric": f"bench_suite_worst_vs_floor[{platform}]",
        "value": round(worst, 4),
        "unit": "x_floor",
        "vs_baseline": round(worst, 4),
        "configs": configs,
        "elasticity": elasticity,
    }))
    # Floor regressions and crashed sub-benches fail the bench loudly.
    return (
        0 if suite.returncode == 0 and elastic.returncode == 0
        and resize.returncode == 0 and resize_sparse.returncode == 0
        else 1
    )


if __name__ == "__main__":
    sys.exit(main())
