"""Job throughput under worker preemption (BASELINE.md target #6).

The reference's headline capability is elasticity: a killed worker pod must
not sink the job, only its in-flight tasks (re-queued by the master,
``k8s_instance_manager.py:278`` -> ``task_dispatcher.py:352-364``). Here the
same contract is mesh-native: recovery = sharded checkpoint + task re-queue
(SURVEY.md §7 stage 5) because there is no PS process to survive.

Measures, in-process (the reference benches this path on minikube pods;
the framework logic is identical either way):

  A. baseline: one worker drains an mnist job of R records      -> rec/sec
  B. preempt:  same job, worker killed mid-task at ~50% (its
     in-flight task is left in `doing` and re-queued by the
     master); a replacement worker restores from the sharded
     checkpoint, retrains the re-queued task, drains the rest    -> rec/sec
  recovery_seconds: replacement construction + checkpoint restore +
     first completed task (the downtime added by the kill, measured
     to the replacement's first report_task_result).

Prints one JSON line per metric; throughput_retention = B/A (1.0 means the
kill cost nothing beyond the re-run of re-queued minibatches).
"""

import json
import os
import sys
import tempfile
import time

TOTAL_RECORDS = 8192
MINIBATCH = 64
MINIBATCHES_PER_TASK = 8
CHECKPOINT_STEPS = 16
REPS = 2


class _Preempted(RuntimeError):
    pass


def _make_cluster(train, ckpt_dir, kill_after_tasks=None):
    from elasticdl_tpu.testing.cluster import MiniCluster
    from elasticdl_tpu.testing.data import model_zoo_dir

    callbacks = None
    if kill_after_tasks is not None:
        calls = {"n": 0}

        # Raise on the report of task K+1: that task is fully trained but
        # unreported, so it sits in the dispatcher's `doing` queue at the
        # kill — recover_tasks() genuinely re-queues in-flight work (the
        # k8s watch-event path), not just undispatched tasks.
        def die(request):
            calls["n"] += 1
            if calls["n"] > kill_after_tasks:
                raise _Preempted("simulated pod preemption (exit 137)")

        callbacks = {"report_task_result": die}
    return MiniCluster(
        model_zoo=model_zoo_dir(),
        model_def="mnist.mnist_functional.custom_model",
        training_data=train,
        minibatch_size=MINIBATCH,
        num_minibatches_per_task=MINIBATCHES_PER_TASK,
        checkpoint_dir=ckpt_dir,
        checkpoint_steps=CHECKPOINT_STEPS,
        worker_callbacks=callbacks,
        fuse_task_steps=True,
    )


def run_resize_scenario(model: str = "mnist"):
    """Mesh-resize under load: dp4 -> dp2 -> dp4 on a virtual CPU mesh.

    The reference's pitch is utilization under elasticity — a worker
    leaves, the job keeps most of its throughput, the worker returns,
    throughput recovers. On TPU a membership change is a NEW Mesh
    (tests/test_elastic_mesh_resize.py proves correctness); this
    scenario makes it quantitative: a task-completion timeline across
    two live resizes, per-phase records/sec, and the recovery seconds
    each transition costs (kill -> first task completed on the resized
    mesh). Runs on 8 virtual CPU devices — the timeline SHAPE (not
    absolute chip rates) is the artifact, same spirit as the
    reference's minikube bench. Results merge into BENCH_SUITE.json
    under "elastic_resize" (/"elastic_resize_sparse") and gate on a hard
    floor: every phase must finish and worst-phase retention vs phase-1
    must stay >= FLOOR.

    ``model="sparse"`` runs the recsys device-sparse model instead of
    mnist: the table (+Adagrad slots) is LIVE row-sharded over dp
    through every resize, so each transition exercises the cross-N
    repartition restore (every device's row range changes) — the
    reference's defining recsys-elasticity composition
    (save_utils.py:206-259 under a mid-training PS-count change).
    Tiny-vocab shapes: the artifact is the timeline, not chip rates.
    """
    import jax
    import numpy as np

    from elasticdl_tpu.checkpoint import CheckpointHook
    from elasticdl_tpu.core.model_spec import get_model_spec
    from elasticdl_tpu.parallel.mesh import make_mesh
    from elasticdl_tpu.parallel.mesh_runner import make_runner_for_spec
    from elasticdl_tpu.testing.data import (
        create_frappe_record_file,
        create_mnist_record_file,
        model_zoo_dir,
    )
    from elasticdl_tpu.testing.in_process_master import InProcessMaster
    from elasticdl_tpu.worker.worker import Worker

    RESIZE_FLOOR = 0.25          # worst-phase retention vs phase 1
    # Smaller job than the preempt scenario: CPU-mesh steps are ~100x
    # the chip's and the artifact is the timeline SHAPE — 16 tasks give
    # ~5 per phase at ~2s each on an idle host.
    resize_records = 4096
    mb_per_task = 4
    records_per_task = MINIBATCH * mb_per_task
    total_tasks = resize_records // records_per_task
    kill_points = (total_tasks // 3, 2 * total_tasks // 3)

    tmp = tempfile.mkdtemp(prefix="bench_resize_")
    from contextlib import ExitStack

    stack = ExitStack()
    try:
        if model == "sparse":
            # Tiny-shape recsys on the device-sparse plane (shared
            # testing.tiny_zoo override — no 1M x 256 table on the CPU
            # mesh); threshold 0 keeps the tiny table row-sharded.
            from elasticdl_tpu.embedding.device_sparse import (
                DeviceSparseRunner,
            )
            from elasticdl_tpu.embedding.optimizer import Adagrad
            from elasticdl_tpu.testing.tiny_zoo import tiny_recsys_zoo

            zoo = stack.enter_context(tiny_recsys_zoo(vocab=4096, dim=16))
            model_def = "recsys.recsys_sparse.custom_model"
            train = create_frappe_record_file(
                os.path.join(tmp, "train.rec"), resize_records, seed=11,
                input_length=8, max_id=zoo.VOCAB,
            )

            def runner_for(spec, mesh):
                return DeviceSparseRunner(
                    zoo.TABLE_SPECS, Adagrad(lr=0.05), use_pallas="never",
                    mesh=mesh, partition_threshold_bytes=0,
                )
        else:
            model_def = "mnist.mnist_functional.custom_model"
            train = create_mnist_record_file(
                os.path.join(tmp, "train.rec"), resize_records, seed=11
            )

            def runner_for(spec, mesh):
                spec.model = spec.make_model(mesh)
                return make_runner_for_spec(spec, mesh)
        ckpt_dir = os.path.join(tmp, "ckpt")

        devices = jax.devices()
        if len(devices) < 4:
            raise SystemExit(
                "resize scenario needs >=4 devices "
                "(run under xla_force_host_platform_device_count)"
            )
        mesh_of = {4: lambda: make_mesh((4,), ("dp",), devices=devices[:4]),
                   2: lambda: make_mesh((2,), ("dp",), devices=devices[:2])}
        phase_sizes = (4, 2, 4)      # dp4 -> shrink -> regrow

        timeline = []                # (t_rel, phase_idx) per completed task
        t0 = time.perf_counter()

        def make_worker(worker_id, phase_idx, servicer, spec, reader,
                        kill_at_total):
            """A worker on the phase's mesh; raises _Preempted once the
            job-wide completed-task count reaches ``kill_at_total``."""
            mesh = mesh_of[phase_sizes[phase_idx]]()
            runner = runner_for(spec, mesh)

            def on_report(request):
                # The callback fires BEFORE the servicer records the result:
                # raising here leaves the trained-but-unreported task in
                # `doing` (the genuine preemption shape), so it must NOT be
                # counted — the resized mesh re-trains and re-reports it.
                if (kill_at_total is not None
                        and len(timeline) + 1 > kill_at_total):
                    raise _Preempted(f"resize point {kill_at_total}")
                timeline.append((time.perf_counter() - t0, phase_idx))

            return Worker(
                worker_id=worker_id,
                master_client=InProcessMaster(
                    servicer, worker_id=worker_id,
                    callbacks={"report_task_result": on_report},
                ),
                model_spec=spec,
                data_reader=reader,
                minibatch_size=MINIBATCH,
                step_runner=runner,
                checkpoint_hook=CheckpointHook(
                    checkpoint_dir=ckpt_dir,
                    checkpoint_steps=mb_per_task,
                ),
                checkpoint_dir_for_init=ckpt_dir if worker_id else "",
                fuse_task_steps=True,
            )

        from elasticdl_tpu.testing.cluster import MiniCluster

        cluster = MiniCluster(
            model_zoo=model_zoo_dir(),
            model_def=model_def,
            training_data=train,
            minibatch_size=MINIBATCH,
            num_minibatches_per_task=mb_per_task,
            checkpoint_dir=ckpt_dir,
            checkpoint_steps=mb_per_task,
            fuse_task_steps=True,
        )
        servicer, dispatcher = cluster.servicer, cluster.dispatcher
        transitions = []
        phase_idx = 0
        worker_id = 0
        while True:
            kill_at = (kill_points[phase_idx]
                       if phase_idx < len(kill_points) else None)
            spec = get_model_spec(model_zoo_dir(), model_def)
            worker = make_worker(
                worker_id, phase_idx, servicer, spec,
                cluster.train_reader, kill_at,
            )
            try:
                worker.run()
            except _Preempted:
                # The in-flight task dies with the worker; the master's
                # watch-event path re-queues it for the resized mesh.
                if dispatcher.doing_tasks_of(worker_id):
                    dispatcher.recover_tasks(worker_id)
                transitions.append(
                    {"killed_at": time.perf_counter() - t0}
                )
                phase_idx += 1
                worker_id += 1
                continue
            break
    finally:
        stack.close()  # un-shrink the zoo for any in-process caller
    if not cluster.finished:
        raise SystemExit("resize scenario did not drain the job")

    # Per-phase throughput from the timeline; recovery = kill -> first
    # completed task on the new mesh (includes restore + recompile —
    # the real downtime a resize costs).
    phases = []
    for p in range(len(phase_sizes)):
        stamps = [t for t, ph in timeline if ph == p]
        if not stamps:
            phases.append({"dp": phase_sizes[p], "tasks": 0, "rate": 0.0})
            continue
        start = 0.0 if p == 0 else transitions[p - 1]["killed_at"]
        span = max(stamps[-1] - start, 1e-9)
        phases.append({
            "dp": phase_sizes[p],
            "tasks": len(stamps),
            "rate": round(len(stamps) * records_per_task / span, 2),
        })
    recoveries = []
    for p, tr in enumerate(transitions):
        nxt = [t for t, ph in timeline if ph == p + 1]
        recoveries.append(
            round(nxt[0] - tr["killed_at"], 3) if nxt else None
        )

    base_rate = phases[0]["rate"] or 1e-9
    worst_retention = min(ph["rate"] / base_rate for ph in phases)
    for metric, value, unit, vs in (
        ("elastic_resize_shrunk_records_per_sec", phases[1]["rate"],
         "records/sec", phases[1]["rate"] / base_rate),
        ("elastic_resize_regrown_records_per_sec", phases[2]["rate"],
         "records/sec", phases[2]["rate"] / base_rate),
        ("elastic_resize_shrink_recovery_seconds", recoveries[0] or -1.0,
         "seconds", 0.0),
        ("elastic_resize_grow_recovery_seconds", recoveries[1] or -1.0,
         "seconds", 0.0),
        ("elastic_resize_worst_phase_retention", round(worst_retention, 4),
         "ratio", round(worst_retention, 4)),
    ):
        tag = "cpu-mesh-sparse" if model == "sparse" else "cpu-mesh"
        print(json.dumps({
            "metric": f"{metric}[{tag}]", "value": round(value, 2),
            "unit": unit, "vs_baseline": round(vs, 4),
        }))

    from benchlib import load_json

    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "BENCH_SUITE.json")
    suite = load_json(out_path, {})
    key = "elastic_resize_sparse" if model == "sparse" else \
        "elastic_resize"
    suite[key] = {
        "phases": phases,
        "recovery_seconds": recoveries,
        "timeline": [
            {"t": round(t, 3), "phase": ph} for t, ph in timeline
        ],
        "floor": RESIZE_FLOOR,
        "worst_phase_retention": round(worst_retention, 4),
    }
    with open(out_path, "w") as f:
        json.dump(suite, f, indent=1)
    if worst_retention < RESIZE_FLOOR:
        raise SystemExit(
            f"resize retention {worst_retention:.3f} < floor {RESIZE_FLOOR}"
        )


def run_autoscale_scenario(reps: int = 3):
    """Live-reshard vs checkpoint-restart resize downtime, in-process.

    The autoscaler's whole case (ISSUE 8): a scale event's cost is the
    dead-hardware window between the last step on the old mesh and the
    first step on the new one. Measures that window for both resize
    mechanisms, per direction, on a virtual 8-device CPU mesh:

    - **checkpoint_restart** (the old path, what a pod relaunch does):
      synchronous save → model-spec reload → fresh runner →
      ``init_state`` on the new mesh → restore from disk → re-place →
      rebuild + run the first step;
    - **live_reshard** (parallel/reshard.py): ``MeshRunner.resize`` —
      gather to host → re-derive shardings → ``device_put`` → rebuild
      + run the first step. No disk, no re-init, worker object kept.

    Both paths pay the first-step XLA build for the new mesh; the
    persistent compilation cache is on (the production setting —
    worker/main.py wires it for elastic relaunches) and one unmeasured
    warmup round populates it for BOTH paths, so the comparison
    isolates the transition mechanism rather than first-ever compile
    cost. Medians over ``reps`` alternating rounds. Writes
    BENCH_AUTOSCALE.json and FAILS (exit nonzero) unless live reshard
    is >= TARGET_SPEEDUP (5x) faster per direction.
    """
    import argparse

    import jax
    import numpy as np

    from elasticdl_tpu.checkpoint import (
        CheckpointHook,
        restore_from_dir,
    )
    from elasticdl_tpu.parallel.mesh import make_mesh
    from elasticdl_tpu.worker.main import _enable_compilation_cache

    import flax.linen as nn
    import jax.numpy as jnp
    import optax

    from elasticdl_tpu.parallel.mesh_runner import MeshRunner

    TARGET_SPEEDUP = 5.0
    # ~400MB of train state: big enough that the transition mechanisms
    # (disk round trip vs device-to-device moves) dominate the window,
    # small enough to keep the bench a few minutes on the CPU mesh.
    WIDTH, DEPTH, BATCH = 2048, 12, 8
    devices = jax.devices()
    if len(devices) < 4:
        raise SystemExit(
            "autoscale scenario needs >=4 devices "
            "(run under xla_force_host_platform_device_count)"
        )
    tmp = tempfile.mkdtemp(prefix="bench_autoscale_")
    _enable_compilation_cache(argparse.Namespace(
        compilation_cache_dir=os.path.join(tmp, "xla_cache")
    ))
    mesh_of = {
        4: lambda: make_mesh((4,), ("dp",), devices=devices[:4]),
        2: lambda: make_mesh((2,), ("dp",), devices=devices[:2]),
    }

    # Production-representative state size (~100MB params + ~100MB
    # momentum, ZeRO-sharded over dp): with a toy-sized model both
    # paths are dominated by the identical first-step program build
    # and the transition mechanism under test is invisible. Matmul
    # work stays small (batch 8) so step time doesn't swamp the
    # window either.
    class WideMLP(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            for _ in range(DEPTH):
                x = nn.relu(nn.Dense(WIDTH)(x))
            return nn.Dense(1)(x)[..., 0]

    def loss_fn(labels, preds, mask):
        per = (preds - labels.astype(jnp.float32)) ** 2
        return (per * mask).sum() / jnp.maximum(mask.sum(), 1)

    rng = np.random.RandomState(0)
    batch = {
        "features": rng.rand(BATCH, WIDTH).astype(np.float32),
        "labels": rng.rand(BATCH).astype(np.float32),
        "mask": np.ones((BATCH,), np.float32),
    }
    make_optimizer = lambda: optax.sgd(1e-3, momentum=0.9)  # noqa: E731
    state_mb = round(
        2 * (DEPTH * WIDTH * WIDTH + WIDTH) * 4 / 2 ** 20
    )

    def fresh_state(dp):
        """Runner + state on a dp-mesh, warmed with 2 steps so the
        transition starts from a mid-training state (buffers live,
        step program compiled — the autoscaler's situation)."""
        mesh = mesh_of[dp]()
        runner = MeshRunner(mesh=mesh)
        model = WideMLP()
        state = runner.init_state(model, make_optimizer(), batch,
                                  seed=0)
        step = runner.train_step(loss_fn)
        for _ in range(2):
            state, _m = step(state, batch)
        jax.block_until_ready(jax.tree_util.tree_leaves(state.params))
        return runner, state

    def first_step(runner, state):
        step = runner.train_step(loss_fn)
        state, _m = step(state, batch)
        jax.block_until_ready(jax.tree_util.tree_leaves(state.params))
        return state

    # The restore side of checkpoint-restart runs in a FRESH process —
    # that is what the mechanism is (save → process teardown → relaunch
    # → restore → re-place → recompile): a relaunched worker pays
    # interpreter start, jax import, backend init, and empty in-process
    # caches. The persistent XLA cache dir is shared (production
    # setting), so its compiles are cache-served like the parent's.
    child_script = os.path.join(tmp, "restore_child.py")
    with open(child_script, "w") as f:
        f.write(
            "import os, sys\n"
            "_f = os.environ.get('XLA_FLAGS', '')\n"
            "if 'xla_force_host_platform_device_count' not in _f:\n"
            "    os.environ['XLA_FLAGS'] = (_f +"
            " ' --xla_force_host_platform_device_count=8').strip()\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_compilation_cache_dir',"
            f" {os.path.join(tmp, 'xla_cache')!r})\n"
            "jax.config.update("
            "'jax_persistent_cache_min_compile_time_secs', 0.0)\n"
            "jax.config.update("
            "'jax_persistent_cache_min_entry_size_bytes', -1)\n"
            "import numpy as np, optax\n"
            "import flax.linen as nn, jax.numpy as jnp\n"
            "from elasticdl_tpu.parallel.mesh import make_mesh\n"
            "from elasticdl_tpu.parallel.mesh_runner import MeshRunner\n"
            "from elasticdl_tpu.checkpoint import restore_from_dir\n"
            f"WIDTH, DEPTH, BATCH = {WIDTH}, {DEPTH}, {BATCH}\n"
            "class WideMLP(nn.Module):\n"
            "    @nn.compact\n"
            "    def __call__(self, x, training=False):\n"
            "        for _ in range(DEPTH):\n"
            "            x = nn.relu(nn.Dense(WIDTH)(x))\n"
            "        return nn.Dense(1)(x)[..., 0]\n"
            "def loss_fn(labels, preds, mask):\n"
            "    per = (preds - labels.astype(jnp.float32)) ** 2\n"
            "    return (per * mask).sum() / jnp.maximum(mask.sum(), 1)\n"
            "ckpt_dir, dp = sys.argv[1], int(sys.argv[2])\n"
            "rng = np.random.RandomState(0)\n"
            "batch = {'features': rng.rand(BATCH, WIDTH)"
            ".astype(np.float32),\n"
            "         'labels': rng.rand(BATCH).astype(np.float32),\n"
            "         'mask': np.ones((BATCH,), np.float32)}\n"
            "mesh = make_mesh((dp,), ('dp',),"
            " devices=jax.devices()[:dp])\n"
            "runner = MeshRunner(mesh=mesh)\n"
            "state = runner.init_state(WideMLP(),"
            " optax.sgd(1e-3, momentum=0.9), batch, seed=1)\n"
            "state = restore_from_dir(state, ckpt_dir, required=True)\n"
            "state = runner.place_state(state)\n"
            "step = runner.train_step(loss_fn)\n"
            "state, _m = step(state, batch)\n"
            "jax.block_until_ready("
            "jax.tree_util.tree_leaves(state.params))\n"
        )

    def checkpoint_restart(from_dp, to_dp, tag):
        """The full old-path transition, timed end to end: sync save,
        then a fresh worker process restores on the new mesh and
        completes its first step."""
        import subprocess

        runner, state = fresh_state(from_dp)
        ckpt_dir = os.path.join(tmp, f"ckpt_{tag}")
        hook = CheckpointHook(
            checkpoint_dir=ckpt_dir, checkpoint_steps=1,
            async_save=False,
        )
        t0 = time.perf_counter()
        hook.save_final(state)                  # save to disk
        here = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(                  # teardown + relaunch
            [sys.executable, child_script, ckpt_dir, str(to_dp)],
            capture_output=True, text=True, env=env, cwd=here,
        )
        elapsed = time.perf_counter() - t0
        if proc.returncode != 0:
            raise SystemExit(
                f"restore child failed:\n{proc.stderr[-2000:]}"
            )
        return elapsed

    def live_reshard(from_dp, to_dp):
        """MeshRunner.resize, timed over the same window, in the
        autoscaler's steady state: the long-lived worker has trained
        on BOTH rungs before (scale events oscillate between a few
        mesh sizes), so its per-rung compiled steps are warm
        (MeshRunner's step memo) and a repeat transition pays only the
        state movement + one already-compiled step. The
        checkpoint-restart baseline can never reach this state — its
        process (and every in-process cache) dies with each resize."""
        runner, state = fresh_state(from_dp)
        state = runner.resize(mesh_of[to_dp](), state)
        state = first_step(runner, state)
        state = runner.resize(mesh_of[from_dp](), state)
        state = first_step(runner, state)
        t0 = time.perf_counter()
        state = runner.resize(mesh_of[to_dp](), state)  # shards move
        first_step(runner, state)               # warm step, runs now
        return time.perf_counter() - t0

    # Warmup: one unmeasured round of each path/direction populates the
    # persistent compile cache for every program both paths build.
    checkpoint_restart(4, 2, "warm_s")
    checkpoint_restart(2, 4, "warm_g")
    live_reshard(4, 2)
    live_reshard(2, 4)

    results = {"shrink": {"ckpt": [], "live": []},
               "grow": {"ckpt": [], "live": []}}
    for rep in range(reps):
        results["shrink"]["ckpt"].append(
            checkpoint_restart(4, 2, f"s{rep}")
        )
        results["shrink"]["live"].append(live_reshard(4, 2))
        results["grow"]["ckpt"].append(
            checkpoint_restart(2, 4, f"g{rep}")
        )
        results["grow"]["live"].append(live_reshard(2, 4))

    out = {
        "method": (
            "downtime = last step on old mesh -> first step completed "
            "on new mesh, in-process virtual CPU mesh (dp4<->dp2), "
            f"~{state_mb}MB train state (params + SGD momentum, "
            "ZeRO-sharded), persistent XLA compile cache warmed for "
            f"both paths; medians over {reps} alternating reps"
        ),
        "state_mb": state_mb,
        "target_speedup": TARGET_SPEEDUP,
        "directions": {},
    }
    worst_speedup = float("inf")
    for direction, series in results.items():
        ckpt_ms = float(np.median(series["ckpt"])) * 1000.0
        live_ms = float(np.median(series["live"])) * 1000.0
        speedup = ckpt_ms / max(live_ms, 1e-9)
        worst_speedup = min(worst_speedup, speedup)
        out["directions"][direction] = {
            "resize_downtime_ms": {
                "checkpoint_restart": round(ckpt_ms, 2),
                "live_reshard": round(live_ms, 2),
            },
            "speedup": round(speedup, 2),
            "raw_secs": {
                "checkpoint_restart": [
                    round(s, 4) for s in series["ckpt"]
                ],
                "live_reshard": [
                    round(s, 4) for s in series["live"]
                ],
            },
        }
        print(json.dumps({
            "metric": f"resize_downtime_ms[{direction}]",
            "checkpoint_restart": round(ckpt_ms, 2),
            "live_reshard": round(live_ms, 2),
            "speedup": round(speedup, 2),
        }))
    out["worst_direction_speedup"] = round(worst_speedup, 2)
    out["passed"] = bool(worst_speedup >= TARGET_SPEEDUP)
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "BENCH_AUTOSCALE.json"), "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    if not out["passed"]:
        raise SystemExit(
            f"live reshard speedup {worst_speedup:.2f}x < "
            f"{TARGET_SPEEDUP}x target"
        )


def main():
    import argparse as _argparse

    ap = _argparse.ArgumentParser()
    ap.add_argument("--scenario", choices=("preempt", "resize",
                                           "autoscale"),
                    default="preempt")
    ap.add_argument("--model", choices=("mnist", "sparse"),
                    default="mnist",
                    help="resize scenario's workload: mnist (dense) or "
                         "the row-sharded device-sparse recsys model")
    args = ap.parse_args()
    scenario = args.scenario
    if scenario == "autoscale":
        # Same virtual-CPU-mesh forcing as the resize scenario.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        return run_autoscale_scenario()
    if scenario == "resize":
        # Resizes need a multi-device CPU mesh and must not contend for
        # the bench chip. The site hook registers the TPU plugin and
        # sets jax_platforms in CONFIG (env vars are too late — same
        # note as tests/conftest.py), so override the config before the
        # first backend init; the XLA flag must precede it too.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        return run_resize_scenario(model=args.model)

    import argparse

    import jax

    from elasticdl_tpu.testing.data import create_mnist_record_file
    from elasticdl_tpu.testing.in_process_master import InProcessMaster
    from elasticdl_tpu.worker.main import _enable_compilation_cache
    from elasticdl_tpu.worker.worker import Worker

    platform = jax.devices()[0].platform
    tmp = tempfile.mkdtemp(prefix="bench_elastic_")
    # The elastic-relaunch story includes the persistent XLA compilation
    # cache (--compilation_cache_dir): a replacement worker restores
    # compiled executables from disk, so recovery is checkpoint-read
    # bound, not compile bound. Same wiring as worker/main.py.
    _enable_compilation_cache(argparse.Namespace(
        compilation_cache_dir=os.path.join(tmp, "xla_cache")
    ))
    train = create_mnist_record_file(
        os.path.join(tmp, "train.rec"), TOTAL_RECORDS, seed=7
    )

    # Warmup job on a small slice: pays jit compilation once so both
    # measured phases see the same (cached) compile cost, as a long-lived
    # worker would.
    warm = create_mnist_record_file(
        os.path.join(tmp, "w.rec"), MINIBATCH * MINIBATCHES_PER_TASK, seed=8
    )
    _make_cluster(warm, os.path.join(tmp, "ckpt_w")).run()

    def run_clean(tag):
        cluster = _make_cluster(train, os.path.join(tmp, f"ckpt_a{tag}"))
        start = time.perf_counter()
        cluster.run()
        elapsed = time.perf_counter() - start
        assert cluster.finished
        return elapsed

    def run_preempted(tag):
        """Kill at ~50% of tasks, requeue, replacement restores + drains."""
        total_tasks = TOTAL_RECORDS // (MINIBATCH * MINIBATCHES_PER_TASK)
        ckpt_b = os.path.join(tmp, f"ckpt_b{tag}")
        cluster = _make_cluster(
            train, ckpt_b, kill_after_tasks=total_tasks // 2
        )
        start = time.perf_counter()
        try:
            cluster.workers[0].run()
        except _Preempted:
            pass
        assert not cluster.finished
        # The in-flight task must be sitting in doing for the requeue
        # path to be exercised.
        assert cluster.dispatcher.doing_tasks_of(0)
        cluster.dispatcher.recover_tasks(0)  # master watch-event path

        recover_start = time.perf_counter()
        first_report = {}

        def record_first_report(request):
            first_report.setdefault("t", time.perf_counter())

        from elasticdl_tpu.checkpoint import CheckpointHook

        replacement = Worker(
            worker_id=1,
            master_client=InProcessMaster(
                cluster.servicer, worker_id=1,
                callbacks={"report_task_result": record_first_report},
            ),
            model_spec=cluster.spec,
            data_reader=cluster.train_reader,
            minibatch_size=MINIBATCH,
            # Same checkpoint duty as the worker it replaces — otherwise
            # phase B throughput wins by skipping checkpoint saves.
            checkpoint_hook=CheckpointHook(
                checkpoint_dir=ckpt_b, checkpoint_steps=CHECKPOINT_STEPS,
            ),
            checkpoint_dir_for_init=ckpt_b,
            fuse_task_steps=True,
        )
        replacement.run()
        elapsed = time.perf_counter() - start
        assert cluster.finished
        return elapsed, first_report["t"] - recover_start

    # Interleave A/B repetitions: the device-tunnel RTT drifts over
    # minutes and per-batch host->device round trips dominate this
    # job-level bench, so alternating phases + medians keeps the
    # retention ratio from measuring tunnel weather.
    t_bases, t_kills, recoveries = [], [], []
    for rep in range(REPS):
        t_bases.append(run_clean(rep))
        t_kill, recovery = run_preempted(rep)
        t_kills.append(t_kill)
        recoveries.append(recovery)

    import numpy as np

    base_rps = TOTAL_RECORDS / float(np.median(t_bases))
    kill_rps = TOTAL_RECORDS / float(np.median(t_kills))
    recovery_seconds = float(np.median(recoveries))
    for metric, value, unit, vs in (
        ("elastic_baseline_records_per_sec", base_rps, "records/sec", 1.0),
        ("elastic_preempted_records_per_sec", kill_rps, "records/sec",
         kill_rps / base_rps),
        ("elastic_recovery_seconds", recovery_seconds, "seconds", 0.0),
    ):
        print(json.dumps({
            "metric": f"{metric}[{platform}]",
            "value": round(value, 2),
            "unit": unit,
            "vs_baseline": round(vs, 4),
        }))


if __name__ == "__main__":
    sys.exit(main())
