"""Job throughput under worker preemption (BASELINE.md target #6).

The reference's headline capability is elasticity: a killed worker pod must
not sink the job, only its in-flight tasks (re-queued by the master,
``k8s_instance_manager.py:278`` -> ``task_dispatcher.py:352-364``). Here the
same contract is mesh-native: recovery = sharded checkpoint + task re-queue
(SURVEY.md §7 stage 5) because there is no PS process to survive.

Measures, in-process (the reference benches this path on minikube pods;
the framework logic is identical either way):

  A. baseline: one worker drains an mnist job of R records      -> rec/sec
  B. preempt:  same job, worker killed mid-task at ~50% (its
     in-flight task is left in `doing` and re-queued by the
     master); a replacement worker restores from the sharded
     checkpoint, retrains the re-queued task, drains the rest    -> rec/sec
  recovery_seconds: replacement construction + checkpoint restore +
     first completed task (the downtime added by the kill, measured
     to the replacement's first report_task_result).

Prints one JSON line per metric; throughput_retention = B/A (1.0 means the
kill cost nothing beyond the re-run of re-queued minibatches).
"""

import json
import os
import sys
import tempfile
import time

TOTAL_RECORDS = 8192
MINIBATCH = 64
MINIBATCHES_PER_TASK = 8
CHECKPOINT_STEPS = 16
REPS = 2


class _Preempted(RuntimeError):
    pass


def _make_cluster(train, ckpt_dir, kill_after_tasks=None):
    from elasticdl_tpu.testing.cluster import MiniCluster
    from elasticdl_tpu.testing.data import model_zoo_dir

    callbacks = None
    if kill_after_tasks is not None:
        calls = {"n": 0}

        # Raise on the report of task K+1: that task is fully trained but
        # unreported, so it sits in the dispatcher's `doing` queue at the
        # kill — recover_tasks() genuinely re-queues in-flight work (the
        # k8s watch-event path), not just undispatched tasks.
        def die(request):
            calls["n"] += 1
            if calls["n"] > kill_after_tasks:
                raise _Preempted("simulated pod preemption (exit 137)")

        callbacks = {"report_task_result": die}
    return MiniCluster(
        model_zoo=model_zoo_dir(),
        model_def="mnist.mnist_functional.custom_model",
        training_data=train,
        minibatch_size=MINIBATCH,
        num_minibatches_per_task=MINIBATCHES_PER_TASK,
        checkpoint_dir=ckpt_dir,
        checkpoint_steps=CHECKPOINT_STEPS,
        worker_callbacks=callbacks,
        fuse_task_steps=True,
    )


def main():
    import argparse

    import jax

    from elasticdl_tpu.testing.data import create_mnist_record_file
    from elasticdl_tpu.testing.in_process_master import InProcessMaster
    from elasticdl_tpu.worker.main import _enable_compilation_cache
    from elasticdl_tpu.worker.worker import Worker

    platform = jax.devices()[0].platform
    tmp = tempfile.mkdtemp(prefix="bench_elastic_")
    # The elastic-relaunch story includes the persistent XLA compilation
    # cache (--compilation_cache_dir): a replacement worker restores
    # compiled executables from disk, so recovery is checkpoint-read
    # bound, not compile bound. Same wiring as worker/main.py.
    _enable_compilation_cache(argparse.Namespace(
        compilation_cache_dir=os.path.join(tmp, "xla_cache")
    ))
    train = create_mnist_record_file(
        os.path.join(tmp, "train.rec"), TOTAL_RECORDS, seed=7
    )

    # Warmup job on a small slice: pays jit compilation once so both
    # measured phases see the same (cached) compile cost, as a long-lived
    # worker would.
    warm = create_mnist_record_file(
        os.path.join(tmp, "w.rec"), MINIBATCH * MINIBATCHES_PER_TASK, seed=8
    )
    _make_cluster(warm, os.path.join(tmp, "ckpt_w")).run()

    def run_clean(tag):
        cluster = _make_cluster(train, os.path.join(tmp, f"ckpt_a{tag}"))
        start = time.perf_counter()
        cluster.run()
        elapsed = time.perf_counter() - start
        assert cluster.finished
        return elapsed

    def run_preempted(tag):
        """Kill at ~50% of tasks, requeue, replacement restores + drains."""
        total_tasks = TOTAL_RECORDS // (MINIBATCH * MINIBATCHES_PER_TASK)
        ckpt_b = os.path.join(tmp, f"ckpt_b{tag}")
        cluster = _make_cluster(
            train, ckpt_b, kill_after_tasks=total_tasks // 2
        )
        start = time.perf_counter()
        try:
            cluster.workers[0].run()
        except _Preempted:
            pass
        assert not cluster.finished
        # The in-flight task must be sitting in doing for the requeue
        # path to be exercised.
        assert cluster.dispatcher.doing_tasks_of(0)
        cluster.dispatcher.recover_tasks(0)  # master watch-event path

        recover_start = time.perf_counter()
        first_report = {}

        def record_first_report(request):
            first_report.setdefault("t", time.perf_counter())

        from elasticdl_tpu.checkpoint import CheckpointHook

        replacement = Worker(
            worker_id=1,
            master_client=InProcessMaster(
                cluster.servicer, worker_id=1,
                callbacks={"report_task_result": record_first_report},
            ),
            model_spec=cluster.spec,
            data_reader=cluster.train_reader,
            minibatch_size=MINIBATCH,
            # Same checkpoint duty as the worker it replaces — otherwise
            # phase B throughput wins by skipping checkpoint saves.
            checkpoint_hook=CheckpointHook(
                checkpoint_dir=ckpt_b, checkpoint_steps=CHECKPOINT_STEPS,
            ),
            checkpoint_dir_for_init=ckpt_b,
            fuse_task_steps=True,
        )
        replacement.run()
        elapsed = time.perf_counter() - start
        assert cluster.finished
        return elapsed, first_report["t"] - recover_start

    # Interleave A/B repetitions: the device-tunnel RTT drifts over
    # minutes and per-batch host->device round trips dominate this
    # job-level bench, so alternating phases + medians keeps the
    # retention ratio from measuring tunnel weather.
    t_bases, t_kills, recoveries = [], [], []
    for rep in range(REPS):
        t_bases.append(run_clean(rep))
        t_kill, recovery = run_preempted(rep)
        t_kills.append(t_kill)
        recoveries.append(recovery)

    import numpy as np

    base_rps = TOTAL_RECORDS / float(np.median(t_bases))
    kill_rps = TOTAL_RECORDS / float(np.median(t_kills))
    recovery_seconds = float(np.median(recoveries))
    for metric, value, unit, vs in (
        ("elastic_baseline_records_per_sec", base_rps, "records/sec", 1.0),
        ("elastic_preempted_records_per_sec", kill_rps, "records/sec",
         kill_rps / base_rps),
        ("elastic_recovery_seconds", recovery_seconds, "seconds", 0.0),
    ):
        print(json.dumps({
            "metric": f"{metric}[{platform}]",
            "value": round(value, 2),
            "unit": unit,
            "vs_baseline": round(vs, 4),
        }))


if __name__ == "__main__":
    sys.exit(main())
