"""Shared measurement harness for bench.py / bench_suite.py.

One implementation of batch synthesis, the warmup/median measurement loop,
and floor-file bookkeeping so the driver bench (bench.py) and the breadth
suite (bench_suite.py) can't drift apart.
"""

import json
import os
import time

import numpy as np


def make_mnist_batch(batch, rng, flat=False):
    """Label-correlated pixels (same scheme as
    testing.data.create_mnist_record_file) so measured steps are healthy
    training, not divergence to inf/nan."""
    labels = rng.randint(0, 10, batch).astype(np.int32)
    images = rng.rand(batch, 28 * 28).astype(np.float32) * 0.125
    block = (28 * 28) // 10
    for i, label in enumerate(labels):
        images[i, label * block:(label + 1) * block] += 0.75
    features = images if flat else images.reshape(batch, 28, 28)
    return {
        "features": features,
        "labels": labels,
        "mask": np.ones((batch,), np.float32),
    }


# Peak dense-matmul throughput per chip (bf16), for MFU accounting.
# Sources: public TPU spec sheets; device_kind prefixes as reported by
# jax.devices()[0].device_kind.
PEAK_BF16_FLOPS = (
    ("TPU v5 lite", 197e12),   # v5e
    ("TPU v5e", 197e12),
    ("TPU v5p", 459e12),
    ("TPU v5", 459e12),
    ("TPU v4", 275e12),
    ("TPU v6", 918e12),        # Trillium
)


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "") or ""
    for prefix, peak in PEAK_BF16_FLOPS:
        if kind.startswith(prefix):
            return peak
    return 0.0


def program_flops(spec, batch):
    """FLOPs of ONE optimizer step (forward+backward+apply) from XLA's
    cost analysis of the compiled single-step program. The bench configs
    run without rematerialization, so this equals the model's analytic
    FLOPs (no recompute inflation) — the numerator MFU is defined over."""
    import jax

    from elasticdl_tpu.core.step import build_train_step
    from elasticdl_tpu.core.train_state import init_train_state

    state = init_train_state(
        spec.model, spec.make_optimizer(), batch, seed=0
    )
    compiled = build_train_step(spec.loss).lower(state, batch).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return float((cost or {}).get("flops", 0.0))


def measure_multi_step(spec, task, batch, steps_per_task, measure_tasks,
                       warmup_tasks=2, measure_rounds=3,
                       compute_mfu=False):
    """Time the fused task-granular step (core/step.build_multi_step) on a
    device-resident task; returns examples/sec (median over rounds — the
    device tunnel's throughput varies run to run). With ``compute_mfu``,
    returns ``(examples_per_sec, mfu, tflops_per_sec)`` where MFU is
    achieved FLOPs/sec over the chip's bf16 peak (program_flops)."""
    import jax

    from elasticdl_tpu.core.step import build_multi_step
    from elasticdl_tpu.core.train_state import init_train_state

    state = init_train_state(
        spec.model, spec.make_optimizer(),
        jax.tree.map(lambda x: x[0], task), seed=0,
    )
    multi_step = build_multi_step(spec.loss)

    def sync(metrics):
        # Host transfer of the last step's loss: a hard sync even where
        # block_until_ready returns early (tunnel'd device backends).
        return float(np.asarray(metrics["loss"][-1]))

    for _ in range(warmup_tasks):
        state, metrics = multi_step(state, task)
    sync(metrics)

    rounds = []
    final_loss = 0.0
    for _ in range(measure_rounds):
        start = time.perf_counter()
        for _ in range(measure_tasks):
            state, metrics = multi_step(state, task)
        final_loss = sync(metrics)
        rounds.append(time.perf_counter() - start)
    elapsed = float(np.median(rounds))
    assert np.isfinite(final_loss), f"bench diverged: loss={final_loss}"
    eps = batch * steps_per_task * measure_tasks / elapsed
    if not compute_mfu:
        return eps
    flops_step = program_flops(spec, jax.tree.map(lambda x: x[0], task))
    achieved = flops_step * steps_per_task * measure_tasks / elapsed
    peak = peak_flops(jax.devices()[0])
    mfu = achieved / peak if peak else 0.0
    return eps, mfu, achieved / 1e12


def load_json(path, default):
    if os.path.exists(path):
        try:
            with open(path) as f:
                return json.load(f)
        except Exception:
            pass
    return default


def merge_json(path, updates):
    """Read-modify-write so subset runs don't drop other entries."""
    data = load_json(path, {})
    data.update(updates)
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
    return data
