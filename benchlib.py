"""Shared measurement harness for bench.py / bench_suite.py.

One implementation of batch synthesis, the warmup/median measurement loop,
and floor-file bookkeeping so the driver bench (bench.py) and the breadth
suite (bench_suite.py) can't drift apart.

Measurement-integrity design (round 3): the chip is reached through a
device tunnel whose per-dispatch latency swings run to run (observed
±12% back-to-back on sub-ms-step configs — BASELINE.md "Floor
re-baseline"). Three defenses, all applied:

1. **Device-time rate**: one measuring round runs under
   ``jax.profiler`` and the per-program device execution time is read
   off the trace's "XLA Modules" lane (``module_device_times``). Device
   time is what the framework controls — tunnel weather cannot touch
   it — so it is the regression-gating metric on TPU; wall rate is
   recorded alongside (production jobs don't run through an HTTP
   tunnel, so wall there tracks device time).
2. **Big fused programs**: dispatch-bound configs fuse 128 steps per
   XLA program (bench_suite CONFIGS), putting per-program wall at
   ~300ms against ~10-15ms dispatch (<5%), where round 2's 32-step
   programs sat at ~15-20%.
3. **Min-of-rounds wall estimator**: tunnel noise is one-sided
   (contention only ever adds time), so the minimum over
   ``measure_rounds`` timed rounds estimates the true sustained rate;
   the spread across rounds is recorded as evidence.
"""

import glob
import gzip
import json
import os
import tempfile
import time

import numpy as np


def enable_bench_compile_cache():
    """Persistent XLA compile cache for bench processes (verified to
    work through the axon remote-compile tunnel: second-process compile
    of the probe program dropped 2.4s -> 0.9s). Makes fresh-process
    isolated floor readings cheap. Cache dir is machine-local."""
    import jax

    cache_dir = os.environ.get(
        "ELASTICDL_BENCH_CACHE", "/tmp/elasticdl_xla_bench_cache"
    )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def make_mnist_batch(batch, rng, flat=False):
    """Label-correlated pixels (same scheme as
    testing.data.create_mnist_record_file) so measured steps are healthy
    training, not divergence to inf/nan."""
    labels = rng.randint(0, 10, batch).astype(np.int32)
    images = rng.rand(batch, 28 * 28).astype(np.float32) * 0.125
    block = (28 * 28) // 10
    for i, label in enumerate(labels):
        images[i, label * block:(label + 1) * block] += 0.75
    features = images if flat else images.reshape(batch, 28, 28)
    return {
        "features": features,
        "labels": labels,
        "mask": np.ones((batch,), np.float32),
    }


# Peak dense-matmul throughput per chip (bf16), for MFU accounting.
# Sources: public TPU spec sheets; device_kind prefixes as reported by
# jax.devices()[0].device_kind.
PEAK_BF16_FLOPS = (
    ("TPU v5 lite", 197e12),   # v5e
    ("TPU v5e", 197e12),
    ("TPU v5p", 459e12),
    ("TPU v5", 459e12),
    ("TPU v4", 275e12),
    ("TPU v6", 918e12),        # Trillium
)


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "") or ""
    for prefix, peak in PEAK_BF16_FLOPS:
        if kind.startswith(prefix):
            return peak
    return 0.0


def load_config_spec(name):
    """(spec, batch, steps, measure_tasks) for a bench_suite config:
    zoo spec with the transformer size fixup applied. Cheap — tools
    that re-measure model variants rebuild just this per variant."""
    import bench_suite
    from elasticdl_tpu.core.model_spec import get_model_spec
    from elasticdl_tpu.testing.data import model_zoo_dir

    model_def, batch, steps, measure_tasks = bench_suite.CONFIGS[name]
    spec = get_model_spec(model_zoo_dir(), model_def)
    if name.startswith("transformer"):
        spec = bench_suite._transformer_spec(spec, name)
    return spec, batch, steps, measure_tasks


def load_config_harness(name, seed=0, spec_parts=None):
    """(spec, task, batch, steps, measure_tasks) for a bench_suite
    config: ``load_config_spec`` plus a device-resident stacked task of
    ``steps`` deterministic batches — the prologue every measurement
    tool shares (profile_config, measure_config, duel_fused_head,
    dump_config_hlo, measure_dispatch_gap). ``spec_parts`` reuses an
    existing ``load_config_spec(name)`` result instead of rebuilding
    the zoo spec (tools that sweep model variants)."""
    import jax
    import numpy as np

    import bench_suite
    from elasticdl_tpu.core.step import stack_batches

    spec, batch, steps, measure_tasks = (
        spec_parts if spec_parts is not None else load_config_spec(name)
    )
    rng = np.random.RandomState(seed)
    task = jax.device_put(stack_batches(
        [bench_suite._make_batch(name, batch, rng) for _ in range(steps)]
    ))
    return spec, task, batch, steps, measure_tasks


def program_flops(spec, batch):
    """FLOPs of ONE optimizer step (forward+backward+apply) from XLA's
    cost analysis of the compiled single-step program. The bench configs
    run without rematerialization, so this equals the model's analytic
    FLOPs (no recompute inflation) — the numerator MFU is defined over."""
    import jax

    from elasticdl_tpu.core.step import build_train_step
    from elasticdl_tpu.core.train_state import init_train_state

    state = init_train_state(
        spec.model, spec.make_optimizer(), batch, seed=0
    )
    compiled = build_train_step(spec.loss).lower(state, batch).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return float((cost or {}).get("flops", 0.0))


def module_device_times(trace_dir, name_filter="multi_step"):
    """Per-program device execution times (ms) from the newest
    ``jax.profiler`` trace under ``trace_dir``.

    Reads the Perfetto JSON the profiler writes and returns the
    durations of complete events on the device process's "XLA Modules"
    lane — one event per executed XLA program, timed ON the device, so
    host/dispatch/tunnel time is excluded by construction.
    ``name_filter`` keeps only the measured program (e.g. the
    ``jit_multi_step`` task program), dropping incidental transfers or
    helper programs that executed inside the trace window; if nothing
    matches, all module events are returned (program naming is backend
    -dependent). Empty list when the trace has no device lane (CPU).
    """
    return [d for _, d in module_device_events(trace_dir, name_filter)]


def module_device_events(trace_dir, name_filter="multi_step"):
    """(start_ms, dur_ms) per device execution of the measured program,
    sorted by start — same lane/name-filter/fallback semantics as
    ``module_device_times`` (which is now a view over this); the starts
    let callers measure inter-program host gaps
    (tools/measure_dispatch_gap.py)."""
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "plugins/profile/*/*.trace.json.gz"
    )))
    if not paths:
        return []
    with gzip.open(paths[-1]) as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    dev_pids = set()
    module_lanes = set()
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name" and "/device:" in (
            args.get("name") or ""
        ):
            dev_pids.add(e.get("pid"))
        if e.get("name") == "thread_name" and args.get("name") == "XLA Modules":
            module_lanes.add((e.get("pid"), e.get("tid")))
    lanes = {(p, t) for (p, t) in module_lanes if p in dev_pids}
    mods = [
        e for e in events
        if e.get("ph") == "X" and (e.get("pid"), e.get("tid")) in lanes
    ]
    named = [e for e in mods if name_filter in (e.get("name") or "")]
    return sorted(
        (e.get("ts", 0) / 1e3, e.get("dur", 0) / 1e3)
        for e in (named or mods)
    )


def _measure_device_time(multi_step, state, task, sync, measure_tasks):
    """Run ``measure_tasks`` programs under a profiler trace; return
    (state, median per-program device ms) — 0.0 if the backend's trace
    has no device lane."""
    import jax

    with tempfile.TemporaryDirectory(prefix="bench_trace_") as td:
        jax.profiler.start_trace(td)
        try:
            for _ in range(measure_tasks):
                state, metrics = multi_step(state, task)
            sync(metrics)
        finally:
            jax.profiler.stop_trace()
        times = module_device_times(td)
    if not times:
        return state, 0.0
    # Median over programs: device time is already near-constant
    # (<2% observed spread); the median shrugs off a stray partial
    # event at the trace boundary.
    return state, float(np.median(times))


def measure_multi_step(spec, task, batch, steps_per_task, measure_tasks,
                       warmup_tasks=2, measure_rounds=5,
                       compute_mfu=False, device_time=True):
    """Time the fused task-granular step (core/step.build_multi_step) on
    a device-resident task.

    Returns a dict:
      ``eps``                examples/sec from the MIN wall time over
                             ``measure_rounds`` rounds (tunnel noise is
                             one-sided — see module docstring)
      ``eps_median``         median-of-rounds wall rate
      ``wall_spread``        (max-min)/min over the timed rounds — the
                             recorded variance evidence
      ``device_ms_per_task`` median per-program device time off the
                             profiler trace (0.0 where no device lane)
      ``eps_device``         examples/sec over device time alone — the
                             tunnel-immune regression-gating rate
      ``mfu`` / ``tflops_per_sec``  (with ``compute_mfu``) achieved
                             FLOPs/sec over bf16 peak, computed on
                             device time when available (wall
                             otherwise) — MFU is a device-efficiency
                             statement, so device time is its honest
                             denominator
    """
    import jax

    from elasticdl_tpu.core.step import build_multi_step
    from elasticdl_tpu.core.train_state import init_train_state

    if getattr(spec, "make_sparse_runner", None):
        # Device-tier sparse models (embedding/device_sparse.py): the
        # runner owns state init and the fused multi-step — the Pallas
        # lookup + row-kernel path this config exists to measure.
        runner = spec.make_sparse_runner()
        state = runner.init_state(
            spec.model, spec.make_optimizer(),
            jax.tree.map(lambda x: x[0], task), seed=0,
        )
        multi_step = runner.train_multi_step(spec.loss)
    else:
        state = init_train_state(
            spec.model, spec.make_optimizer(),
            jax.tree.map(lambda x: x[0], task), seed=0,
        )
        multi_step = build_multi_step(spec.loss)

    def sync(metrics):
        # Host transfer of the last step's loss: a hard sync even where
        # block_until_ready returns early (tunnel'd device backends).
        return float(np.asarray(metrics["loss"][-1]))

    for _ in range(warmup_tasks):
        state, metrics = multi_step(state, task)
    sync(metrics)

    rounds = []
    final_loss = 0.0
    for _ in range(measure_rounds):
        start = time.perf_counter()
        for _ in range(measure_tasks):
            state, metrics = multi_step(state, task)
        final_loss = sync(metrics)
        rounds.append(time.perf_counter() - start)
    assert np.isfinite(final_loss), f"bench diverged: loss={final_loss}"

    examples = batch * steps_per_task * measure_tasks
    best = float(np.min(rounds))
    result = {
        "eps": examples / best,
        "eps_median": examples / float(np.median(rounds)),
        "wall_spread": float((np.max(rounds) - np.min(rounds))
                             / np.min(rounds)),
        "rounds_sec": [round(r, 5) for r in rounds],
    }

    device_ms = 0.0
    if device_time:
        state, device_ms = _measure_device_time(
            multi_step, state, task, sync, measure_tasks
        )
    result["device_ms_per_task"] = round(device_ms, 3)
    result["eps_device"] = (
        batch * steps_per_task / (device_ms / 1e3) if device_ms else 0.0
    )

    if compute_mfu and getattr(spec, "make_sparse_runner", None):
        # Embedding-bound by construction: MFU is structurally ~0 and
        # the dense-step cost analysis doesn't apply to the sparse
        # program. Rate is the metric (BASELINE.md round-2 notes).
        result["mfu"] = 0.0
        result["tflops_per_sec"] = 0.0
    elif compute_mfu:
        flops_step = program_flops(
            spec, jax.tree.map(lambda x: x[0], task)
        )
        if device_ms:
            achieved = flops_step * steps_per_task / (device_ms / 1e3)
        else:
            achieved = flops_step * steps_per_task * measure_tasks / best
        peak = peak_flops(jax.devices()[0])
        result["mfu"] = achieved / peak if peak else 0.0
        result["tflops_per_sec"] = achieved / 1e12
    return result


def load_json(path, default):
    if os.path.exists(path):
        try:
            with open(path) as f:
                return json.load(f)
        except Exception:
            pass
    return default


def merge_json(path, updates):
    """Read-modify-write so subset runs don't drop other entries."""
    data = load_json(path, {})
    data.update(updates)
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
    return data
