"""Shared measurement harness for bench.py / bench_suite.py.

One implementation of batch synthesis, the warmup/median measurement loop,
and floor-file bookkeeping so the driver bench (bench.py) and the breadth
suite (bench_suite.py) can't drift apart.

Measurement-integrity design (round 3): the chip is reached through a
device tunnel whose per-dispatch latency swings run to run (observed
±12% back-to-back on sub-ms-step configs — BASELINE.md "Floor
re-baseline"). Three defenses, all applied:

1. **Device-time rate**: one measuring round runs under
   ``jax.profiler`` and the per-program device execution time is read
   off the trace's "XLA Modules" lane (``module_device_times``). Device
   time is what the framework controls — tunnel weather cannot touch
   it — so it is the regression-gating metric on TPU; wall rate is
   recorded alongside (production jobs don't run through an HTTP
   tunnel, so wall there tracks device time).
2. **Big fused programs**: dispatch-bound configs fuse 128 steps per
   XLA program (bench_suite CONFIGS), putting per-program wall at
   ~300ms against ~10-15ms dispatch (<5%), where round 2's 32-step
   programs sat at ~15-20%.
3. **Min-of-rounds wall estimator**: tunnel noise is one-sided
   (contention only ever adds time), so the minimum over
   ``measure_rounds`` timed rounds estimates the true sustained rate;
   the spread across rounds is recorded as evidence.
"""

import glob
import gzip
import json
import os
import tempfile
import time

import numpy as np


def enable_bench_compile_cache():
    """Persistent XLA compile cache for bench processes (verified to
    work through the axon remote-compile tunnel: second-process compile
    of the probe program dropped 2.4s -> 0.9s). Makes fresh-process
    isolated floor readings cheap. Cache dir is machine-local."""
    import jax

    cache_dir = os.environ.get(
        "ELASTICDL_BENCH_CACHE", "/tmp/elasticdl_xla_bench_cache"
    )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def make_mnist_batch(batch, rng, flat=False):
    """Label-correlated pixels (same scheme as
    testing.data.create_mnist_record_file) so measured steps are healthy
    training, not divergence to inf/nan."""
    labels = rng.randint(0, 10, batch).astype(np.int32)
    images = rng.rand(batch, 28 * 28).astype(np.float32) * 0.125
    block = (28 * 28) // 10
    for i, label in enumerate(labels):
        images[i, label * block:(label + 1) * block] += 0.75
    features = images if flat else images.reshape(batch, 28, 28)
    return {
        "features": features,
        "labels": labels,
        "mask": np.ones((batch,), np.float32),
    }


# Peak dense-matmul throughput per chip (bf16), for MFU accounting.
# Sources: public TPU spec sheets; device_kind prefixes as reported by
# jax.devices()[0].device_kind.
PEAK_BF16_FLOPS = (
    ("TPU v5 lite", 197e12),   # v5e
    ("TPU v5e", 197e12),
    ("TPU v5p", 459e12),
    ("TPU v5", 459e12),
    ("TPU v4", 275e12),
    ("TPU v6", 918e12),        # Trillium
)


def peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "") or ""
    for prefix, peak in PEAK_BF16_FLOPS:
        if kind.startswith(prefix):
            return peak
    return 0.0


# Peak HBM bandwidth per chip (bytes/sec), for roofline accounting on
# embedding-bound configs (MFU is meaningless there — the honest
# efficiency metric is fraction of memory bandwidth). Public spec-sheet
# numbers, same prefix scheme as PEAK_BF16_FLOPS.
PEAK_HBM_BYTES_PER_SEC = (
    ("TPU v5 lite", 819e9),    # v5e
    ("TPU v5e", 819e9),
    ("TPU v5p", 2765e9),
    ("TPU v5", 2765e9),
    ("TPU v4", 1228e9),
    ("TPU v6", 1640e9),        # Trillium
)


def peak_hbm_bw(device) -> float:
    kind = getattr(device, "device_kind", "") or ""
    for prefix, peak in PEAK_HBM_BYTES_PER_SEC:
        if kind.startswith(prefix):
            return peak
    return 0.0


def load_config_spec(name):
    """(spec, batch, steps, measure_tasks) for a bench_suite config —
    delegates to bench_suite.config_spec so tools always measure the
    exact spec (transformer sizes, recsys packed layout) the suite
    gates on."""
    import bench_suite

    return bench_suite.config_spec(name)


def load_config_harness(name, seed=0, spec_parts=None):
    """(spec, task, batch, steps, measure_tasks) for a bench_suite
    config: ``load_config_spec`` plus a device-resident stacked task of
    ``steps`` deterministic batches — the prologue every measurement
    tool shares (profile_config, measure_config, duel_fused_head,
    dump_config_hlo, measure_dispatch_gap). ``spec_parts`` reuses an
    existing ``load_config_spec(name)`` result instead of rebuilding
    the zoo spec (tools that sweep model variants)."""
    import jax
    import numpy as np

    import bench_suite
    from elasticdl_tpu.core.step import stack_batches

    spec, batch, steps, measure_tasks = (
        spec_parts if spec_parts is not None else load_config_spec(name)
    )
    rng = np.random.RandomState(seed)
    task = jax.device_put(stack_batches(
        [bench_suite._make_batch(name, batch, rng) for _ in range(steps)]
    ))
    return spec, task, batch, steps, measure_tasks


def program_cost(spec, batch, state=None, step=None):
    """XLA cost analysis of ONE compiled optimizer step (forward +
    backward + apply): {"flops": ...}. The bench configs run without
    rematerialization, so flops equals the model's analytic FLOPs (no
    recompute inflation) — the numerator MFU is defined over.

    Device-tier sparse specs (``make_sparse_runner``) are costed through
    THEIR program — the runner's lookup + row-kernel step — not the
    dense ``build_train_step``, which would never compile against a
    SparseTrainState. Pass ``state``/``step`` to reuse a live state and
    step function (measure_multi_step does — building a second sparse
    state would transiently double the production table in HBM)."""
    import jax

    from elasticdl_tpu.core.step import build_train_step
    from elasticdl_tpu.core.train_state import init_train_state

    if (state is None) != (step is None):
        # A lone state would be silently discarded and rebuilt — for a
        # sparse spec that transiently doubles the table in HBM, the
        # exact hazard passing state exists to avoid.
        raise ValueError("pass state and step together, or neither")
    if state is None:
        if getattr(spec, "make_sparse_runner", None):
            runner = spec.make_sparse_runner()
            state = runner.init_state(
                spec.model, spec.make_optimizer(), batch, seed=0
            )
            step = runner.train_step(spec.loss)
        else:
            state = init_train_state(
                spec.model, spec.make_optimizer(), batch, seed=0
            )
            step = build_train_step(spec.loss)
    cost = step.lower(state, batch).compile().cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return cost or {}


def program_flops(spec, batch, state=None, step=None):
    """FLOPs of one optimizer step (see ``program_cost``)."""
    return float(
        program_cost(spec, batch, state=state, step=step)
        .get("flops", 0.0)
    )


def analytic_bytes_per_step(state, batch, table_specs=()) -> float:
    """USEFUL HBM traffic of one optimizer step, in bytes — the
    numerator ``hbm_frac`` is defined over.

    Deliberately analytic, not XLA's "bytes accessed": the cost model
    charges a gather/scatter the FULL operand (a 1M-row table per
    lookup), which measured >1.0 "of peak" on deepfm — an estimator
    that can exceed the roofline attributes nothing. The analytic count
    is the traffic the training math REQUIRES; achieved/peak below 1.0
    then honestly splits into "moving bytes slower than the pin limit"
    vs "spending time on non-traffic work" (dispatch, sorts, compute).

    Model (documented so the number is auditable):
    - dense params ``p``: read at forward + read at backward + write at
      apply (3p), gradient write + read (2p) -> 5 x param bytes;
    - optimizer-state leaves: read + write at apply -> 2 x their bytes;
    - device-sparse tables (``table_specs``, SparseTrainState): per id
      in the batch (upper bound of unique rows) one row of traffic for
      forward read, row-grad write+read, apply read+write, and
      read+write per slot table -> (5 + 2*n_slots) x ids x row bytes;
      untouched rows move nothing — that IS the sparse plane's claim.
    - activations and the ids themselves are excluded (second-order at
      these shapes; documented as such in BASELINE.md).
    """
    import jax

    def nbytes(tree):
        return float(sum(
            np.size(leaf) * np.dtype(
                getattr(leaf, "dtype", np.float32)
            ).itemsize
            for leaf in jax.tree.leaves(tree)
        ))

    total = 5.0 * nbytes(state.params) + 2.0 * nbytes(state.opt_state)
    tables = getattr(state, "tables", None) or {}
    slot_tables = getattr(state, "slot_tables", None) or {}
    for spec in table_specs:
        if spec.name not in tables:
            continue
        ids = batch["features"][spec.feature_key]
        ids = getattr(ids, "ids", ids)          # RaggedIds -> ids
        itemsize = np.dtype(tables[spec.name].dtype).itemsize
        width = int(np.shape(tables[spec.name])[-1])
        if width > spec.dim:
            # packed_slots layout (optimizer.pack_table): forward reads
            # the full packed row (1x), apply gathers + scatters it
            # (2x), row grads write+read at model dim (2x).
            total += np.size(ids) * itemsize * (
                3.0 * width + 2.0 * spec.dim
            )
        else:
            n_slots = len(slot_tables.get(spec.name, {}))
            total += (
                (5.0 + 2.0 * n_slots) * np.size(ids) * spec.dim * itemsize
            )
    return total


def module_device_times(trace_dir, name_filter="multi_step"):
    """Per-program device execution times (ms) from the newest
    ``jax.profiler`` trace under ``trace_dir``.

    Reads the Perfetto JSON the profiler writes and returns the
    durations of complete events on the device process's "XLA Modules"
    lane — one event per executed XLA program, timed ON the device, so
    host/dispatch/tunnel time is excluded by construction.
    ``name_filter`` keeps only the measured program (e.g. the
    ``jit_multi_step`` task program), dropping incidental transfers or
    helper programs that executed inside the trace window; if nothing
    matches, all module events are returned (program naming is backend
    -dependent). Empty list when the trace has no device lane (CPU).
    """
    return [d for _, d in module_device_events(trace_dir, name_filter)]


def module_device_events(trace_dir, name_filter="multi_step"):
    """(start_ms, dur_ms) per device execution of the measured program,
    sorted by start — same lane/name-filter/fallback semantics as
    ``module_device_times`` (which is now a view over this); the starts
    let callers measure inter-program host gaps
    (tools/measure_dispatch_gap.py)."""
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "plugins/profile/*/*.trace.json.gz"
    )))
    if not paths:
        return []
    with gzip.open(paths[-1]) as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    dev_pids = set()
    module_lanes = set()
    for e in events:
        if e.get("ph") != "M":
            continue
        args = e.get("args") or {}
        if e.get("name") == "process_name" and "/device:" in (
            args.get("name") or ""
        ):
            dev_pids.add(e.get("pid"))
        if e.get("name") == "thread_name" and args.get("name") == "XLA Modules":
            module_lanes.add((e.get("pid"), e.get("tid")))
    lanes = {(p, t) for (p, t) in module_lanes if p in dev_pids}
    mods = [
        e for e in events
        if e.get("ph") == "X" and (e.get("pid"), e.get("tid")) in lanes
    ]
    named = [e for e in mods if name_filter in (e.get("name") or "")]
    return sorted(
        (e.get("ts", 0) / 1e3, e.get("dur", 0) / 1e3)
        for e in (named or mods)
    )


def _measure_device_time(multi_step, state, task, sync, measure_tasks):
    """Run ``measure_tasks`` programs under a profiler trace; return
    (state, median per-program device ms) — 0.0 if the backend's trace
    has no device lane."""
    import jax

    with tempfile.TemporaryDirectory(prefix="bench_trace_") as td:
        jax.profiler.start_trace(td)
        try:
            for _ in range(measure_tasks):
                state, metrics = multi_step(state, task)
            sync(metrics)
        finally:
            jax.profiler.stop_trace()
        times = module_device_times(td)
    if not times:
        return state, 0.0
    # Median over programs: device time is already near-constant
    # (<2% observed spread); the median shrugs off a stray partial
    # event at the trace boundary.
    return state, float(np.median(times))


def measure_multi_step(spec, task, batch, steps_per_task, measure_tasks,
                       warmup_tasks=2, measure_rounds=5,
                       compute_mfu=False, device_time=True):
    """Time the fused task-granular step (core/step.build_multi_step) on
    a device-resident task.

    Returns a dict:
      ``eps``                examples/sec from the MIN wall time over
                             ``measure_rounds`` rounds (tunnel noise is
                             one-sided — see module docstring)
      ``eps_median``         median-of-rounds wall rate
      ``wall_spread``        (max-min)/min over the timed rounds — the
                             recorded variance evidence
      ``device_ms_per_task`` median per-program device time off the
                             profiler trace (0.0 where no device lane)
      ``eps_device``         examples/sec over device time alone — the
                             tunnel-immune regression-gating rate
      ``mfu`` / ``tflops_per_sec``  (with ``compute_mfu``) achieved
                             FLOPs/sec over bf16 peak, computed on
                             device time when available (wall
                             otherwise) — MFU is a device-efficiency
                             statement, so device time is its honest
                             denominator
    """
    import jax

    from elasticdl_tpu.core.step import build_multi_step, build_train_step
    from elasticdl_tpu.core.train_state import init_train_state

    if getattr(spec, "make_sparse_runner", None):
        # Device-tier sparse models (embedding/device_sparse.py): the
        # runner owns state init and the fused multi-step — the Pallas
        # lookup + row-kernel path this config exists to measure.
        runner = spec.make_sparse_runner()
        sparse_specs = runner.specs
        state = runner.init_state(
            spec.model, spec.make_optimizer(),
            jax.tree.map(lambda x: x[0], task), seed=0,
        )
        multi_step = runner.train_multi_step(spec.loss)
        cost_step = runner.train_step(spec.loss)
    else:
        sparse_specs = ()
        state = init_train_state(
            spec.model, spec.make_optimizer(),
            jax.tree.map(lambda x: x[0], task), seed=0,
        )
        multi_step = build_multi_step(spec.loss)
        cost_step = build_train_step(spec.loss)

    def sync(metrics):
        # Host transfer of the last step's loss: a hard sync even where
        # block_until_ready returns early (tunnel'd device backends).
        return float(np.asarray(metrics["loss"][-1]))

    for _ in range(warmup_tasks):
        state, metrics = multi_step(state, task)
    sync(metrics)

    rounds = []
    final_loss = 0.0
    for _ in range(measure_rounds):
        start = time.perf_counter()
        for _ in range(measure_tasks):
            state, metrics = multi_step(state, task)
        final_loss = sync(metrics)
        rounds.append(time.perf_counter() - start)
    assert np.isfinite(final_loss), f"bench diverged: loss={final_loss}"

    examples = batch * steps_per_task * measure_tasks
    best = float(np.min(rounds))
    result = {
        "eps": examples / best,
        "eps_median": examples / float(np.median(rounds)),
        "wall_spread": float((np.max(rounds) - np.min(rounds))
                             / np.min(rounds)),
        "rounds_sec": [round(r, 5) for r in rounds],
    }

    device_ms = 0.0
    if device_time:
        state, device_ms = _measure_device_time(
            multi_step, state, task, sync, measure_tasks
        )
    result["device_ms_per_task"] = round(device_ms, 3)
    result["eps_device"] = (
        batch * steps_per_task / (device_ms / 1e3) if device_ms else 0.0
    )

    if compute_mfu:
        one_batch = jax.tree.map(lambda x: x[0], task)
        flops_step = program_flops(
            spec, one_batch, state=state, step=cost_step
        )
        bytes_step = analytic_bytes_per_step(
            state, one_batch, table_specs=sparse_specs
        )
        if device_ms:
            sec = device_ms / 1e3 / steps_per_task
        else:
            sec = best / (steps_per_task * measure_tasks)
        device = jax.devices()[0]
        peak = peak_flops(device)
        result["mfu"] = flops_step / sec / peak if peak else 0.0
        result["tflops_per_sec"] = flops_step / sec / 1e12
        # Roofline companion: achieved USEFUL bandwidth as a fraction
        # of the chip's peak (analytic_bytes_per_step) — the honest
        # efficiency statement for embedding-bound configs
        # (deepfm/census/recsys), where the step streams table rows and
        # mfu is structurally ~0. Near-1.0 means the program is at the
        # memory roofline and "faster" requires touching fewer bytes;
        # far below it with mfu also ~0 means the time goes to
        # non-traffic work — attribute before optimizing.
        peak_bw = peak_hbm_bw(device)
        result["bytes_per_step"] = bytes_step
        result["hbm_gbps"] = bytes_step / sec / 1e9 if sec else 0.0
        result["hbm_frac"] = (
            bytes_step / sec / peak_bw if peak_bw and sec else 0.0
        )
    return result


def load_json(path, default):
    if os.path.exists(path):
        try:
            with open(path) as f:
                return json.load(f)
        except Exception:
            pass
    return default


def merge_json(path, updates):
    """Read-modify-write so subset runs don't drop other entries."""
    data = load_json(path, {})
    data.update(updates)
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
    return data
