"""Shared measurement harness for bench.py / bench_suite.py.

One implementation of batch synthesis, the warmup/median measurement loop,
and floor-file bookkeeping so the driver bench (bench.py) and the breadth
suite (bench_suite.py) can't drift apart.
"""

import json
import os
import time

import numpy as np


def make_mnist_batch(batch, rng, flat=False):
    """Label-correlated pixels (same scheme as
    testing.data.create_mnist_record_file) so measured steps are healthy
    training, not divergence to inf/nan."""
    labels = rng.randint(0, 10, batch).astype(np.int32)
    images = rng.rand(batch, 28 * 28).astype(np.float32) * 0.125
    block = (28 * 28) // 10
    for i, label in enumerate(labels):
        images[i, label * block:(label + 1) * block] += 0.75
    features = images if flat else images.reshape(batch, 28, 28)
    return {
        "features": features,
        "labels": labels,
        "mask": np.ones((batch,), np.float32),
    }


def measure_multi_step(spec, task, batch, steps_per_task, measure_tasks,
                       warmup_tasks=2, measure_rounds=3):
    """Time the fused task-granular step (core/step.build_multi_step) on a
    device-resident task; returns examples/sec (median over rounds — the
    device tunnel's throughput varies run to run)."""
    import jax

    from elasticdl_tpu.core.step import build_multi_step
    from elasticdl_tpu.core.train_state import init_train_state

    state = init_train_state(
        spec.model, spec.make_optimizer(),
        jax.tree.map(lambda x: x[0], task), seed=0,
    )
    multi_step = build_multi_step(spec.loss)

    def sync(metrics):
        # Host transfer of the last step's loss: a hard sync even where
        # block_until_ready returns early (tunnel'd device backends).
        return float(np.asarray(metrics["loss"][-1]))

    for _ in range(warmup_tasks):
        state, metrics = multi_step(state, task)
    sync(metrics)

    rounds = []
    final_loss = 0.0
    for _ in range(measure_rounds):
        start = time.perf_counter()
        for _ in range(measure_tasks):
            state, metrics = multi_step(state, task)
        final_loss = sync(metrics)
        rounds.append(time.perf_counter() - start)
    elapsed = float(np.median(rounds))
    assert np.isfinite(final_loss), f"bench diverged: loss={final_loss}"
    return batch * steps_per_task * measure_tasks / elapsed


def load_json(path, default):
    if os.path.exists(path):
        try:
            with open(path) as f:
                return json.load(f)
        except Exception:
            pass
    return default


def merge_json(path, updates):
    """Read-modify-write so subset runs don't drop other entries."""
    data = load_json(path, {})
    data.update(updates)
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
    return data
