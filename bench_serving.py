"""Serving-plane benchmark: latency/throughput vs. batch deadline.

Answers the question the batching knobs exist for: what does adaptive
micro-batching buy over single-request serving, and what does the
flush deadline cost in p50/p99? The harness is fully in-process (an
``InferenceServer`` on an ephemeral port over a freshly exported
bundle) so the artifact measures the serving plane, not a network.

Phases:
1. export a dense MLP bundle and warm every batch bucket (the
   StableHLO artifact compiles once per power-of-two shape);
2. single-request closed-loop baseline (concurrency 1) — the
   no-batching reference point;
3. a deadline sweep at fixed concurrency: throughput, p50/p99, and
   the measured mean batch occupancy per flush (from the
   ``edl_tpu_serving_batch_occupancy`` histogram);
4. scrape ``/metrics`` over HTTP and record which
   ``edl_tpu_serving_*`` families are live.

Writes ``BENCH_SERVING.json`` (override with --out) and prints one
summary line with the best batched-vs-single speedup.

Usage: python bench_serving.py [--requests N] [--concurrency C]
       [--deadlines 0,2,5,10] [--out BENCH_SERVING.json]
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

import numpy as np

_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

FEATURE_DIM = 64
# Wide enough that per-call predict cost dominates the HTTP handler
# cost (the regime batching exists for): bs=1 ~1.2ms vs ~0.14ms/ex
# amortized at bs=16 on the 2-core CI host.
HIDDEN = 1024
CLASSES = 10


def _spawn_load(addr: str, requests: int, processes: int,
                threads_per: int, warmup: int = 2) -> dict:
    """Closed-loop load from SEPARATE client processes (the server
    process must not share its GIL with the generator — in-process
    client threads throttle the very handler threads they measure),
    aggregated into one run_load-shaped dict. serve_client imports
    only numpy+msgpack, so client startup is cheap."""
    per = max(1, requests // processes)
    cmd_base = [
        sys.executable, os.path.join(_ROOT, "tools", "serve_client.py"),
        "--addr", addr, "--requests", str(per),
        "--concurrency", str(threads_per),
        "--warmup", str(warmup), "--dump-latencies",
    ]
    procs = [
        subprocess.Popen(
            cmd_base, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, cwd=_ROOT,
        )
        for _ in range(processes)
    ]
    outputs = []
    for proc in procs:
        out, _ = proc.communicate(timeout=600)
        if proc.returncode:
            raise RuntimeError(
                f"serve_client exited {proc.returncode}"
            )
        outputs.append(json.loads(out))
    latencies = [v for o in outputs for v in o["latencies_ms"]]
    ok = sum(o["ok"] for o in outputs)
    elapsed = max(o["elapsed_s"] for o in outputs)
    statuses = {}
    for o in outputs:
        for code, count in o["statuses"].items():
            statuses[code] = statuses.get(code, 0) + count
    return {
        "requests": per * processes,
        "client_processes": processes,
        "threads_per_process": threads_per,
        "elapsed_s": round(elapsed, 4),
        "ok": ok,
        "statuses": statuses,
        "throughput_rps": round(ok / elapsed, 2) if elapsed else 0.0,
        "p50_ms": round(float(np.percentile(latencies, 50)), 3)
        if latencies else 0.0,
        "p99_ms": round(float(np.percentile(latencies, 99)), 3)
        if latencies else 0.0,
    }


def _build_bundle(tmpdir: str) -> str:
    import flax.linen as nn
    import optax

    from elasticdl_tpu.core.train_state import init_train_state
    from elasticdl_tpu.serving.export import export_serving_bundle

    class Mlp(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            x = nn.relu(nn.Dense(HIDDEN)(x))
            x = nn.relu(nn.Dense(HIDDEN)(x))
            return nn.Dense(CLASSES)(x)

    model = Mlp()
    batch = {
        "features": np.random.RandomState(0)
        .rand(8, FEATURE_DIM).astype(np.float32),
        "labels": np.zeros((8,), np.int32),
        "mask": np.ones((8,), np.float32),
    }
    state = init_train_state(model, optax.sgd(0.1), batch, seed=0)
    bundle = os.path.join(tmpdir, "v1")
    export_serving_bundle(
        bundle, model, state, batch_example=batch,
        model_def="bench_serving.Mlp",
    )
    return bundle


def _occupancy(registry) -> tuple:
    """(sum, count) of the batch-occupancy histogram right now."""
    for family in registry.snapshot()["families"]:
        if family["name"] == "edl_tpu_serving_batch_occupancy":
            series = family["series"]
            if series:
                return series[0]["sum"], series[0]["count"]
    return 0.0, 0


def _scrape_families(addr: str):
    with urllib.request.urlopen(f"http://{addr}/metrics") as resp:
        text = resp.read().decode("utf-8")
    return sorted({
        line.split()[2]
        for line in text.splitlines()
        if line.startswith("# TYPE edl_tpu_serving")
    })


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("bench_serving")
    parser.add_argument("--requests", type=int, default=600)
    parser.add_argument(
        "--concurrency", type=int, default=8,
        help="total in-flight requests (client procs x threads); "
             "past ~8 on a small host the clients' own CPU starves "
             "the server they measure",
    )
    parser.add_argument("--deadlines", default="0,2,5,10",
                        help="comma list of batch deadlines (ms)")
    parser.add_argument("--max_batch_size", type=int, default=64)
    parser.add_argument("--out", default="BENCH_SERVING.json")
    args = parser.parse_args(argv)

    from elasticdl_tpu.observability import default_registry
    from elasticdl_tpu.serving.model_store import ModelStore
    from elasticdl_tpu.serving.server import InferenceServer

    registry = default_registry()
    deadlines = [float(d) for d in args.deadlines.split(",")]
    processes = max(1, args.concurrency // 4)
    threads_per = max(1, args.concurrency // processes)
    result = {
        "config": {
            "requests": args.requests,
            "concurrency": args.concurrency,
            "client_processes": processes,
            "threads_per_process": threads_per,
            "max_batch_size": args.max_batch_size,
            "model": f"MLP {FEATURE_DIM}-{HIDDEN}-{HIDDEN}-{CLASSES}",
        },
    }
    with tempfile.TemporaryDirectory(prefix="bench_serving_") as td:
        _build_bundle(td)
        store = ModelStore(td, poll_seconds=3600)
        store.load_initial()

        # Warm every bucket shape once so the sweep never pays a
        # compile inside a timed window.
        model = store.current()
        bucket = 1
        while bucket <= args.max_batch_size:
            model.predict(np.zeros((bucket, FEATURE_DIM), np.float32))
            bucket *= 2

        server = InferenceServer(
            store, max_batch_size=args.max_batch_size,
            batch_deadline_ms=deadlines[0], port=0,
        ).start()
        addr = f"localhost:{server.port}"

        # Single-request baseline: one in-flight request -> every
        # batch has occupancy 1 regardless of deadline. Measured
        # TWICE (before and after the sweep) and the FASTER run is
        # the speedup denominator — host noise must make the batched
        # claim conservative, not inflate it.
        single = _spawn_load(
            addr, requests=min(args.requests, 200), processes=1,
            threads_per=1,
        )
        result["single_request"] = single
        print(f"single-request: {single['throughput_rps']} req/s "
              f"p50={single['p50_ms']}ms p99={single['p99_ms']}ms",
              flush=True)

        sweep = []
        for deadline in deadlines:
            server.predictor.batch_deadline = deadline / 1e3
            occ_sum0, occ_count0 = _occupancy(registry)
            run = _spawn_load(
                addr, requests=args.requests, processes=processes,
                threads_per=threads_per,
            )
            occ_sum1, occ_count1 = _occupancy(registry)
            flushes = occ_count1 - occ_count0
            occupancy = (
                (occ_sum1 - occ_sum0) / flushes if flushes else 0.0
            )
            run.update({
                "batch_deadline_ms": deadline,
                "mean_batch_occupancy": round(occupancy, 2),
            })
            sweep.append(run)
            print(
                f"deadline={deadline}ms: {run['throughput_rps']} req/s "
                f"occupancy={run['mean_batch_occupancy']} "
                f"p50={run['p50_ms']}ms p99={run['p99_ms']}ms",
                flush=True,
            )
        result["metrics_families"] = _scrape_families(addr)
        # Restore the first deadline: a lone request must not sit out
        # the LAST sweep value's window (that would deflate the
        # baseline and flatter the speedup).
        server.predictor.batch_deadline = deadlines[0] / 1e3
        single2 = _spawn_load(
            addr, requests=min(args.requests, 200), processes=1,
            threads_per=1,
        )
        result["single_request_recheck"] = single2
        server.stop()

    baseline = max(
        single["throughput_rps"], single2["throughput_rps"], 1e-9
    )
    result["single_baseline_rps"] = baseline
    for run in sweep:
        run["speedup_vs_single"] = round(
            run["throughput_rps"] / baseline, 2
        )
    result["deadline_sweep"] = sweep

    batched = [r for r in sweep if r["mean_batch_occupancy"] > 1.0]
    best = max(
        batched, key=lambda r: r["speedup_vs_single"], default=None
    )
    result["best"] = best
    result["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    if best is None:
        print("BENCH_SERVING: no batched regime reached (occupancy "
              "<= 1 everywhere)")
        return 1
    print(
        "BENCH_SERVING: best "
        f"{best['speedup_vs_single']}x single-request throughput at "
        f"deadline={best['batch_deadline_ms']}ms "
        f"(occupancy {best['mean_batch_occupancy']}, "
        f"p99 {best['p99_ms']}ms); families="
        f"{len(result['metrics_families'])}; artifact -> {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
