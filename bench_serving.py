"""Serving-plane benchmark: latency/throughput vs. batch deadline.

Answers the question the batching knobs exist for: what does adaptive
micro-batching buy over single-request serving, and what does the
flush deadline cost in p50/p99? The harness is fully in-process (an
``InferenceServer`` on an ephemeral port over a freshly exported
bundle) so the artifact measures the serving plane, not a network.

Phases:
1. export a dense MLP bundle and warm every batch bucket (the
   StableHLO artifact compiles once per power-of-two shape);
2. single-request closed-loop baseline (concurrency 1) — the
   no-batching reference point;
3. a deadline sweep at fixed concurrency: throughput, p50/p99, and
   the measured mean batch occupancy per flush (from the
   ``edl_tpu_serving_batch_occupancy`` histogram);
4. scrape ``/metrics`` over HTTP and record which
   ``edl_tpu_serving_*`` families are live.

With ``--router`` (ISSUE 6) two fleet sections run as well, over a
DeepFM host-tier bundle served through a LIVE in-process row service:

5. fleet points: N ``serve`` replica SUBPROCESSES (each with a
   hot-row cache) behind an in-process ``serving/router.py`` — fleet
   throughput, per-replica cache hit rate, hedge fire/win counts for
   each N in --replicas, vs a single-replica single-request baseline;
6. cache trace evidence: one in-process replica with the flight
   recorder on, cold (no cache) vs warm (cache): per-phase p99
   breakdown of request spans + ``row_resolve`` p99 +
   ``rpc/pull_rows`` span counts — showing the warm cache removes
   the row-service round trip from the p99 critical path.

Writes ``BENCH_SERVING.json`` (override with --out) and prints one
summary line with the best batched-vs-single speedup.

Usage: python bench_serving.py [--requests N] [--concurrency C]
       [--deadlines 0,2,5,10] [--router] [--replicas 1,2,4]
       [--out BENCH_SERVING.json]
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

import numpy as np

_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_ROOT, "tools"))

FEATURE_DIM = 64
# Wide enough that per-call predict cost dominates the HTTP handler
# cost (the regime batching exists for): bs=1 ~1.2ms vs ~0.14ms/ex
# amortized at bs=16 on the 2-core CI host.
HIDDEN = 1024
CLASSES = 10


def _spawn_load(addr: str, requests: int, processes: int,
                threads_per: int, warmup: int = 2,
                payload_pool: int = 1) -> dict:
    """Closed-loop load from SEPARATE client processes (the server
    process must not share its GIL with the generator — in-process
    client threads throttle the very handler threads they measure),
    aggregated into one run_load-shaped dict. serve_client imports
    only numpy+msgpack, so client startup is cheap. ``payload_pool``:
    distinct payloads cycled per process (deterministic per process
    index), so a serving-side row cache sees realistic id diversity
    instead of one repeated request."""
    per = max(1, requests // processes)

    def cmd(i):
        return [
            sys.executable,
            os.path.join(_ROOT, "tools", "serve_client.py"),
            "--addr", addr, "--requests", str(per),
            "--concurrency", str(threads_per),
            "--warmup", str(warmup), "--dump-latencies",
            "--seed", str(31 * i),
            "--payload_pool", str(payload_pool),
        ]

    procs = [
        subprocess.Popen(
            cmd(i), stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, cwd=_ROOT,
        )
        for i in range(processes)
    ]
    outputs = []
    for proc in procs:
        out, _ = proc.communicate(timeout=600)
        if proc.returncode:
            raise RuntimeError(
                f"serve_client exited {proc.returncode}"
            )
        outputs.append(json.loads(out))
    latencies = [v for o in outputs for v in o["latencies_ms"]]
    ok = sum(o["ok"] for o in outputs)
    elapsed = max(o["elapsed_s"] for o in outputs)
    statuses = {}
    for o in outputs:
        for code, count in o["statuses"].items():
            statuses[code] = statuses.get(code, 0) + count
    return {
        "requests": per * processes,
        "client_processes": processes,
        "threads_per_process": threads_per,
        "elapsed_s": round(elapsed, 4),
        "ok": ok,
        "statuses": statuses,
        "throughput_rps": round(ok / elapsed, 2) if elapsed else 0.0,
        "p50_ms": round(float(np.percentile(latencies, 50)), 3)
        if latencies else 0.0,
        "p99_ms": round(float(np.percentile(latencies, 99)), 3)
        if latencies else 0.0,
    }


def _build_bundle(tmpdir: str) -> str:
    import flax.linen as nn
    import optax

    from elasticdl_tpu.core.train_state import init_train_state
    from elasticdl_tpu.serving.export import export_serving_bundle

    class Mlp(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            x = nn.relu(nn.Dense(HIDDEN)(x))
            x = nn.relu(nn.Dense(HIDDEN)(x))
            return nn.Dense(CLASSES)(x)

    model = Mlp()
    batch = {
        "features": np.random.RandomState(0)
        .rand(8, FEATURE_DIM).astype(np.float32),
        "labels": np.zeros((8,), np.int32),
        "mask": np.ones((8,), np.float32),
    }
    state = init_train_state(model, optax.sgd(0.1), batch, seed=0)
    bundle = os.path.join(tmpdir, "v1")
    export_serving_bundle(
        bundle, model, state, batch_example=batch,
        model_def="bench_serving.Mlp",
    )
    return bundle


def _occupancy(registry) -> tuple:
    """(sum, count) of the batch-occupancy histogram right now."""
    for family in registry.snapshot()["families"]:
        if family["name"] == "edl_tpu_serving_batch_occupancy":
            series = family["series"]
            if series:
                return series[0]["sum"], series[0]["count"]
    return 0.0, 0


def _scrape_families(addr: str):
    with urllib.request.urlopen(f"http://{addr}/metrics") as resp:
        text = resp.read().decode("utf-8")
    return sorted({
        line.split()[2]
        for line in text.splitlines()
        if line.startswith("# TYPE edl_tpu_serving")
    })


def _scrape_counter_totals(addr: str, names) -> dict:
    """Sum each named counter family's series from a /metrics scrape."""
    with urllib.request.urlopen(
        f"http://{addr}/metrics", timeout=10
    ) as resp:
        text = resp.read().decode("utf-8")
    totals = {name: 0.0 for name in names}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        metric, _, value = line.rpartition(" ")
        family = metric.split("{", 1)[0]
        if family in totals:
            try:
                totals[family] += float(value)
            except ValueError:
                pass
    return totals


# ---- fleet mode (ISSUE 6) --------------------------------------------


def _free_port() -> int:
    import socket

    sock = socket.socket()
    sock.bind(("", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def _wait_healthy(addr: str, timeout: float = 120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                f"http://{addr}/healthz", timeout=2
            ) as resp:
                if resp.status == 200:
                    return
        except Exception:
            pass
        time.sleep(0.25)
    raise RuntimeError(f"replica {addr} never became healthy")


_FORCE_CPU = (
    "import jax; jax.config.update('jax_platforms', 'cpu'); "
    "import sys; from elasticdl_tpu.serving.server import main; "
    "sys.exit(main(sys.argv[1:]))"
)

_FORCE_CPU_ROWSVC = (
    "import jax; jax.config.update('jax_platforms', 'cpu'); "
    "import sys; from elasticdl_tpu.embedding.row_service import main; "
    "sys.exit(main(sys.argv[1:]))"
)


def _spawn_row_service():
    """The deepfm host row plane as its OWN process — sharing the
    bench process's GIL with the router would throttle both."""
    import socket

    from elasticdl_tpu.testing.data import model_zoo_dir

    port = _free_port()
    proc = subprocess.Popen(
        [
            sys.executable, "-c", _FORCE_CPU_ROWSVC,
            "--model_zoo", model_zoo_dir(),
            "--model_def", "deepfm.deepfm_host.custom_model",
            "--addr", f"localhost:{port}",
        ],
        cwd=_ROOT, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("localhost", port),
                                     timeout=1).close()
            return proc, f"localhost:{port}"
        except OSError:
            if proc.poll() is not None:
                raise RuntimeError("row service process died")
            time.sleep(0.25)
    proc.kill()
    raise RuntimeError("row service never came up")


def _spawn_replicas(bundle: str, row_addr: str, n: int,
                    max_batch: int, cache_rows: int):
    """N real ``serve`` processes (the deployment unit) — separate
    processes, NOT threads: a fleet bench through one GIL would
    measure contention the production fleet doesn't have. Each
    replica is PINNED to one core (taskset, round-robin): colocated
    replicas otherwise thrash each other's XLA thread pools — the
    same one-core-per-replica cpuset a production pod gets."""
    import shutil

    pin = shutil.which("taskset") is not None
    cores = max(1, os.cpu_count() or 1)
    replicas = []
    for i in range(n):
        port = _free_port()
        cmd = [
            sys.executable, "-c", _FORCE_CPU,
            "--model_dir", bundle,
            "--row_service_addr", row_addr,
            "--port", str(port),
            "--max_batch_size", str(max_batch),
            "--batch_deadline_ms", "5",
            "--poll_seconds", "3600",
            "--row_cache_capacity", str(cache_rows),
            "--row_cache_version_check_ms", "50",
        ]
        if pin:
            cmd = ["taskset", "-c", str(i % cores)] + cmd
        proc = subprocess.Popen(
            cmd, cwd=_ROOT, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        replicas.append((proc, f"localhost:{port}"))
    for _, addr in replicas:
        _wait_healthy(addr)
    return replicas


def _stop_replicas(replicas):
    import signal as _signal

    for proc, _ in replicas:
        proc.send_signal(_signal.SIGTERM)
    for proc, _ in replicas:
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()


_CACHE_COUNTERS = (
    "edl_tpu_serving_row_cache_hits_total",
    "edl_tpu_serving_row_cache_misses_total",
)


def _warm_replicas(replicas, concurrency: int):
    """TWO warm passes per replica at MEASUREMENT concurrency: the
    batch-polymorphic sparse artifact compiles one program per
    (batch bucket, row bucket) pair, and the pairs reached depend on
    occupancy — warming at low concurrency leaves the saturated
    shapes cold and the timed window then measures XLA compiles
    (4.4x observed error on the 2-core host). The second pass runs
    over already-warm shapes and fills the hot-row cache."""
    import threading as _threading

    def warm(addr):
        # Until-stable, not fixed-pass: with several replicas
        # compiling at once on a small host, two passes can end with
        # shapes still cold (observed: a 5x-slow "measured" window
        # that was really XLA compile time).
        last = 0.0
        for _ in range(6):
            run = _spawn_load(
                addr, requests=max(160, 16 * concurrency),
                processes=1, threads_per=concurrency,
                payload_pool=8,
            )
            rps = run["throughput_rps"]
            if last and rps < last * 1.15:
                break
            last = rps

    threads = [
        _threading.Thread(target=warm, args=(addr,))
        for _, addr in replicas
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def _drive_direct(replicas, requests: int, concurrency: int) -> dict:
    """Aggregate fleet capacity: one client process per replica,
    total offered concurrency split evenly — the L4-load-balancer
    deployment shape (the in-process router hop is measured
    separately as via_router)."""
    import threading as _threading

    n = len(replicas)
    results = [None] * n
    per_conc = max(2, concurrency // n)

    def drive(i, addr):
        results[i] = _spawn_load(
            addr, requests=requests // n, processes=1,
            threads_per=per_conc, payload_pool=8,
        )

    threads = [
        _threading.Thread(target=drive, args=(i, addr))
        for i, (_, addr) in enumerate(replicas)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ok = sum(r["ok"] for r in results)
    elapsed = max(r["elapsed_s"] for r in results)
    lat = {
        "p50_ms": round(float(np.median(
            [r["p50_ms"] for r in results]
        )), 3),
        "p99_ms": round(max(r["p99_ms"] for r in results), 3),
    }
    statuses = {}
    for r in results:
        for code, count in r["statuses"].items():
            statuses[code] = statuses.get(code, 0) + count
    return {
        "requests": requests // n * n,
        "client_processes": n,
        "threads_per_process": per_conc,
        "elapsed_s": round(elapsed, 4),
        "ok": ok,
        "statuses": statuses,
        "throughput_rps": round(ok / elapsed, 2) if elapsed else 0.0,
        **lat,
    }


def _fleet_cache_rate(replicas) -> float:
    hits = misses = 0.0
    for _, addr in replicas:
        totals = _scrape_counter_totals(addr, _CACHE_COUNTERS)
        hits += totals[_CACHE_COUNTERS[0]]
        misses += totals[_CACHE_COUNTERS[1]]
    return round(hits / (hits + misses), 4) if hits + misses else 0.0


def _bench_fleet(bundle: str, row_addr: str, sizes, requests: int,
                 concurrency: int, max_batch: int) -> dict:
    """Fleet points: N pinned replica processes per point, recording
    direct aggregate throughput, via-router throughput, cache hit
    rate, and hedge fire/win counts."""
    from elasticdl_tpu.observability import MetricsRegistry
    from elasticdl_tpu.serving.router import RouterServer

    out = {"requests": requests, "concurrency": concurrency,
           "points": []}
    baseline = None
    for n in sizes:
        replicas = _spawn_replicas(
            bundle, row_addr, n, max_batch, cache_rows=8192
        )
        try:
            _warm_replicas(replicas, max(2, concurrency // n))
            if baseline is None:
                # Single-request single-replica reference (occupancy
                # 1, no router): the PR 2 serving shape this fleet is
                # measured against.
                baseline = _spawn_load(
                    replicas[0][1], requests=min(requests, 200),
                    processes=1, threads_per=1, payload_pool=8,
                )
                out["single_replica_baseline"] = baseline
                print(
                    "fleet baseline (1 replica, concurrency 1): "
                    f"{baseline['throughput_rps']} req/s",
                    flush=True,
                )
            run = _drive_direct(replicas, requests, concurrency)
            run["replicas"] = n
            run["cache_hit_rate"] = _fleet_cache_rate(replicas)
            run["speedup_vs_single_replica"] = round(
                run["throughput_rps"]
                / max(baseline["throughput_rps"], 1e-9), 2
            )
            # Via-router pass: the same fleet behind serving/router.py
            # (policy + hedging + shed tiers). Shy hedge floor: on a
            # saturated small host an eager hedge would double load
            # exactly when there is no headroom.
            registry = MetricsRegistry()
            router = RouterServer(
                [addr for _, addr in replicas], port=0,
                metrics_registry=registry,
                hedge_min_ms=200, hedge_max_ms=2000,
                replica_timeout=30.0,
            ).start()
            try:
                via = _spawn_load(
                    f"localhost:{router.port}", requests=requests,
                    processes=max(1, concurrency // 8),
                    threads_per=min(concurrency, 8),
                    payload_pool=8,
                )
            finally:
                router.drain(grace=10.0)
            hedges = {}
            for family in registry.snapshot()["families"]:
                if family["name"] == "edl_tpu_router_hedges_total":
                    hedges = {
                        s["labels"][0]: s["value"]
                        for s in family["series"]
                    }
            run["via_router"] = {
                "throughput_rps": via["throughput_rps"],
                "p50_ms": via["p50_ms"],
                "p99_ms": via["p99_ms"],
                "statuses": via["statuses"],
                "hedges_fired": hedges.get("fired", 0.0),
                "hedges_won": hedges.get("won", 0.0),
                "hedges_cancelled": hedges.get("cancelled", 0.0),
            }
            out["points"].append(run)
            print(
                f"fleet n={n}: direct {run['throughput_rps']} req/s "
                f"({run['speedup_vs_single_replica']}x baseline, "
                f"p99 {run['p99_ms']}ms), via router "
                f"{via['throughput_rps']} req/s, "
                f"cache_hit={run['cache_hit_rate']}, hedges "
                f"{int(run['via_router']['hedges_fired'])} fired / "
                f"{int(run['via_router']['hedges_won'])} won",
                flush=True,
            )
        finally:
            _stop_replicas(replicas)
    points = {p["replicas"]: p for p in out["points"]}
    if 1 in points and max(points) > 1:
        top = points[max(points)]
        out["fleet_scaling_vs_one_replica"] = round(
            top["throughput_rps"]
            / max(points[1]["throughput_rps"], 1e-9), 2
        )
    return out


def _percentile_ms(durs, q) -> float:
    return round(
        float(np.percentile(np.asarray(durs), q)) * 1e3, 3
    ) if durs else 0.0


def _trace_section(spans) -> dict:
    """Reduce one run's recorder spans into the cache-evidence view:
    p99 per-phase breakdown of request spans + row_resolve /
    rpc/pull_rows stats."""
    from elasticdl_tpu.observability.critical_path import (
        build_index,
        phase_breakdown,
    )

    by_name = {}
    for span in spans:
        by_name.setdefault(span["name"], []).append(span)
    requests = by_name.get("request", [])
    _, children = build_index(spans)
    section = {
        "request_spans": len(requests),
        "row_resolve_p99_ms": _percentile_ms(
            [s["dur"] for s in by_name.get("row_resolve", [])], 99
        ),
        "pull_rows_spans": len(by_name.get("rpc/pull_rows", [])),
        "pull_rows_total_ms": round(
            sum(s["dur"] for s in by_name.get("rpc/pull_rows", []))
            * 1e3, 3,
        ),
    }
    if requests:
        ordered = sorted(requests, key=lambda s: s["dur"])
        p99_span = ordered[min(
            len(ordered) - 1, int(0.99 * len(ordered))
        )]
        section["request_p99_ms"] = round(p99_span["dur"] * 1e3, 3)
        section["request_p99_phases_ms"] = {
            name: round(dur * 1e3, 3)
            for name, dur in sorted(
                phase_breakdown(p99_span, children).items()
            )
        }
    return section


def _bench_cache_trace(bundle: str, row_addr: str,
                       requests: int) -> dict:
    """Trace-plane evidence (acceptance): cold (no cache) vs warm
    (hot-row cache) single replica, flight recorder on — the warm run
    must show the row-service round trip gone from the p99 path."""
    from elasticdl_tpu.observability import (
        FlightRecorder,
        MetricsRegistry,
        tracing,
    )
    from elasticdl_tpu.serving.model_store import ModelStore
    from elasticdl_tpu.serving.server import InferenceServer

    out = {}
    for mode, cache_rows in (("cold", 0), ("warm", 8192)):
        registry = MetricsRegistry()
        store = ModelStore(
            bundle, row_service_addr=row_addr, poll_seconds=3600,
            row_cache_capacity=cache_rows,
            row_cache_version_check_secs=0.05,
            metrics_registry=registry,
        )
        store.load_initial()
        server = InferenceServer(
            store, max_batch_size=16, batch_deadline_ms=2.0, port=0,
            metrics_registry=registry,
        ).start()
        try:
            addr = f"localhost:{server.port}"
            # Unrecorded warmup at MEASUREMENT concurrency, twice:
            # the saturated (batch bucket, row bucket) shapes must
            # all be compiled before the recorder goes on, and for
            # the warm mode the cache must be filled (the claim under
            # test is the WARM hit path, not the fill).
            for _ in range(2):
                _spawn_load(addr, requests=200, processes=1,
                            threads_per=4, payload_pool=8)
            tracing.set_process_role("serving")
            tracing.install_recorder(FlightRecorder(65536))
            try:
                run = _spawn_load(
                    addr, requests=requests, processes=1,
                    threads_per=4, payload_pool=8,
                )
                spans = tracing.recorder_spans()
            finally:
                tracing.uninstall_recorder()
            section = _trace_section(spans)
            section.update({
                "throughput_rps": run["throughput_rps"],
                "p50_ms": run["p50_ms"],
                "p99_ms": run["p99_ms"],
            })
            totals = _scrape_counter_totals(addr, _CACHE_COUNTERS)
            hits = totals[_CACHE_COUNTERS[0]]
            misses = totals[_CACHE_COUNTERS[1]]
            section["cache_hit_rate"] = round(
                hits / (hits + misses), 4
            ) if hits + misses else 0.0
            out[mode] = section
            print(
                f"cache {mode}: p99={section['p99_ms']}ms "
                f"row_resolve_p99="
                f"{section['row_resolve_p99_ms']}ms "
                f"pull_rows_spans={section['pull_rows_spans']} "
                f"hit_rate={section['cache_hit_rate']}",
                flush=True,
            )
        finally:
            server.stop()
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("bench_serving")
    parser.add_argument("--requests", type=int, default=600)
    parser.add_argument(
        "--concurrency", type=int, default=8,
        help="total in-flight requests (client procs x threads); "
             "past ~8 on a small host the clients' own CPU starves "
             "the server they measure",
    )
    parser.add_argument("--deadlines", default="0,2,5,10",
                        help="comma list of batch deadlines (ms)")
    parser.add_argument("--max_batch_size", type=int, default=64)
    parser.add_argument(
        "--router", action="store_true",
        help="Also bench the serving fleet (ISSUE 6): router + N "
             "replica processes over a live row service, plus the "
             "cold/warm hot-row-cache trace evidence",
    )
    parser.add_argument(
        "--replicas", default="1,2,4",
        help="Comma list of fleet sizes for --router mode",
    )
    parser.add_argument("--fleet_requests", type=int, default=600)
    parser.add_argument(
        "--fleet_concurrency", type=int, default=16,
        help="Total in-flight requests during fleet points",
    )
    parser.add_argument("--out", default="BENCH_SERVING.json")
    args = parser.parse_args(argv)

    from elasticdl_tpu.observability import default_registry
    from elasticdl_tpu.serving.model_store import ModelStore
    from elasticdl_tpu.serving.server import InferenceServer

    registry = default_registry()
    deadlines = [float(d) for d in args.deadlines.split(",")]
    processes = max(1, args.concurrency // 4)
    threads_per = max(1, args.concurrency // processes)
    result = {
        "config": {
            "requests": args.requests,
            "concurrency": args.concurrency,
            "client_processes": processes,
            "threads_per_process": threads_per,
            "max_batch_size": args.max_batch_size,
            "model": f"MLP {FEATURE_DIM}-{HIDDEN}-{HIDDEN}-{CLASSES}",
        },
    }
    with tempfile.TemporaryDirectory(prefix="bench_serving_") as td:
        _build_bundle(td)
        store = ModelStore(td, poll_seconds=3600)
        store.load_initial()

        # Warm every bucket shape once so the sweep never pays a
        # compile inside a timed window.
        model = store.current()
        bucket = 1
        while bucket <= args.max_batch_size:
            model.predict(np.zeros((bucket, FEATURE_DIM), np.float32))
            bucket *= 2

        server = InferenceServer(
            store, max_batch_size=args.max_batch_size,
            batch_deadline_ms=deadlines[0], port=0,
        ).start()
        addr = f"localhost:{server.port}"

        # Single-request baseline: one in-flight request -> every
        # batch has occupancy 1 regardless of deadline. Measured
        # TWICE (before and after the sweep) and the FASTER run is
        # the speedup denominator — host noise must make the batched
        # claim conservative, not inflate it.
        single = _spawn_load(
            addr, requests=min(args.requests, 200), processes=1,
            threads_per=1,
        )
        result["single_request"] = single
        print(f"single-request: {single['throughput_rps']} req/s "
              f"p50={single['p50_ms']}ms p99={single['p99_ms']}ms",
              flush=True)

        sweep = []
        for deadline in deadlines:
            server.predictor.batch_deadline = deadline / 1e3
            occ_sum0, occ_count0 = _occupancy(registry)
            run = _spawn_load(
                addr, requests=args.requests, processes=processes,
                threads_per=threads_per,
            )
            occ_sum1, occ_count1 = _occupancy(registry)
            flushes = occ_count1 - occ_count0
            occupancy = (
                (occ_sum1 - occ_sum0) / flushes if flushes else 0.0
            )
            run.update({
                "batch_deadline_ms": deadline,
                "mean_batch_occupancy": round(occupancy, 2),
            })
            sweep.append(run)
            print(
                f"deadline={deadline}ms: {run['throughput_rps']} req/s "
                f"occupancy={run['mean_batch_occupancy']} "
                f"p50={run['p50_ms']}ms p99={run['p99_ms']}ms",
                flush=True,
            )
        result["metrics_families"] = _scrape_families(addr)
        # Restore the first deadline: a lone request must not sit out
        # the LAST sweep value's window (that would deflate the
        # baseline and flatter the speedup).
        server.predictor.batch_deadline = deadlines[0] / 1e3
        single2 = _spawn_load(
            addr, requests=min(args.requests, 200), processes=1,
            threads_per=1,
        )
        result["single_request_recheck"] = single2
        server.stop()

    baseline = max(
        single["throughput_rps"], single2["throughput_rps"], 1e-9
    )
    result["single_baseline_rps"] = baseline
    for run in sweep:
        run["speedup_vs_single"] = round(
            run["throughput_rps"] / baseline, 2
        )
    result["deadline_sweep"] = sweep

    batched = [r for r in sweep if r["mean_batch_occupancy"] > 1.0]
    best = max(
        batched, key=lambda r: r["speedup_vs_single"], default=None
    )
    result["best"] = best

    if args.router:
        # Fleet sections run over a DeepFM host-tier bundle with a
        # LIVE row-service process — the sparse serving shape the
        # hot-row cache and the router exist for.
        from elasticdl_tpu.chaos.serving_drill import (
            export_sparse_bundle,
        )

        fleet_tmp = tempfile.mkdtemp(prefix="bench_fleet_")
        bundle, _ = export_sparse_bundle(fleet_tmp, seed=0)
        row_proc, row_addr = _spawn_row_service()
        try:
            sizes = [
                int(s) for s in args.replicas.split(",") if s.strip()
            ]
            result["fleet"] = _bench_fleet(
                bundle, row_addr, sizes,
                requests=args.fleet_requests,
                concurrency=args.fleet_concurrency,
                # 16, not 64: every extra batch bucket is another
                # (batch, row-bucket) XLA compile per replica, and
                # per-replica occupancy can't exceed the split
                # concurrency anyway.
                max_batch=16,
            )
            result["cache_trace_evidence"] = _bench_cache_trace(
                bundle, row_addr, requests=300,
            )
        finally:
            row_proc.terminate()
            try:
                row_proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                row_proc.kill()

    result["timestamp"] = time.strftime("%Y-%m-%dT%H:%M:%S")
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    if best is None:
        print("BENCH_SERVING: no batched regime reached (occupancy "
              "<= 1 everywhere)")
        return 1
    print(
        "BENCH_SERVING: best "
        f"{best['speedup_vs_single']}x single-request throughput at "
        f"deadline={best['batch_deadline_ms']}ms "
        f"(occupancy {best['mean_batch_occupancy']}, "
        f"p99 {best['p99_ms']}ms); families="
        f"{len(result['metrics_families'])}; artifact -> {args.out}"
    )
    if "fleet" in result and result["fleet"]["points"]:
        top = max(
            result["fleet"]["points"], key=lambda p: p["replicas"]
        )
        via = top.get("via_router", {})
        print(
            f"BENCH_SERVING fleet: {top['replicas']} replicas -> "
            f"{top['throughput_rps']} req/s "
            f"({top['speedup_vs_single_replica']}x single-replica "
            f"baseline), cache_hit={top['cache_hit_rate']}, "
            f"hedges fired/won "
            f"{int(via.get('hedges_fired', 0))}/"
            f"{int(via.get('hedges_won', 0))}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
