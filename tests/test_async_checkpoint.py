"""Async checkpoint writes: ordering, flush semantics, deferred errors,
and the snapshot-before-donation guarantee."""

import os
import time

import numpy as np
import optax
import pytest

from elasticdl_tpu.checkpoint import CheckpointHook, CheckpointSaver
from elasticdl_tpu.core.model_spec import get_model_spec
from elasticdl_tpu.core.step import build_train_step
from elasticdl_tpu.core.train_state import init_train_state
from elasticdl_tpu.testing.data import model_zoo_dir


def _state(seed=0):
    spec = get_model_spec(model_zoo_dir(),
                          "mnist.mnist_functional.custom_model")
    rng = np.random.RandomState(seed)
    batch = {
        "features": rng.rand(8, 28, 28).astype(np.float32),
        "labels": rng.randint(0, 10, 8).astype(np.int32),
        "mask": np.ones((8,), np.float32),
    }
    state = init_train_state(spec.model, optax.sgd(0.1), batch, seed=0)
    return spec, state, batch


def test_async_save_lands_after_flush(tmp_path):
    _, state, _ = _state()
    hook = CheckpointHook(str(tmp_path), checkpoint_steps=1,
                          async_save=True)
    state = state.replace(step=state.step + 1)
    assert hook.maybe_save(state)
    hook.flush()
    assert CheckpointSaver(str(tmp_path)).get_valid_latest_version() == 1


def test_save_final_flushes(tmp_path):
    _, state, _ = _state()
    hook = CheckpointHook(str(tmp_path), checkpoint_steps=2,
                          async_save=True)
    state = state.replace(step=state.step + 3)
    assert hook.save_final(state)
    # No explicit flush needed: save_final joined the writer.
    assert CheckpointSaver(str(tmp_path)).get_valid_latest_version() == 3


def test_deferred_write_error_surfaces_on_flush(tmp_path):
    _, state, _ = _state()

    class BrokenSaver:
        def save(self, version, leaves):
            raise IOError("disk full")

    hook = CheckpointHook(checkpoint_steps=1, saver=BrokenSaver(),
                          async_save=True)
    state = state.replace(step=state.step + 1)
    hook.maybe_save(state)
    with pytest.raises(IOError, match="disk full"):
        hook.flush()


def test_snapshot_is_consistent_despite_donation(tmp_path):
    """The device->host copy happens before the next (donating) train
    step mutates buffers: the checkpoint equals the state at save time,
    not whatever the buffers hold later."""
    spec, state, batch = _state()
    hook = CheckpointHook(str(tmp_path), checkpoint_steps=1,
                          async_save=True)
    step = build_train_step(spec.loss)
    state, _ = step(state, batch)
    saved_version = int(state.step)
    want = np.asarray(
        state.params["Dense_0"]["kernel"]
    ).copy()
    hook.maybe_save(state)
    # Donating steps immediately reuse/overwrite the old buffers.
    for _ in range(3):
        state, _ = step(state, batch)
    hook.flush()
    saver = CheckpointSaver(str(tmp_path))
    _, dense, _ = saver.restore(version=saved_version)
    got = dense["params['Dense_0']['kernel']"]
    np.testing.assert_array_equal(got, want)


def test_sync_mode_writes_inline(tmp_path):
    _, state, _ = _state()
    hook = CheckpointHook(str(tmp_path), checkpoint_steps=1,
                          async_save=False)
    state = state.replace(step=state.step + 1)
    assert hook.maybe_save(state)
    # Visible immediately, no flush required.
    assert CheckpointSaver(str(tmp_path)).get_valid_latest_version() == 1