"""Host-tier embedding training, end to end.

The capability the reference delivers with PS pods + gRPC row push/pull
(worker.py:362-391/:570-580, optimizer_wrapper.py:143): train a model
whose embedding table lives OFF-device, rows pulled per batch and row
gradients scattered back through a row optimizer. Here: host RAM table +
bucket-padded device row blocks + jit step differentiating w.r.t. the
row block (embedding/host_engine.py).
"""

import flax.linen as nn
import numpy as np
import optax
import pytest

from elasticdl_tpu.core.train_state import init_train_state
from elasticdl_tpu.embedding.combiner import RaggedIds
from elasticdl_tpu.embedding.host_engine import (
    HostEmbedding,
    HostEmbeddingEngine,
    bucket_size,
    build_host_train_step,
    host_rows_template,
)
from elasticdl_tpu.embedding.optimizer import SGD, HostOptimizerWrapper
from elasticdl_tpu.embedding.table import EmbeddingTable

VOCAB = 1000
DIM = 8
FIELDS = 4


class TinyHostModel(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        emb = HostEmbedding("items", DIM)(features["item_ids"])  # (B,F,D)
        x = emb.reshape((emb.shape[0], -1))
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(1)(x)[..., 0]


def loss_fn(labels, preds, mask):
    import jax.numpy as jnp

    per = optax.sigmoid_binary_cross_entropy(preds, labels.astype(np.float32))
    return (per * mask).sum() / jnp.maximum(mask.sum(), 1)


def make_batch(rng, batch=16):
    ids = rng.randint(0, VOCAB, (batch, FIELDS)).astype(np.int64)
    # Learnable signal: label = parity of the first id.
    labels = (ids[:, 0] % 2).astype(np.int32)
    return {
        "features": {"item_ids": ids},
        "labels": labels,
        "mask": np.ones((batch,), np.float32),
    }


@pytest.fixture
def engine():
    tables = {"items": EmbeddingTable("items", DIM)}
    return HostEmbeddingEngine(
        tables, HostOptimizerWrapper(SGD(lr=0.5)),
        id_keys={"items": "item_ids"},
    )


def test_bucket_size():
    assert bucket_size(1) == 8
    assert bucket_size(8) == 8
    assert bucket_size(9) == 16
    assert bucket_size(100) == 128


def test_prepare_batch_shapes_and_padding(engine):
    rng = np.random.RandomState(0)
    batch = make_batch(rng)
    prepared, host_rows, uniques = engine.prepare_batch(batch)
    uniq, u = uniques["items"]
    rows = host_rows["items"]
    assert rows.shape == (bucket_size(u), DIM)
    assert np.all(rows[u:] == 0.0)  # padding slots
    inv = prepared["features"]["item_ids"]
    assert inv.dtype == np.int32 and inv.shape == (16, FIELDS)
    # Inverse maps back to the original ids.
    assert np.array_equal(uniq[inv], batch["features"]["item_ids"])


def test_end_to_end_training_learns(engine):
    # Small id space so every embedding row gets enough visits to learn
    # the per-id signal (each id must be SEEN to be trained — the whole
    # point of the sparse path).
    rng = np.random.RandomState(1)

    def small_batch():
        b = make_batch(rng, batch=32)
        b["features"]["item_ids"] = b["features"]["item_ids"] % 50
        b["labels"] = (b["features"]["item_ids"][:, 0] % 2).astype(np.int32)
        return b

    init_prepared, _, _ = engine.prepare_batch(small_batch())
    model = TinyHostModel()
    state = init_train_state(model, optax.adam(3e-2), init_prepared, seed=0)
    step = build_host_train_step(
        loss_fn, host_rows_template(model, init_prepared)
    )

    losses = []
    for _ in range(80):
        prepared, host_rows, uniques = engine.prepare_batch(small_batch())
        state, row_grads, metrics = step(state, prepared, host_rows)
        engine.apply_row_grads(
            {k: np.asarray(v) for k, v in row_grads.items()}, uniques
        )
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses[::16]
    # Rows were actually trained into the host table.
    assert engine.tables["items"].num_rows > 0


def test_untouched_rows_keep_lazy_init(engine):
    rng = np.random.RandomState(2)
    batch = make_batch(rng, batch=4)
    prepared, host_rows, uniques = engine.prepare_batch(batch)
    model = TinyHostModel()
    state = init_train_state(model, optax.sgd(0.1), prepared, seed=0)
    step = build_host_train_step(
        loss_fn, host_rows_template(model, prepared)
    )
    state, row_grads, _ = step(state, prepared, host_rows)
    engine.apply_row_grads(
        {k: np.asarray(v) for k, v in row_grads.items()}, uniques
    )
    touched = set(int(i) for i in uniques["items"][0])
    # An untouched id still materializes from the deterministic lazy
    # initializer (reference EmbeddingTable.get:51-62 semantics).
    fresh = next(i for i in range(VOCAB) if i not in touched)
    ref = EmbeddingTable("items", DIM)
    np.testing.assert_array_equal(
        engine.tables["items"].get([fresh]), ref.get([fresh])
    )


def test_ragged_ids_path(engine):
    ragged = RaggedIds.from_lists([[1, 2, 3], [4], []], max_ids=4)

    class RaggedModel(nn.Module):
        @nn.compact
        def __call__(self, features, training=False):
            emb = HostEmbedding("items", DIM, combiner="mean")(
                features["item_ids"]
            )
            return nn.Dense(1)(emb)[..., 0]

    batch = {
        "features": {"item_ids": ragged},
        "labels": np.array([1, 0, 1], np.int32),
        "mask": np.ones((3,), np.float32),
    }
    prepared, host_rows, uniques = engine.prepare_batch(batch)
    inv = prepared["features"]["item_ids"]
    assert isinstance(inv, RaggedIds)
    model = RaggedModel()
    state = init_train_state(model, optax.sgd(0.1), prepared, seed=0)
    step = build_host_train_step(
        loss_fn, host_rows_template(model, prepared)
    )
    state, row_grads, metrics = step(state, prepared, host_rows)
    engine.apply_row_grads(
        {k: np.asarray(v) for k, v in row_grads.items()}, uniques
    )
    assert np.isfinite(float(metrics["loss"]))


def test_prepared_batches_double_buffering(engine):
    rng = np.random.RandomState(3)
    batches = [make_batch(rng) for _ in range(5)]
    with engine.prepared_batches(iter(batches)) as it:
        seen = list(it)
    assert len(seen) == 5
    for i, pb in enumerate(seen):
        assert "items" in pb.host_rows and "items" in pb.uniques
        assert pb.raw is batches[i]


def test_prepared_batches_close_stops_producer(engine):
    rng = np.random.RandomState(5)
    batches = (make_batch(rng) for _ in range(100))
    it = engine.prepared_batches(batches)
    next(iter(it))
    it.close()  # abandoning mid-stream must not leak a blocked thread


def test_duplicate_feature_keys_rejected():
    with pytest.raises(ValueError, match="unique across tables"):
        HostEmbeddingEngine(
            {"a": EmbeddingTable("a", DIM), "b": EmbeddingTable("b", DIM)},
            HostOptimizerWrapper(SGD(lr=0.1)),
            id_keys={"a": "ids", "b": "ids"},
        )


def test_prepared_batches_propagates_errors(engine):
    def gen():
        yield make_batch(np.random.RandomState(4))
        raise RuntimeError("reader died")

    it = engine.prepared_batches(gen())
    next(it)
    with pytest.raises(RuntimeError, match="reader died"):
        for _ in it:
            pass


def test_unknown_table_key_rejected():
    with pytest.raises(ValueError, match="unknown tables"):
        HostEmbeddingEngine(
            {"items": EmbeddingTable("items", DIM)},
            HostOptimizerWrapper(SGD(lr=0.1)),
            id_keys={"typo": "item_ids"},
        )


def _runner_engine(async_apply, table=None, optimizer=None):
    from elasticdl_tpu.embedding.host_engine import HostStepRunner

    tables = {"items": table or EmbeddingTable("items", DIM)}
    engine = HostEmbeddingEngine(
        tables, optimizer or HostOptimizerWrapper(SGD(lr=0.5)),
        id_keys={"items": "item_ids"},
    )
    return HostStepRunner(engine, async_apply=async_apply)


def test_async_apply_matches_sync_exactly():
    """VERDICT r2 #7: async-applied runs must end with bit-identical
    tables to the synchronous path (FIFO single applier; flush is the
    read barrier). Batches use DISJOINT id ranges: on ids shared
    between adjacent batches the async path is one apply behind by
    design (the reference async-PS relaxed window, async_sgd.md) —
    exactness is the contract only where reads don't race writes."""
    batches = []
    for s in range(6):
        b = make_batch(np.random.RandomState(s))
        ids = b["features"]["item_ids"]
        b["features"]["item_ids"] = (ids % 100) + 100 * s
        batches.append(b)
    finals = {}
    for mode in (False, True):
        runner = _runner_engine(async_apply=mode)
        state = runner.init_state(TinyHostModel(), optax.sgd(0.1),
                                  batches[0])
        step = runner.train_step(loss_fn)
        for b in batches:
            state, _ = step(state, b)
        runner.flush()
        finals[mode] = runner.engine.tables["items"].to_arrays()
    np.testing.assert_array_equal(finals[False][0], finals[True][0])
    np.testing.assert_allclose(finals[False][1], finals[True][1],
                               rtol=0, atol=0)


def test_async_apply_overlaps_pull_latency():
    """The measured overlap assertion (VERDICT r2 #7 'Done' criterion):
    with row-service-shaped latency (concurrent-safe store, each pull
    and each push sleeping like an RPC round trip), the pipelined path
    (iter_prepared pull-ahead + async apply) must beat the serial path
    decisively — pulls ride the prefetch thread and pushes ride the
    applier thread, concurrently in flight like the reference Go PS
    serves them."""
    import time

    class SlowTable(EmbeddingTable):
        concurrent_safe = True  # what _RemoteTable declares

        def get(self, ids):
            time.sleep(0.02)
            return super().get(ids)

    class SlowOpt(HostOptimizerWrapper):
        concurrent_safe = True  # what _RemoteOptimizer declares

        def apply_gradients(self, table, ids, grads):
            time.sleep(0.02)
            return super().apply_gradients(table, ids, grads)

    batches = [make_batch(np.random.RandomState(s)) for s in range(8)]

    def run(async_apply, prepared):
        runner = _runner_engine(
            async_apply,
            table=SlowTable("items", DIM),
            optimizer=SlowOpt(SGD(lr=0.5)),
        )
        state = runner.init_state(TinyHostModel(), optax.sgd(0.1),
                                  batches[0])
        step = runner.train_step(loss_fn)
        # Warm the jit caches outside the timed window.
        state, _ = step(state, batches[0])
        runner.flush()
        start = time.perf_counter()
        if prepared:
            it = runner.iter_prepared(iter(batches))
            try:
                for pb in it:
                    state, _ = step(state, pb)
            finally:
                it.close()
        else:
            for b in batches:
                state, _ = step(state, b)
        runner.flush()
        return time.perf_counter() - start

    serial = run(async_apply=False, prepared=False)
    pipelined = run(async_apply=True, prepared=True)
    # Serial pays 8 x (pull 20ms + apply 20ms) >= 320ms of sleeps on
    # the critical path; pipelined keeps only the pulls' steady-state
    # (applies fully hidden, pulls prefetched ahead). Generous margin
    # for CI noise: demand at least a 25% cut.
    assert pipelined < serial * 0.75, (serial, pipelined)


def test_applier_errors_surface_on_flush():
    class BoomOpt(HostOptimizerWrapper):
        def apply_gradients(self, table, ids, grads):
            raise RuntimeError("row service down")

    runner = _runner_engine(True, optimizer=BoomOpt(SGD(lr=0.5)))
    batch = make_batch(np.random.RandomState(0))
    state = runner.init_state(TinyHostModel(), optax.sgd(0.1), batch)
    step = runner.train_step(loss_fn)
    state, _ = step(state, batch)
    with pytest.raises(RuntimeError, match="row service down"):
        runner.flush()


def test_host_tables_snapshot_drains_pending_applies():
    """A checkpoint snapshot taken right after a step must include that
    step's row updates (the _LockedTable flush barrier)."""
    runner = _runner_engine(True)
    batch = make_batch(np.random.RandomState(1))
    state = runner.init_state(TinyHostModel(), optax.sgd(0.1), batch)
    step = runner.train_step(loss_fn)
    state, _ = step(state, batch)
    # No explicit flush: reading through host_tables must drain first.
    ids, rows = runner.host_tables["items"].to_arrays()
    sync = _runner_engine(False)
    state2 = sync.init_state(TinyHostModel(), optax.sgd(0.1), batch)
    step2 = sync.train_step(loss_fn)
    step2(state2, batch)
    ids2, rows2 = sync.engine.tables["items"].to_arrays()
    np.testing.assert_array_equal(np.sort(ids), np.sort(ids2))
