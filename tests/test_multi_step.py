"""Fused multi-step (scan over a task's minibatches) == per-step loop,
plus the worker/mesh production wiring."""

import jax
import numpy as np
import optax

from elasticdl_tpu.core.model_spec import get_model_spec
from elasticdl_tpu.core.step import (
    build_multi_step,
    build_train_step,
    stack_batches,
)
from elasticdl_tpu.core.train_state import init_train_state
from elasticdl_tpu.testing.data import model_zoo_dir


def _batches(n=4, b=16, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {
            "features": rng.rand(b, 28, 28).astype(np.float32),
            "labels": rng.randint(0, 10, b).astype(np.int32),
            "mask": np.ones((b,), np.float32),
        }
        for _ in range(n)
    ]


def test_multi_step_matches_per_step_loop():
    spec = get_model_spec(model_zoo_dir(),
                          "mnist.mnist_functional.custom_model")
    batches = _batches()

    s0 = init_train_state(spec.model, optax.sgd(0.1, momentum=0.9),
                          batches[0], seed=0)
    s1 = init_train_state(spec.model, optax.sgd(0.1, momentum=0.9),
                          batches[0], seed=0)

    step = build_train_step(spec.loss)
    losses0 = []
    for b in batches:
        s0, m = step(s0, b)
        losses0.append(float(m["loss"]))

    multi = build_multi_step(spec.loss)
    s1, metrics = multi(s1, stack_batches(batches))

    np.testing.assert_allclose(
        np.asarray(metrics["loss"]), np.asarray(losses0),
        rtol=1e-4, atol=3e-5,
    )
    assert int(s1.step) == int(s0.step) == 4
    # bf16 forward compute recompiled as a scan body fuses differently,
    # so 4 accumulated applies drift ~1e-3 relative; this asserts
    # semantic equivalence, not bitwise.
    for a, b in zip(jax.tree.leaves(s0.params),
                    jax.tree.leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-3)
    # BatchNorm running stats advanced equivalently too.
    for a, b in zip(jax.tree.leaves(s0.batch_stats),
                    jax.tree.leaves(s1.batch_stats)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-3)


def test_fused_worker_drains_and_learns(tmp_path):
    """--fuse_task_steps through the MiniCluster: job drains, loss drops,
    checkpoints still written at (crossed) intervals."""
    from elasticdl_tpu.checkpoint import CheckpointSaver
    from elasticdl_tpu.testing.cluster import MiniCluster
    from elasticdl_tpu.testing.data import create_mnist_record_file

    train = create_mnist_record_file(str(tmp_path / "t.rec"), 192, seed=1)
    ckpt = str(tmp_path / "ckpt")
    cluster = MiniCluster(
        model_zoo=model_zoo_dir(),
        model_def="mnist.mnist_functional.custom_model",
        training_data=train,
        minibatch_size=16,
        num_minibatches_per_task=3,   # odd: exercises interval crossing
        num_epochs=2,
        checkpoint_dir=ckpt,
        checkpoint_steps=4,
    )
    for worker in cluster.workers:
        worker._fuse_task_steps = True
    results = cluster.run()
    assert cluster.finished
    assert results[0]["trained_batches"] == 24
    assert results[0]["final_version"] == 24
    assert results[0]["final_loss"] < 1.0
    version = CheckpointSaver(ckpt).get_valid_latest_version()
    assert version == 24


def test_fused_mesh_runner_matches_stepwise():
    """MeshRunner.train_multi_step == stepwise mesh training (transformer
    with dp/sp/tp batch rules: place_task shifts specs right one dim)."""
    import importlib.util
    import os

    from elasticdl_tpu.core.step import stack_batches
    from elasticdl_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        transformer_sharding_rules,
    )
    from elasticdl_tpu.parallel import rules as rules_lib
    from elasticdl_tpu.parallel.mesh import make_mesh
    from elasticdl_tpu.parallel.mesh_runner import MeshRunner

    zoo_path = os.path.join(
        model_zoo_dir(), "transformer", "transformer_lm.py"
    )
    zspec = importlib.util.spec_from_file_location("tlm", zoo_path)
    zoo = importlib.util.module_from_spec(zspec)
    zspec.loader.exec_module(zoo)

    cfg = TransformerConfig(
        vocab_size=32, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        max_len=32, compute_dtype=np.float32,
    )
    mesh = make_mesh((2, 2, 2), ("dp", "sp", "tp"),
                     devices=jax.devices()[:8])

    rng = np.random.RandomState(0)

    def lm_batch(seed):
        r = np.random.RandomState(seed)
        start = r.randint(0, 32, (8, 1))
        seq = (start + np.arange(17)[None, :]) % 32
        return {
            "features": seq[:, :-1].astype(np.int32),
            "labels": seq[:, 1:].astype(np.int32),
            "mask": np.ones((8,), np.float32),
        }

    batches = [lm_batch(i) for i in range(3)]

    def build(donate):
        model = TransformerLM(cfg, mesh=mesh)
        runner = MeshRunner(
            mesh=mesh,
            param_rule=rules_lib.regex_param_rule(
                transformer_sharding_rules(), mesh=mesh
            ),
            batch_rule=zoo.batch_sharding_rule,
            donate_state=donate,
        )
        state = runner.init_state(model, optax.adam(1e-2), batches[0],
                                  seed=0)
        return runner, state

    runner0, s0 = build(donate=False)
    step = runner0.train_step(zoo.loss)
    for b in batches:
        s0, m0 = step(s0, b)

    runner1, s1 = build(donate=False)
    multi = runner1.train_multi_step(zoo.loss)
    s1, m1 = multi(s1, stack_batches(batches))

    assert int(s1.step) == int(s0.step) == 3
    np.testing.assert_allclose(
        float(m1["loss"][-1]), float(m0["loss"]), rtol=1e-4, atol=1e-4
    )
    # Adam's eps term amplifies compile-order noise on near-zero params
    # early in training; the loss equality above is the tight check.
    for a, b in zip(jax.tree.leaves(s0.params),
                    jax.tree.leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-3)
