"""Fused multi-step (scan over a task's minibatches) == per-step loop."""

import jax
import numpy as np
import optax

from elasticdl_tpu.core.model_spec import get_model_spec
from elasticdl_tpu.core.step import (
    build_multi_step,
    build_train_step,
    stack_batches,
)
from elasticdl_tpu.core.train_state import init_train_state
from elasticdl_tpu.testing.data import model_zoo_dir


def _batches(n=4, b=16, seed=0):
    rng = np.random.RandomState(seed)
    return [
        {
            "features": rng.rand(b, 28, 28).astype(np.float32),
            "labels": rng.randint(0, 10, b).astype(np.int32),
            "mask": np.ones((b,), np.float32),
        }
        for _ in range(n)
    ]


def test_multi_step_matches_per_step_loop():
    spec = get_model_spec(model_zoo_dir(),
                          "mnist.mnist_functional.custom_model")
    batches = _batches()

    s0 = init_train_state(spec.model, optax.sgd(0.1, momentum=0.9),
                          batches[0], seed=0)
    s1 = init_train_state(spec.model, optax.sgd(0.1, momentum=0.9),
                          batches[0], seed=0)

    step = build_train_step(spec.loss)
    losses0 = []
    for b in batches:
        s0, m = step(s0, b)
        losses0.append(float(m["loss"]))

    multi = build_multi_step(spec.loss)
    s1, metrics = multi(s1, stack_batches(batches))

    np.testing.assert_allclose(
        np.asarray(metrics["loss"]), np.asarray(losses0),
        rtol=1e-4, atol=3e-5,
    )
    assert int(s1.step) == int(s0.step) == 4
    # bf16 forward compute recompiled as a scan body fuses differently,
    # so 4 accumulated applies drift ~1e-3 relative; this asserts
    # semantic equivalence, not bitwise.
    for a, b in zip(jax.tree.leaves(s0.params),
                    jax.tree.leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-3)
    # BatchNorm running stats advanced equivalently too.
    for a, b in zip(jax.tree.leaves(s0.batch_stats),
                    jax.tree.leaves(s1.batch_stats)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-3, atol=1e-3)
