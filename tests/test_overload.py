"""Overload plane semantics (ISSUE 20): ambient deadlines and their
edge cases over real RPC, priority admission control, retry budgets,
circuit breakers, and hedged reads (comm/deadline.py +
comm/overload.py + the comm/rpc.py integration).

The brownout drill (chaos/brownout_drill.py) exercises the whole plane
against a live fleet; these tests pin the unit semantics and the
client/server contract edges the drill's aggregate gates would blur —
expired-on-arrival never reaching a handler, the non-retryable detail
contract, nested scopes only shrinking, the shed/budget/breaker state
machines.
"""

import threading
import time

import pytest

from elasticdl_tpu.comm import deadline
from elasticdl_tpu.comm import overload
from elasticdl_tpu.comm.overload import (
    AdmissionController,
    BACKGROUND_PURPOSES,
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    HedgeTimer,
    RetryBudget,
    hedged_call,
    parse_retry_after,
    tier_of,
)
from elasticdl_tpu.comm.rpc import (
    EXPIRED_DETAIL,
    RpcError,
    RpcServer,
    RpcStub,
)
from elasticdl_tpu.observability import default_registry
from elasticdl_tpu.observability import principal


@pytest.fixture(autouse=True)
def _fresh_controls():
    overload.reset_retry_budgets()
    overload.reset_breakers()
    yield
    overload.reset_retry_budgets()
    overload.reset_breakers()
    overload.set_controls_enabled(True)


def _counter_value(name: str, labels=()):
    """Current value of one labeled series, 0.0 if absent — snapshot
    lookup so tests never have to re-state a family's help text."""
    for family in default_registry().snapshot()["families"]:
        if family["name"] != f"edl_tpu_{name}":
            continue
        for series in family["series"]:
            if tuple(series.get("labels") or ()) == tuple(labels):
                return float(series["value"])
    return 0.0


# ---- deadline scopes ------------------------------------------------------


class TestDeadlineScopes:
    def test_no_scope_is_inert(self):
        assert deadline.current() is None
        assert deadline.remaining() is None
        assert not deadline.expired()
        assert deadline.hop_timeout(None) is None
        assert deadline.hop_timeout(2.5) == 2.5

    def test_running_out_and_remaining(self):
        with deadline.running_out(5.0):
            left = deadline.remaining()
            assert 4.5 < left <= 5.0
            assert not deadline.expired()
        assert deadline.current() is None

    def test_nested_scope_only_shrinks(self):
        with deadline.running_out(5.0) as outer:
            # A LOOSER child clamps to the parent: a callee can never
            # outlive its caller's patience.
            with deadline.running_at(outer + 60.0) as inner:
                assert inner == outer
            # A TIGHTER child wins.
            with deadline.running_out(0.5):
                assert deadline.remaining() <= 0.5
            # Back to the outer budget afterwards.
            assert deadline.remaining() > 4.0

    def test_none_scope_is_noop(self):
        with deadline.running_at(None) as instant:
            assert instant is None
            assert deadline.current() is None

    def test_expired_after_instant_passes(self):
        with deadline.running_at(time.time() - 0.01):
            assert deadline.expired()
            assert deadline.remaining() <= 0.0

    def test_hop_timeout_min_of_explicit_and_ambient(self):
        with deadline.running_out(10.0):
            assert deadline.hop_timeout(0.25) == 0.25
            assert deadline.hop_timeout(None) <= 10.0
            assert deadline.hop_timeout(60.0) <= 10.0
        # Nearly-spent budgets still get one floored attempt instead
        # of a zero/negative gRPC timeout.
        with deadline.running_at(time.time() - 1.0):
            assert (deadline.hop_timeout(5.0)
                    == deadline.MIN_HOP_TIMEOUT_SECS)

    def test_bind_carries_deadline_to_pool_thread(self):
        seen = {}

        def probe():
            seen["remaining"] = deadline.remaining()

        with deadline.running_out(5.0):
            bound = deadline.bind(probe)
        # Thread-locals do NOT flow into other threads; the bound
        # closure re-establishes the captured instant there.
        t = threading.Thread(target=bound)
        t.start()
        t.join()
        assert seen["remaining"] is not None
        assert 0.0 < seen["remaining"] <= 5.0

        seen.clear()
        t = threading.Thread(target=probe)
        t.start()
        t.join()
        assert seen["remaining"] is None


# ---- deadlines over real RPC ----------------------------------------------


class TestDeadlineOverRpc:
    def _server(self, handlers, **kwargs):
        return RpcServer("localhost:0", {"Echo": handlers},
                         **kwargs).start()

    def test_expired_on_arrival_never_reaches_handler(self):
        called = []
        server = self._server({"echo": lambda req: called.append(1)})
        stub = RpcStub(f"localhost:{server.port}", "Echo",
                       max_retries=2)
        try:
            # Wire-level expired deadline with NO ambient scope: the
            # client would short-circuit its own expired scope, so
            # stamping the field directly is what isolates the
            # SERVER-side rejection (before the handler, and by
            # detail contract non-retryable — one attempt only).
            before = _counter_value(
                "rpc_retries_total",
                ("Echo", "echo", "DEADLINE_EXCEEDED"),
            )
            with pytest.raises(RpcError) as err:
                stub.call("echo", timeout=5.0,
                          _deadline=time.time() - 1.0)
            assert err.value.code == "DEADLINE_EXCEEDED"
            assert EXPIRED_DETAIL in str(err.value)
            assert not called
            assert _counter_value(
                "rpc_retries_total",
                ("Echo", "echo", "DEADLINE_EXCEEDED"),
            ) == before
        finally:
            stub.close()
            server.stop(0)

    def test_expired_ambient_scope_never_sends(self):
        called = []
        server = self._server({"echo": lambda req: called.append(1)})
        stub = RpcStub(f"localhost:{server.port}", "Echo",
                       max_retries=2)
        try:
            with deadline.running_at(time.time() - 0.5):
                with pytest.raises(RpcError) as err:
                    stub.call("echo", timeout=5.0)
            assert err.value.code == "DEADLINE_EXCEEDED"
            assert "not sent" in str(err.value)
            assert not called
        finally:
            stub.close()
            server.stop(0)

    def test_handler_inherits_ambient_deadline(self):
        seen = {}

        def probe(_req):
            seen["remaining"] = deadline.remaining()
            return {}

        server = self._server({"probe": probe})
        stub = RpcStub(f"localhost:{server.port}", "Echo",
                       max_retries=0)
        try:
            with deadline.running_out(5.0):
                stub.call("probe")
            assert seen["remaining"] is not None
            assert 0.0 < seen["remaining"] <= 5.0
            # Without a scope nothing is propagated or invented.
            stub.call("probe")
            assert seen["remaining"] is None
        finally:
            stub.close()
            server.stop(0)

    def test_slow_handler_deadline_is_terminal_not_retried(self):
        calls = []

        def slow(_req):
            calls.append(1)
            time.sleep(0.5)
            return {}

        server = self._server({"slow": slow})
        stub = RpcStub(f"localhost:{server.port}", "Echo",
                       max_retries=3)
        try:
            t0 = time.monotonic()
            with deadline.running_out(0.2):
                with pytest.raises(RpcError) as err:
                    stub.call("slow")
            # DEADLINE_EXCEEDED is retryable in general (a per-call
            # timeout may just have been tight) but NOT once the
            # ambient budget is spent: one attempt, no retry sleeps,
            # prompt surfacing.
            assert err.value.code == "DEADLINE_EXCEEDED"
            assert len(calls) == 1
            assert time.monotonic() - t0 < 0.45
        finally:
            stub.close()
            server.stop(0)

    def test_chaos_delay_consumes_budget_before_send(self):
        from elasticdl_tpu.chaos.faults import FaultEvent, FaultPlan
        from elasticdl_tpu.chaos.interceptors import FaultInjector

        called = []
        server = self._server({"echo": lambda req: called.append(1)})
        stub = RpcStub(f"localhost:{server.port}", "Echo",
                       max_retries=2)
        injector = FaultInjector(FaultPlan(events=[FaultEvent(
            kind="rpc_delay", target="Echo", method="echo",
            probability=1.0, delay_secs=0.3, max_fires=0,
        )], seed=3))
        injector.install()
        try:
            # The injected client-site delay models queue time: it
            # burns the whole 150ms budget, so the attempt goes out
            # with the floored hop timeout and comes back
            # DEADLINE_EXCEEDED — never retried (budget spent).
            with deadline.running_out(0.15):
                with pytest.raises(RpcError) as err:
                    stub.call("echo")
            assert err.value.code == "DEADLINE_EXCEEDED"
            assert not called
        finally:
            injector.uninstall()
            stub.close()
            server.stop(0)


# ---- priority admission ---------------------------------------------------


class TestAdmissionController:
    def test_limit_must_be_positive(self):
        with pytest.raises(ValueError):
            AdmissionController(0)

    def test_tier_thresholds_monotone_and_floored(self):
        ctl = AdmissionController(10)
        ts = [ctl.threshold(t) for t in range(4)]
        assert ts == sorted(ts, reverse=True)
        assert ts[0] == 10
        # Background tiers keep strictly less headroom than serving.
        assert ts[3] < ts[0]
        # A tiny limit still admits one request per tier on an idle
        # server (canaries must not starve outright).
        tiny = AdmissionController(1)
        assert all(tiny.threshold(t) == 1 for t in range(4))

    def test_shed_order_follows_tiers(self):
        ctl = AdmissionController(4)  # thresholds 4 / 3 / 2 / 2
        for _ in range(ctl.threshold(tier_of("training"))):
            assert ctl.try_acquire("training")
        # Tier-1 full: more training sheds, serving still admitted.
        assert not ctl.try_acquire("training")
        assert not ctl.try_acquire("canary")
        assert ctl.try_acquire("serving_read")
        assert ctl.inflight == 4
        # Fully saturated: serving sheds too (the last thing to go).
        assert not ctl.try_acquire("serving_read")
        for _ in range(4):
            ctl.release()
        assert ctl.inflight == 0
        assert ctl.try_acquire("canary")
        ctl.release()

    def test_shed_verdict_round_trips_retry_after(self):
        ctl = AdmissionController(2, retry_after_base=0.1)
        code, detail = ctl.shed_verdict("canary")
        assert code == "RESOURCE_EXHAUSTED"
        hint = parse_retry_after(detail)
        # Lower tiers are told to stay away longer.
        assert hint == pytest.approx(
            0.1 * (tier_of("canary") + 1)
        )
        assert hint > parse_retry_after(
            ctl.shed_verdict("training")[1]
        )
        # Non-shed details parse to None (plain RESOURCE_EXHAUSTED
        # from elsewhere must not be mistaken for a hinted shed).
        assert parse_retry_after("quota exceeded") is None

    def test_shed_and_depth_metrics(self):
        ctl = AdmissionController(1, tag="t")
        before = _counter_value("overload_shed_total", ("replay",))
        assert ctl.try_acquire("training")
        assert not ctl.try_acquire("replay")
        assert _counter_value(
            "overload_shed_total", ("replay",)
        ) == before + 1
        ctl.release()

    def test_unknown_purpose_rides_with_training(self):
        assert tier_of(None) == tier_of("training")
        assert tier_of("no-such-purpose") == tier_of("training")
        for purpose in BACKGROUND_PURPOSES:
            assert tier_of(purpose) > tier_of("serving_read")


class TestAdmissionOverRpc:
    def test_background_shed_serving_admitted(self):
        release = threading.Event()
        entered = threading.Event()

        def slow(_req):
            entered.set()
            release.wait(timeout=10.0)
            return {}

        def fast(_req):
            return {"ok": True}

        server = RpcServer(
            "localhost:0",
            {"Echo": {"slow": slow, "fast": fast}},
            admission=AdmissionController(2),
        ).start()  # thresholds: serving 2, training 1, background 1
        stubs = [RpcStub(f"localhost:{server.port}", "Echo",
                         max_retries=0) for _ in range(3)]
        occupant = threading.Thread(
            target=lambda: stubs[0].call("slow", timeout=10.0)
        )
        try:
            with principal.pushed(job="j", component="c",
                                  purpose="training"):
                occupant.start()
                assert entered.wait(timeout=5.0)
            # One training request in flight fills every background
            # tier; a canary shed is an immediate retryable
            # RESOURCE_EXHAUSTED carrying the hint...
            with principal.pushed(job="j", component="c",
                                  purpose="canary"):
                with pytest.raises(RpcError) as err:
                    stubs[1].call("fast", timeout=5.0)
            assert err.value.code == "RESOURCE_EXHAUSTED"
            assert parse_retry_after(str(err.value)) is not None
            # ...while a serving read on the SAME saturated server is
            # admitted and served.
            with principal.pushed(job="j", component="c",
                                  purpose="serving_read"):
                assert stubs[2].call(
                    "fast", timeout=5.0
                )["ok"] is True
        finally:
            release.set()
            occupant.join(timeout=10.0)
            for stub in stubs:
                stub.close()
            server.stop(0)


# ---- retry budget ---------------------------------------------------------


class TestRetryBudget:
    def test_exhaustion_and_metric(self):
        budget = RetryBudget(capacity=2.0, refill_per_sec=0.0,
                             success_refill=0.0, key="svc-x")
        before = _counter_value(
            "rpc_retry_budget_exhausted_total", ("svc-x",)
        )
        assert budget.try_spend()
        assert budget.try_spend()
        assert not budget.try_spend()
        assert _counter_value(
            "rpc_retry_budget_exhausted_total", ("svc-x",)
        ) == before + 1

    def test_success_refills(self):
        budget = RetryBudget(capacity=4.0, refill_per_sec=0.0,
                             success_refill=0.5)
        while budget.try_spend():
            pass
        budget.on_success()
        budget.on_success()
        assert budget.try_spend()
        assert not budget.try_spend()

    def test_time_refill_capped_at_capacity(self):
        budget = RetryBudget(capacity=1.0, refill_per_sec=1000.0)
        assert budget.try_spend()
        time.sleep(0.01)
        assert budget.tokens() == pytest.approx(1.0)

    def test_shared_per_service_and_reset(self):
        a = overload.retry_budget_for("RowService")
        assert overload.retry_budget_for("RowService") is a
        assert overload.retry_budget_for("Master") is not a
        overload.reset_retry_budgets()
        assert overload.retry_budget_for("RowService") is not a


# ---- circuit breaker ------------------------------------------------------


class TestCircuitBreaker:
    def test_trip_probe_and_close(self):
        # rand=0.0 pins the jittered cooldown at 0.5 * cooldown_secs.
        b = CircuitBreaker("t:1", failure_threshold=3,
                           cooldown_secs=0.1, rand=lambda: 0.0)
        for _ in range(2):
            b.on_failure()
        assert b.state == BREAKER_CLOSED and b.allow()
        b.on_failure()
        assert b.state == BREAKER_OPEN
        assert not b.allow()
        time.sleep(0.06)
        # Exactly ONE caller is admitted as the half-open probe.
        assert b.allow()
        assert b.state == BREAKER_HALF_OPEN
        assert not b.allow()
        b.on_success()
        assert b.state == BREAKER_CLOSED and b.allow()

    def test_failed_probe_reopens(self):
        b = CircuitBreaker("t:2", failure_threshold=1,
                           cooldown_secs=0.1, rand=lambda: 0.0)
        b.on_failure()
        time.sleep(0.06)
        assert b.allow()
        b.on_failure()  # the probe failed
        assert b.state == BREAKER_OPEN
        assert not b.allow()

    def test_success_resets_consecutive_count(self):
        b = CircuitBreaker("t:3", failure_threshold=2)
        b.on_failure()
        b.on_success()
        b.on_failure()
        assert b.state == BREAKER_CLOSED

    def test_state_gauge_tracks_transitions(self):
        b = CircuitBreaker("t:gauge", failure_threshold=1,
                           cooldown_secs=30.0)
        assert _counter_value(
            "rpc_breaker_state", ("t:gauge",)
        ) == BREAKER_CLOSED
        b.on_failure()
        assert _counter_value(
            "rpc_breaker_state", ("t:gauge",)
        ) == BREAKER_OPEN

    def test_breaker_for_shared_and_reset(self):
        a = overload.breaker_for("host:9")
        assert overload.breaker_for("host:9") is a
        overload.reset_breakers()
        assert overload.breaker_for("host:9") is not a


# ---- hedged reads ---------------------------------------------------------


class TestHedgedCall:
    def test_no_secondary_is_a_plain_call(self):
        assert hedged_call(lambda: 41, None, 0.01) == 41

    def test_slow_primary_hedged_second_wins(self):
        release = threading.Event()

        def slow_primary():
            release.wait(timeout=5.0)
            return "primary"

        before = _counter_value("rpc_hedge_wins_total", ("S", "m"))
        result = hedged_call(slow_primary, lambda: "secondary",
                             delay_secs=0.02, service="S", method="m")
        release.set()
        assert result == "secondary"
        assert _counter_value(
            "rpc_hedge_wins_total", ("S", "m")
        ) == before + 1

    def test_fast_primary_wins_no_hedge(self):
        before = _counter_value(
            "rpc_hedge_attempts_total", ("S", "fast")
        )
        assert hedged_call(lambda: "primary", lambda: "secondary",
                           delay_secs=1.0, service="S",
                           method="fast") == "primary"
        assert _counter_value(
            "rpc_hedge_attempts_total", ("S", "fast")
        ) == before

    def test_failed_primary_falls_back(self):
        def boom():
            raise RuntimeError("down")

        assert hedged_call(boom, lambda: "secondary",
                           delay_secs=5.0) == "secondary"

    def test_both_failing_surfaces_primary_error(self):
        # Primary outlives the hedge delay before failing, so this is
        # the true hedged path (not the fast-fail fallback, which by
        # design surfaces the secondary's error instead).
        def slow_boom():
            time.sleep(0.05)
            raise RuntimeError("primary down")

        def boom_b():
            raise RuntimeError("secondary down")

        with pytest.raises(RuntimeError, match="primary down"):
            hedged_call(slow_boom, boom_b, delay_secs=0.01)

    def test_hedge_timer_clamps_and_tracks(self):
        timer = HedgeTimer(floor=0.01, cap=0.5)
        assert timer.delay() == 0.5  # no samples: never hedge early
        for _ in range(100):
            timer.observe(0.002)
        assert timer.delay() == 0.01  # clamped to the floor
        for _ in range(200):
            timer.observe(0.2)
        assert timer.delay() == pytest.approx(0.2, abs=0.05)
