"""Master write-ahead journal + crash recovery (ISSUE 5 tentpole).

Unit level: record framing/torn-tail truncation, snapshot compaction,
fsck (tools/check_journal.py). Recovery level: a scripted
dispatch/report history replays into an equivalent dispatcher — with
and without a snapshot in the middle, and after a simulated torn tail
write — and the recovered master resolves duplicate/fenced reports
per the generation-fencing protocol.
"""

import os

import pytest

from elasticdl_tpu.common.constants import TaskType
from elasticdl_tpu.master.journal import (
    JournalFormatError,
    MasterJournal,
    read_records,
    recover_master_state,
)
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from tools.check_journal import check_journal


def make_dispatcher(records=100, per_task=10, epochs=1, shuffle=False,
                    **kw):
    return TaskDispatcher(
        training_shards={"f1": (0, records)},
        records_per_task=per_task,
        num_epochs=epochs,
        shuffle=shuffle,
        seed=3,
        **kw,
    )


def journaled_pair(tmp_path, snapshot_every=1000, **disp_kw):
    """(dispatcher with journal attached, journal)."""
    journal = MasterJournal(
        str(tmp_path / "journal"), snapshot_every=snapshot_every
    )
    dispatcher = make_dispatcher(**disp_kw)
    journal.open_generation()
    dispatcher.attach_journal(journal)
    return dispatcher, journal


def normalized(state: dict) -> dict:
    return {k: v for k, v in state.items() if k != "worker_version"}


def recover(tmp_path, **disp_kw):
    """Fresh journal handle + fresh dispatcher, replayed (the crash
    path: nothing from the old process survives but the file)."""
    journal = MasterJournal(str(tmp_path / "journal"))
    dispatcher = make_dispatcher(**disp_kw)
    servicer = MasterServicer(dispatcher, journal=journal)
    stats = recover_master_state(journal, dispatcher, servicer=servicer)
    return dispatcher, servicer, journal, stats


class TestJournalFile:
    def test_records_roundtrip_and_seq(self, tmp_path):
        journal = MasterJournal(str(tmp_path / "j"))
        journal.open_generation()
        journal.append("version", model_version=3)
        journal.append("version", model_version=7)
        journal.close()
        records = [r for _o, _e, r in read_records(journal.path)]
        assert [r["t"] for r in records] == [
            "generation", "version", "version",
        ]
        assert [r["seq"] for r in records] == [1, 2, 3]
        assert check_journal(journal.path) == []

    def test_torn_tail_is_truncated_not_fatal(self, tmp_path):
        journal = MasterJournal(str(tmp_path / "j"))
        journal.open_generation()
        journal.append("version", model_version=1)
        journal.close()
        good = open(journal.path, "rb").read()
        # Crash mid-write: a partial frame lands after the good bytes.
        with open(journal.path, "ab") as fh:
            fh.write(b"\x07\x00\x00\x00GARBAGE-NO-CRC"[:9])
        again = MasterJournal(str(tmp_path / "j"))
        assert again.open_generation() == 1  # fenced past gen 0
        again.close()
        blob = open(journal.path, "rb").read()
        assert blob.startswith(good)  # intact prefix preserved

    def test_mid_file_corruption_detected_by_fsck(self, tmp_path):
        journal = MasterJournal(str(tmp_path / "j"))
        journal.open_generation()
        for v in range(4):
            journal.append("version", model_version=v)
        journal.close()
        # Flip a byte INSIDE the first record's payload: framing can't
        # resync, so everything after reads as a (huge) torn tail —
        # fsck must flag the loss, not bless the file.
        with open(journal.path, "r+b") as fh:
            fh.seek(12)
            byte = fh.read(1)
            fh.seek(12)
            fh.write(bytes([byte[0] ^ 0xFF]))
        errors = check_journal(journal.path)
        assert errors and any("torn" in e or "trailing" in e
                              for e in errors)

    def test_snapshot_compacts_file(self, tmp_path):
        dispatcher, journal = journaled_pair(
            tmp_path, snapshot_every=4, records=100, per_task=10
        )
        for _ in range(4):
            task = dispatcher.get(0)
            dispatcher.report(task.task_id, True)
        # 8 dispatch/report records crossed the cadence twice; the
        # file holds only [fence, snapshot, tail] after compaction.
        types = [
            r["t"] for _o, _e, r in read_records(journal.path)
        ]
        assert types[0] == "generation"
        assert "snapshot" in types
        assert len([t for t in types if t in ("dispatch", "report")]) < 8
        assert check_journal(journal.path) == []
        journal.close()


class TestRecovery:
    def _drive(self, dispatcher, n_complete=3, n_fail=1):
        for _ in range(n_complete):
            task = dispatcher.get(0)
            dispatcher.report(task.task_id, True)
        for _ in range(n_fail):
            task = dispatcher.get(1)
            dispatcher.report(task.task_id, False, err_reason="boom")
        # Leave two leases in flight (the crash-survivor scenario).
        dispatcher.get(0)
        dispatcher.get(1)

    @pytest.mark.parametrize("snapshot_every", [1000, 3])
    def test_replay_recovers_equivalent_state(self, tmp_path,
                                              snapshot_every):
        dispatcher, journal = journaled_pair(
            tmp_path, snapshot_every=snapshot_every,
            records=100, per_task=10, epochs=2, shuffle=True,
        )
        self._drive(dispatcher)
        dead = dispatcher.export_state()
        journal.close()
        recovered, _servicer, journal2, stats = recover(
            tmp_path, records=100, per_task=10, epochs=2, shuffle=True
        )
        assert normalized(recovered.export_state()) == normalized(dead)
        assert stats["generation"] == 1
        assert stats["snapshot"] == (snapshot_every == 3)
        assert sorted(stats["known_workers"]) == [0, 1]
        journal2.close()

    def test_torn_tail_recovers_to_last_intact_record(self, tmp_path):
        dispatcher, journal = journaled_pair(
            tmp_path, records=40, per_task=10
        )
        t1 = dispatcher.get(0)
        dispatcher.report(t1.task_id, True)
        checkpointed = dispatcher.export_state()
        dispatcher.get(0)  # the dispatch whose record we tear
        journal.close()
        # Tear the LAST record: keep a prefix long enough to damage it.
        size = os.path.getsize(journal.path)
        with open(journal.path, "r+b") as fh:
            fh.truncate(size - 5)
        recovered, _sv, journal2, stats = recover(
            tmp_path, records=40, per_task=10
        )
        # The torn dispatch never happened as far as recovery can
        # know — state equals the pre-dispatch checkpoint.
        assert normalized(recovered.export_state()) == normalized(
            checkpointed
        )
        journal2.close()

    def test_recovered_master_fences_and_dedups_reports(self, tmp_path):
        dispatcher, journal = journaled_pair(
            tmp_path, records=40, per_task=10
        )
        servicer = MasterServicer(dispatcher, journal=journal)
        done = servicer.get_task({"worker_id": 0})["task"]["task_id"]
        assert servicer.report_task_result(
            {"task_id": done, "worker_id": 0}
        )["accepted"]
        leased = servicer.get_task({"worker_id": 0})["task"]["task_id"]
        journal.close()
        recovered, servicer2, journal2, _stats = recover(
            tmp_path, records=40, per_task=10
        )
        assert servicer2.generation == 1
        # Duplicate of a pre-crash-applied report: original outcome.
        dup = servicer2.report_task_result(
            {"task_id": done, "worker_id": 0, "generation": 0}
        )
        assert dup["accepted"] and dup["generation"] == 1
        # The surviving lease re-reports and applies exactly once.
        late = servicer2.report_task_result(
            {"task_id": leased, "worker_id": 0, "generation": 0}
        )
        assert late["accepted"]
        assert recovered.counters.total_records[TaskType.TRAINING] == 20
        # A task id no incarnation ever dispatched: fenced.
        bogus = servicer2.report_task_result(
            {"task_id": 999, "worker_id": 0, "generation": 0}
        )
        assert not bogus["accepted"] and bogus["fenced"]
        journal2.close()

    def test_replay_divergence_fails_loudly(self, tmp_path):
        dispatcher, journal = journaled_pair(
            tmp_path, records=40, per_task=10
        )
        dispatcher.get(0)
        journal.close()
        # Recover with DIFFERENT job config: the replayed dispatch
        # cannot reproduce the journaled task.
        journal2 = MasterJournal(str(tmp_path / "journal"))
        wrong = make_dispatcher(records=40, per_task=20)
        with pytest.raises(JournalFormatError, match="diverged"):
            recover_master_state(journal2, wrong)
        journal2.close()

    def test_model_version_survives_compaction(self, tmp_path):
        """Compaction discards the raw VERSION records; the snapshot
        must carry the high-water mark or every post-compaction
        recovery re-arms eval triggering at version 0."""
        dispatcher, journal = journaled_pair(
            tmp_path, snapshot_every=2, records=40, per_task=10
        )
        journal.append("version", model_version=5)
        for _ in range(2):  # crosses the cadence -> snapshot+compact
            t = dispatcher.get(0)
            dispatcher.report(t.task_id, True)
        types = [r["t"] for _o, _e, r in read_records(journal.path)]
        assert "version" not in types  # compacted away
        journal.close()
        _recovered, servicer, journal2, stats = recover(
            tmp_path, records=40, per_task=10
        )
        assert stats["model_version"] == 5
        assert servicer.model_version == 5
        journal2.close()

    def test_retry_counts_survive_recovery(self, tmp_path):
        dispatcher, journal = journaled_pair(
            tmp_path, records=20, per_task=10
        )
        task = dispatcher.get(0)
        dispatcher.report(task.task_id, False, err_reason="x")
        journal.close()
        recovered, _sv, journal2, _stats = recover(
            tmp_path, records=20, per_task=10
        )
        key = f"{task.shard_name}:{task.start}:{task.end}"
        assert recovered._task_retry_count[key] == 1
        journal2.close()
