"""Live row-service resharding + hot-row replication (PR 12).

Shard-map algebra, REDIRECT convergence, the generation-fenced
migration protocol, replica staleness, tiered-table migration without
hot-budget churn, the authority's crash-safety artifacts
(tools/check_reshard.py), and the reshard chaos drill's fast lane.
docs/sparse_path.md "Live resharding & hot-row replication".
"""

import json
import os
import time

import numpy as np
import pytest

from elasticdl_tpu.embedding.optimizer import (
    SGD,
    Adam,
    HostOptimizerWrapper,
)
from elasticdl_tpu.embedding.row_service import (
    DirectTransport,
    HostRowService,
    make_remote_engine,
)
from elasticdl_tpu.embedding.shard_map import (
    NUM_BUCKETS,
    ClientShardMap,
    ShardMap,
    ShardMapError,
    bucket_of,
)
from elasticdl_tpu.embedding.table import EmbeddingTable
from elasticdl_tpu.master.row_reshard import (
    ReshardPolicy,
    ShardMapController,
)

DIM = 8


# ---- shard-map algebra -------------------------------------------------


def test_bootstrap_covers_bucket_space():
    m = ShardMap.bootstrap(["a", "b", "c"])
    assert m.version == 1
    covered = sum(hi - lo for lo, hi, _s in m.ranges)
    assert covered == NUM_BUCKETS
    # Vectorized owner lookup agrees with the ranges.
    for lo, hi, s in m.ranges:
        assert (m.owner_table[lo:hi] == s).all()
    # Dense ids spread across all shards.
    homes = set(m.home_of_ids(np.arange(0, 30000, 17)).tolist())
    assert homes == {0, 1, 2}


def test_move_range_and_split_plan_algebra():
    m = ShardMap.bootstrap(["a", "b"])
    lo, hi = m.split_plan(0)
    m2 = m.add_shard("c")
    assert m2.version == 2 and m2.buckets_owned(2) == 0
    m3 = m2.move_range(lo, hi, 2)
    assert m3.version == 3
    assert m3.buckets_owned(0) + (hi - lo) == m.buckets_owned(0)
    assert m3.buckets_owned(2) == hi - lo
    # Still disjoint + covering (validate runs in the constructor).
    assert sum(h - l for l, h, _ in m3.ranges) == NUM_BUCKETS
    # Merge: drain shard 2 back into 0.
    m4 = m3.move_shard(2, 0)
    assert m4.buckets_owned(2) == 0
    assert m4.buckets_owned(0) == m.buckets_owned(0)


def test_map_validation_rejects_bad_shapes():
    with pytest.raises(ShardMapError):
        ShardMap(1, ["a"], [(0, NUM_BUCKETS - 1, 0)])  # gap at end
    with pytest.raises(ShardMapError):
        ShardMap(1, ["a"], [(0, NUM_BUCKETS, 1)])  # unknown shard
    with pytest.raises(ShardMapError):
        ShardMap(0, ["a"], [(0, NUM_BUCKETS, 0)])  # version < 1
    m = ShardMap.bootstrap(["a", "b"])
    with pytest.raises(ShardMapError):
        m.move_range(10, 10, 1)  # empty range
    with pytest.raises(ShardMapError):
        m.add_shard("a")  # duplicate address


def test_serialization_roundtrip_and_client_map_monotonic():
    m = ShardMap.bootstrap(["a", "b"]).with_replicas(
        {"items": {7: (1,), 11: (0, 1)}}
    )
    again = ShardMap.from_json(
        json.loads(json.dumps(m.to_json()))
    )
    assert again == m
    assert again.replica_targets("items", 7) == (1,)
    cmap = ClientShardMap(m)
    older = ShardMap.bootstrap(["a", "b"])
    assert not cmap.update(older.to_json())  # stale: rejected
    newer = m.move_range(0, 8, 1)
    assert cmap.update(newer.to_json())
    assert cmap.version == newer.version


# ---- fixtures ----------------------------------------------------------


def _start_shard(opt=None, **kwargs):
    svc = HostRowService(
        {"items": EmbeddingTable("items", DIM)},
        HostOptimizerWrapper(opt or SGD(lr=0.5)), **kwargs,
    )
    return svc.start()


def _fleet(n, tmp_path, policy=None, direct=True):
    shards = [_start_shard() for _ in range(n)]
    addrs = [f"localhost:{s.port}" for s in shards]
    by_addr = dict(zip(addrs, shards))
    factory = (
        (lambda a: DirectTransport(by_addr[a])) if direct else None
    )
    if direct:
        for s in shards:
            s.transport_factory = factory
    ctrl = ShardMapController(
        str(tmp_path / "shard_map.json"),
        transport_factory=factory, policy=policy,
    )
    ctrl.bootstrap(addrs)
    return shards, addrs, by_addr, ctrl


def _stop(shards):
    for s in shards:
        s.stop(0)


def _spread_ids(n, seed=5):
    rng = np.random.RandomState(seed)
    return np.unique(rng.randint(0, 1_000_000, n).astype(np.int64))


# ---- REDIRECT convergence (the satellite's mid-stream bump) ------------


def test_map_version_bump_mid_stream_retries_cleanly(tmp_path):
    """A client routing under epoch v is NOT told about a cutover; its
    next pulls/pushes to the old home get REDIRECTed and must land on
    the new home without loss or double-apply — never silently pull
    from the wrong shard (the old client-side id%N failure mode)."""
    shards, addrs, by_addr, ctrl = _fleet(2, tmp_path)
    engine = make_remote_engine(
        ",".join(addrs), id_keys={"items": "ids"},
        retries=2, backoff_secs=0.1,
    )
    table = engine.tables["items"]
    ids = _spread_ids(64)
    before = table.get(ids)
    assert engine.shard_map.version == 1

    # Live split onto a third shard while the client still holds v1.
    target = _start_shard()
    by_addr[f"localhost:{target.port}"] = target
    target.transport_factory = shards[0].transport_factory
    ctrl.split(0, new_addr=f"localhost:{target.port}")
    shards.append(target)
    assert ctrl.map.version > 1

    # Pull mid-stream: values identical, epoch adopted via REDIRECT.
    np.testing.assert_array_equal(table.get(ids), before)
    assert engine.shard_map.version == ctrl.map.version

    # Push after another unannounced change: single application.
    grads = np.ones((ids.size, DIM), np.float32)
    engine.optimizer.apply_gradients(table, ids, grads)
    after = table.get(ids)
    np.testing.assert_allclose(after, before - 0.5 * grads, rtol=1e-6)
    # Single-homed: each id materialized on exactly its map home.
    m = ctrl.map
    for i in ids.tolist():
        homes = [
            k for k, svc in enumerate(shards)
            if bool(svc._tables["items"].contains([i])[0])
        ]
        assert homes == [int(m.home_of_ids([i])[0])]
    _stop(shards)


def test_migration_moves_optimizer_slots_in_lockstep(tmp_path):
    """Adam: a migrated row's m/v slot bytes land on the target
    EXACTLY as the source held them (and leave the source) —
    optimizer state moves with its rows, it is never reset to the
    lazy slot init."""
    shards = [_start_shard(opt=Adam(lr=0.05)) for _ in range(2)]
    addrs = [f"localhost:{s.port}" for s in shards]
    by_addr = dict(zip(addrs, shards))
    for s in shards:
        s.transport_factory = lambda a: DirectTransport(by_addr[a])
    ctrl = ShardMapController(
        str(tmp_path / "m2.json"),
        transport_factory=lambda a: DirectTransport(by_addr[a]),
    )
    ctrl.bootstrap(addrs)
    engine = make_remote_engine(
        ",".join(addrs), id_keys={"items": "ids"},
        retries=2, backoff_secs=0.1,
    )
    table = engine.tables["items"]
    ids = _spread_ids(48, seed=9)
    rng = np.random.RandomState(3)
    for _seq in range(3):
        grads = rng.rand(ids.size, DIM).astype(np.float32)
        engine.optimizer.apply_gradients(table, ids, grads)

    # Source slot bytes for the range about to move.
    plan_lo, plan_hi = ctrl.map.split_plan(0)
    b = bucket_of(ids)
    moved = ids[(b >= plan_lo) & (b < plan_hi)
                & (ctrl.map.home_of_ids(ids) == 0)]
    assert moved.size > 0
    src_slots = {
        name: np.asarray(view.get(moved.tolist()))
        for name, view in shards[0].host_tables.items()
        if name.startswith("items-")
    }
    assert src_slots  # Adam has m/v slots
    # Slots hold real optimizer state, not the lazy init.
    assert any(np.abs(v).sum() > 0 for v in src_slots.values())

    target = _start_shard(opt=Adam(lr=0.05))
    by_addr[f"localhost:{target.port}"] = target
    target.transport_factory = shards[0].transport_factory
    ctrl.split(0, new_addr=f"localhost:{target.port}")
    shards.append(target)
    assert ctrl.map.home_of_ids(moved).tolist() == [2] * moved.size
    for name, want in src_slots.items():
        got = np.asarray(
            target.host_tables[name].get(moved.tolist())
        )
        np.testing.assert_array_equal(got, want)
        # Lockstep erase: the source's slot rows left with the
        # primary rows.
        assert not shards[0]._optimizer._slot_tables[name].contains(
            moved
        ).any()
    # Per-table apply counts migrate too (max-adopted): the target's
    # first post-cutover Adam apply must not bias-correct migrated
    # state as if it were step 1.
    assert target._optimizer._steps.get("items") == (
        shards[0]._optimizer._steps.get("items")
    )
    _stop(shards)


def test_fenced_pushes_retry_and_apply_exactly_once(tmp_path):
    """A push landing in the write-fence window between the final
    migration delta and the cutover must be rejected-without-apply and
    succeed on retry — one application total."""
    from elasticdl_tpu.embedding import row_service as rs

    shards, addrs, by_addr, ctrl = _fleet(2, tmp_path)
    engine = make_remote_engine(
        ",".join(addrs), id_keys={"items": "ids"},
        retries=2, backoff_secs=0.1,
    )
    table = engine.tables["items"]
    ids = _spread_ids(32, seed=13)
    before = table.get(ids)
    pushed = {"n": 0}
    import threading

    def racing_push(_svc, _mig, _view, _chunk):
        # Runs inside migrate_out: fire one concurrent push so the
        # catch-up/fence path sees live writes.
        if pushed["n"] == 0:
            pushed["n"] = 1

            def go():
                engine.optimizer.apply_gradients(
                    table, ids,
                    np.ones((ids.size, DIM), np.float32),
                )

            t = threading.Thread(target=go, daemon=True)
            t.start()
            pushed["thread"] = t

    target = _start_shard()
    by_addr[f"localhost:{target.port}"] = target
    target.transport_factory = shards[0].transport_factory
    rs.set_reshard_chaos_hooks(mid_migrate=racing_push)
    try:
        ctrl.split(0, new_addr=f"localhost:{target.port}")
    finally:
        rs.set_reshard_chaos_hooks(mid_migrate=None)
    shards.append(target)
    assert pushed["n"] == 1
    pushed["thread"].join(timeout=30)
    after = table.get(ids)
    np.testing.assert_allclose(after, before - 0.5, rtol=1e-6)
    _stop(shards)


# ---- hot-row replicas --------------------------------------------------


def test_replica_refresh_and_staleness_metric(tmp_path):
    """A push to a replicated id refreshes the replica copies within
    the refresh window; replica reads serve the fresh bytes and the
    row_replica_staleness_seconds histogram observes the lag."""
    from elasticdl_tpu.observability import default_registry

    policy = ReshardPolicy(replica_min_pulls=2, replica_top_k=8,
                           replica_count=2)
    shards, addrs, by_addr, ctrl = _fleet(3, tmp_path, policy=policy)
    engine = make_remote_engine(
        ",".join(addrs), id_keys={"items": "ids"},
        retries=2, backoff_secs=0.1,
    )
    table = engine.tables["items"]
    hot = np.array([5, 9000], np.int64)
    for _ in range(6):
        table.get(hot)
    assert ctrl.update_replicas()
    m = ctrl.map
    assert all(m.replica_targets("items", int(i)) for i in hot)

    # Client learns the replica epoch from the piggybacked version
    # (replica-only epochs never REDIRECT).
    table.get(hot)
    assert engine.shard_map.version == m.version

    engine.optimizer.apply_gradients(
        table, hot, np.ones((hot.size, DIM), np.float32)
    )
    want = None
    deadline = time.time() + 5.0
    while time.time() < deadline:
        ok = True
        for i in hot.tolist():
            home = int(m.home_of_ids([i])[0])
            fresh = by_addr[m.shards[home]]._tables["items"].get([i])[0]
            for r in m.replica_targets("items", i):
                entry = by_addr[m.shards[r]]._replica_store.get(
                    "items", {}
                ).get(i)
                if entry is None or not np.array_equal(
                    entry[0], np.asarray(fresh, np.float32)
                ):
                    ok = False
        if ok:
            break
        time.sleep(0.05)
    assert ok, "replica copies did not refresh within the window"

    # Replica-path reads agree with home; read repeatedly so the
    # round-robin actually exercises replicas.
    ref = table.get(hot)
    for _ in range(4):
        np.testing.assert_allclose(table.get(hot), ref, rtol=1e-6)
    snap = default_registry().snapshot()["families"]
    stale = next(
        f for f in snap
        if f["name"].endswith("row_replica_staleness_seconds")
    )
    assert sum(s["count"] for s in stale["series"]) > 0
    reads = next(
        f for f in snap
        if f["name"].endswith("row_replica_reads_total")
    )
    assert sum(s["value"] for s in reads["series"]) > 0
    _stop(shards)


def test_replica_miss_falls_back_to_home(tmp_path):
    """A designated replica that has not received its refresh yet must
    not break reads — misses fall back to the authoritative home."""
    shards, addrs, by_addr, ctrl = _fleet(2, tmp_path)
    engine = make_remote_engine(
        ",".join(addrs), id_keys={"items": "ids"},
        retries=2, backoff_secs=0.1,
    )
    table = engine.tables["items"]
    ids = np.array([123], np.int64)
    ref = table.get(ids)
    # Designate a replica by hand WITHOUT warming it: wipe the store.
    m = ctrl.map.with_replicas({"items": {123: (1,)}})
    with ctrl._lock:
        ctrl._map = m
        ctrl._persist()
        ctrl._sync_locked()
    by_addr[addrs[1]]._replica_store.clear()
    for _ in range(4):  # every rr pick, incl. the replica route
        np.testing.assert_array_equal(table.get(ids), ref)
    _stop(shards)


# ---- migration with tiered tables --------------------------------------


def test_migration_streams_cold_rows_without_promotion(tmp_path):
    """A tiered source shard migrates a mostly-cold range via segment
    reads: the hot arena's membership is untouched (no cold row is
    promoted through the budget by the copy) and the target receives
    byte-equal rows."""
    svc = HostRowService(
        {"items": EmbeddingTable("items", DIM)},
        HostOptimizerWrapper(SGD(lr=0.5)),
    )
    svc.configure_tiering(str(tmp_path / "cold"), hot_budget_rows=32,
                          background_compact=False)
    svc.start()
    target = _start_shard()
    by_addr = {
        f"localhost:{svc.port}": svc,
        f"localhost:{target.port}": target,
    }
    svc.transport_factory = lambda a: DirectTransport(by_addr[a])
    target.transport_factory = svc.transport_factory
    ctrl = ShardMapController(
        str(tmp_path / "sm.json"),
        transport_factory=lambda a: DirectTransport(by_addr[a]),
    )
    ctrl.bootstrap([f"localhost:{svc.port}"])
    ctrl.map  # noqa: B018

    # Materialize 8x the hot budget: most rows live cold. x37 spreads
    # the ids across the bucket space so the split's upper-half range
    # actually contains some of them.
    ids = np.arange(0, 256, dtype=np.int64) * 37
    rng = np.random.RandomState(7)
    rows = rng.rand(ids.size, DIM).astype(np.float32)
    table = svc._tables["items"]
    table.set(ids, rows)
    stats = svc.tier_stats()["items"]
    assert stats["cold_rows"] > 0

    hot_before = set(table._hot)
    ctrl.split(0, new_addr=f"localhost:{target.port}")
    # No promotion: the copy read cold rows via segment reads, never
    # through the hot budget.
    assert set(table._hot) <= hot_before
    m = ctrl.map
    moved = ids[m.home_of_ids(ids) == 1]
    assert moved.size > 0
    got = target._tables["items"].get(moved.tolist())
    np.testing.assert_array_equal(
        got, rows[np.isin(ids, moved)]
    )
    # Source erased its moved rows across BOTH tiers (single-homing).
    assert not table.contains(moved).any()
    svc.stop(0)
    target.stop(0)


# ---- checkpoint meta / journal -----------------------------------------


def test_shard_map_rides_checkpoint_meta(tmp_path):
    shards, addrs, by_addr, ctrl = _fleet(2, tmp_path)
    ckpt = str(tmp_path / "ckpt0")
    svc = shards[0]
    svc.configure_checkpoint(ckpt, checkpoint_steps=1,
                            async_write=False)
    engine = make_remote_engine(
        ",".join(addrs), id_keys={"items": "ids"},
        retries=2, backoff_secs=0.1,
    )
    ids = _spread_ids(16, seed=21)
    engine.optimizer.apply_gradients(
        engine.tables["items"], ids,
        np.ones((ids.size, DIM), np.float32),
    )
    version = ctrl.map.version
    port = svc.port
    svc.stop(0)
    relaunched = HostRowService(
        {"items": EmbeddingTable("items", DIM)},
        HostOptimizerWrapper(SGD(lr=0.5)),
        checkpoint_dir=ckpt, checkpoint_steps=1,
    ).start(f"localhost:{port}")
    assert relaunched._shard_map is not None
    assert relaunched._shard_map.version == version
    assert relaunched._shard_id == 0
    relaunched.stop(0)
    shards[1].stop(0)


def test_shard_map_journal_record(tmp_path):
    from elasticdl_tpu.master.journal import (
        MasterJournal,
        validate_record,
    )
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    journal = MasterJournal(str(tmp_path / "journal"))
    journal.open_generation()
    m = ShardMap.bootstrap(["a", "b"])
    journal.append("shard_map", version=m.version, map=m.to_json())
    m2 = m.move_range(0, 8, 1)
    journal.append("shard_map", version=m2.version, map=m2.to_json())
    journal.close()

    reopened = MasterJournal(str(tmp_path / "journal"))
    records = reopened.replay_records()
    assert all(validate_record(r) is None for r in records)
    stats = reopened.recover_into(TaskDispatcher({}, {}, {}, 16))
    assert stats["shard_map"]["version"] == m2.version
    assert validate_record(
        {"t": "shard_map", "seq": 1, "version": "x", "map": {}}
    ) is not None


def test_controller_persist_and_resume(tmp_path):
    shards, addrs, by_addr, ctrl = _fleet(2, tmp_path)
    target = _start_shard()
    addr3 = f"localhost:{target.port}"
    by_addr[addr3] = target
    target.transport_factory = shards[0].transport_factory
    ctrl.split(0, new_addr=addr3)
    shards.append(target)
    version = ctrl.map.version

    again = ShardMapController(
        str(tmp_path / "shard_map.json"),
        transport_factory=lambda a: DirectTransport(by_addr[a]),
    )
    assert again.map == ctrl.map
    assert again.resume() is None  # nothing in flight
    assert again.map.version == version
    _stop(shards)


# ---- policy units ------------------------------------------------------


def test_policy_pick_move_thresholds():
    policy = ReshardPolicy(imbalance_factor=2.0,
                           min_rows_per_tick=100)
    assert policy.pick_move({0: 10, 1: 10}) is None  # under min rows
    assert policy.pick_move({0: 300, 1: 290}) is None  # balanced
    assert policy.pick_move({0: 900, 1: 100}) == (0, 1)
    assert policy.pick_move({0: 500}) is None  # nowhere to move


def test_policy_pick_replicas_ring_spread():
    policy = ReshardPolicy(replica_top_k=2, replica_min_pulls=10,
                           replica_count=2)
    out = policy.pick_replicas(
        {"items": {7: 100, 8: 50, 9: 5}}, 3,
        home_of=lambda table, i: 0,
    )
    assert set(out["items"]) == {7, 8}  # 9 under min_pulls
    assert out["items"][7] == (1, 2)
    assert policy.pick_replicas({"items": {7: 100}}, 1,
                                home_of=lambda t, i: 0) == {}


# ---- fsck + drill fast lane --------------------------------------------


def _tools():
    import sys

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools",
    )
    if path not in sys.path:
        sys.path.insert(0, path)


def test_check_reshard_fsck(tmp_path):
    _tools()
    from check_reshard import check_reshard

    state = str(tmp_path / "sm.json")
    errors, report = check_reshard(state)
    assert errors  # missing file

    m = ShardMap.bootstrap(["a", "b"])
    good = {"map": m.to_json(), "migration": None, "mig_seq": 0}
    with open(state, "w") as fh:
        json.dump(good, fh)
    errors, report = check_reshard(state)
    assert not errors and report["map_version"] == 1
    assert not report["migration_in_flight"]

    # Resumable half-moved range (phase copy, source still owns).
    lo, hi = m.split_plan(0)
    good["migration"] = {
        "migration_id": "m1", "source": 0, "target": 1,
        "lo": lo, "hi": hi, "phase": "copy",
    }
    with open(state, "w") as fh:
        json.dump(good, fh)
    errors, report = check_reshard(state)
    assert not errors
    assert report["migration_in_flight"] and report["resumable"]

    # Phase/ownership inconsistency is an error.
    good["migration"]["phase"] = "cutover"
    with open(state, "w") as fh:
        json.dump(good, fh)
    errors, report = check_reshard(state)
    assert errors and not report["resumable"]

    good["migration"]["phase"] = "warp"
    with open(state, "w") as fh:
        json.dump(good, fh)
    errors, _report = check_reshard(state)
    assert any("unknown" in e for e in errors)


def test_reshard_drill_passes(tmp_path):
    """Fast-lane twin of ``make reshard-smoke``: kills mid-migration
    and mid-cutover must converge byte-equal to the fault-free twin
    with no row lost or double-homed."""
    from elasticdl_tpu.chaos.reshard_drill import run_drill

    report = run_drill(str(tmp_path), seed=7)
    assert report["passed"], report["problems"]
    assert len(report["scenarios"]) == 2
