"""Test env: force an 8-device virtual CPU mesh before JAX initializes.

Mesh/sharding logic must be testable without TPU hardware (SURVEY.md §7
"hard parts" (a)); bench.py and real runs use the TPU backend instead.

Note: the environment's sitecustomize registers the 'axon' TPU plugin and
calls ``jax.config.update("jax_platforms", "axon,cpu")`` in every process,
which overrides the JAX_PLATFORMS env var — so we must override the config
back to cpu here, not just set the env var, or tests silently run on the
TPU tunnel (and hang when it is unavailable).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long multi-process integration tests"
    )
