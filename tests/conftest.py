"""Test env: force an 8-device virtual CPU mesh before JAX initializes.

Mesh/sharding logic must be testable without TPU hardware (SURVEY.md §7
"hard parts" (a)); bench.py and real runs use the TPU backend instead.

Note: the environment's sitecustomize registers the 'axon' TPU plugin and
calls ``jax.config.update("jax_platforms", "axon,cpu")`` in every process,
which overrides the JAX_PLATFORMS env var — so we must override the config
back to cpu here, not just set the env var, or tests silently run on the
TPU tunnel (and hang when it is unavailable).
"""

import os

# grpc's C-core INFO logs (GOAWAY notices on every server teardown)
# splice into pytest's dot-progress lines and corrupt the plain-text
# test output the CI lane parses; only errors are worth the noise.
os.environ.setdefault("GRPC_VERBOSITY", "ERROR")

# The TPU kernel-correctness lane (`make test-tpu`, tests marked `tpu`)
# must run on the REAL chip — compiled, non-interpret — so it skips the
# CPU forcing below and keeps the default (axon) platform.
_TPU_LANE = os.environ.get("ELASTICDL_TPU_TESTS", "") == "1"

if not _TPU_LANE:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

if not _TPU_LANE:
    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long multi-process integration tests"
    )
    config.addinivalue_line(
        "markers", "tpu: requires the real TPU chip (compiled, "
        "non-interpret kernel correctness lane; run via make test-tpu)"
    )
    config.addinivalue_line(
        "markers", "k8s: live-cluster integration lane, gated on "
        "ELASTICDL_K8S_TESTS=1 + a reachable cluster (make test-k8s)"
    )
    config.addinivalue_line(
        "markers", "perf: wall-clock overhead pins (sampler pass "
        "cost, null-span cost) that flake under CI box noise; "
        "excluded from the CI fast lane, still in make test-all"
    )


# Test tiering (VERDICT round 1 #10): `make test` runs the fast lane
# (<4 min); `make test-all` runs everything. Modules/tests listed here
# are auto-marked slow — measured >8s each on the CI box; the breadth
# they add (zoo e2e, multi-process jobs, bench smoke, heavy numerics)
# belongs in the full lane, not the edit-compile-test loop.
_SLOW_MODULES = {
    "test_example_zoo",
    "test_multihost_job",
    "test_multihost_2proc",
    "test_bench_suite",
    "test_elastic_mesh_resize",
    "test_pipeline_lm",
}
_SLOW_TESTS = {
    "test_fused_mesh_runner_matches_stepwise",
    "test_remat_matches_plain",
    "test_moe_top2_routing",
    "test_training_learns_on_dp_sp_tp",
    "test_mesh_training_matches_single_device",
    "test_moe_expert_parallel",
    "test_mesh_wiring_end_to_end",
    "test_sharded_roundtrip",
    "test_local_mnist_trains_and_loss_decreases",
    "test_remat_transformer_with_dropout",
    "test_incremental_decode_matches_full_forward",
    "test_trained_model_generates_learned_chain",
    "test_pallas_ring_matches_dense",
    "test_ring_gradients_match_dense",
    "test_single_worker_job_drains_and_learns",
    "test_two_workers_share_the_queue",
    "test_job_over_real_grpc",
    "test_graceful_sigterm_checkpoints_and_returns_task",
    "test_worker_death_checkpoint_resume",
    "test_mesh_matches_local_trajectory",
    "test_accum_steps_applies_every_n",
    "test_mesh_worker_in_cluster",
    "test_pipeline_gradients_match_sequential",
    "test_checkpoint_and_resume",
}


def pytest_collection_modifyitems(config, items):
    import pytest

    for item in items:
        mod = getattr(item.module, "__name__", "")
        base = item.name.split("[")[0]
        if mod in _SLOW_MODULES or base in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
        if not _TPU_LANE and item.get_closest_marker("tpu"):
            item.add_marker(pytest.mark.skip(
                reason="TPU lane: set ELASTICDL_TPU_TESTS=1 "
                       "(make test-tpu) to run on the real chip"
            ))
