"""Ring attention == dense attention, values and gradients.

Runs on the 8-device virtual CPU mesh (conftest.py). The sequence axis is
genuinely sharded, so the ppermute ring and the online-softmax
accumulation are both exercised.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.ops.ring_attention import dense_attention, ring_attention
from elasticdl_tpu.parallel.mesh import make_mesh

B, S, H, D = 2, 32, 4, 8


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D), dtype) * 0.3
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("mesh_shape,axes", [
    ((4,), ("sp",)),
    ((2, 2, 2), ("dp", "sp", "tp")),
    ((8,), ("sp",)),
])
def test_ring_matches_dense(causal, mesh_shape, axes):
    q, k, v = _qkv()
    mesh = make_mesh(mesh_shape, axes,
                     devices=jax.devices()[: int(np.prod(mesh_shape))])
    want = dense_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_gradients_match_dense():
    q, k, v = _qkv(seed=1)
    mesh = make_mesh((4,), ("sp",), devices=jax.devices()[:4])

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, causal=True) ** 2)

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


def test_sp_absent_falls_back_to_dense():
    q, k, v = _qkv(seed=2)
    mesh = make_mesh((4,), ("dp",), devices=jax.devices()[:4])
    got = ring_attention(q, k, v, mesh, causal=True)
    want = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("causal", [True, False])
def test_pallas_ring_matches_dense(causal):
    """The fused (flash_chunk_update) ring == dense, values and grads
    (interpret mode on the CPU mesh)."""
    q, k, v = _qkv(seed=6)
    mesh = make_mesh((4,), ("sp",), devices=jax.devices()[:4])

    def ring_p(q, k, v):
        return ring_attention(q, k, v, mesh, causal=causal,
                              use_pallas=True, interpret=True)

    got = ring_p(q, k, v)
    want = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    gr = jax.grad(lambda *a: jnp.sum(ring_p(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(
        lambda *a: jnp.sum(dense_attention(*a, causal=causal) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_pallas_ring_composes_with_dp_tp():
    q, k, v = _qkv(seed=7)
    mesh = make_mesh((2, 2, 2), ("dp", "sp", "tp"),
                     devices=jax.devices()[:8])

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True,
                              use_pallas=True, interpret=True)

    got = f(q, k, v)
    want = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_under_jit_with_batch_sharding():
    q, k, v = _qkv(seed=3)
    mesh = make_mesh((2, 2, 2), ("dp", "sp", "tp"),
                     devices=jax.devices()[:8])

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh, causal=True)

    got = f(q, k, v)
    want = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
