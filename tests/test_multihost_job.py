"""Full two-process distributed job: RPC master + two mesh workers under
jax.distributed, with mid-training eval tasks.

The real thing end to end: process 0 hosts the master (task dispatcher +
eval service over localhost gRPC) AND runs worker 0; process 1 runs
worker 1. Both workers pull tasks dynamically from one queue while their
device meshes form a single 4-device global mesh. The typed-tick barrier
must reconcile: uneven task pulls, mid-training eval tasks (one worker
runs the forward program while the other feeds a dummy), and the final
drain. Assertions: both processes finish, same final version, eval
metrics reported, loss finite.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = textwrap.dedent("""
    import os, sys, time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1]); jax_port = sys.argv[2]
    master_port = sys.argv[3]; data_dir = sys.argv[4]
    jax.distributed.initialize(f"localhost:{jax_port}", 2, pid)
    sys.path.insert(0, "@REPO@")

    from elasticdl_tpu.core.model_spec import get_model_spec
    from elasticdl_tpu.data.factory import create_data_reader
    from elasticdl_tpu.parallel.mesh import make_mesh
    from elasticdl_tpu.parallel.mesh_runner import make_runner_for_spec
    from elasticdl_tpu.testing.data import model_zoo_dir
    from elasticdl_tpu.worker.master_client import MasterClient
    from elasticdl_tpu.worker.worker import Worker

    spec = get_model_spec(model_zoo_dir(),
                          "mnist.mnist_functional.custom_model")
    mesh = make_mesh((len(jax.devices()),), ("dp",))
    spec.model = spec.make_model(mesh)
    runner = make_runner_for_spec(spec, mesh)
    train_path = os.path.join(data_dir, "train.rec")
    reader = create_data_reader(train_path)

    server = None
    if pid == 0:
        from elasticdl_tpu.master.evaluation_service import (
            EvaluationService,
        )
        from elasticdl_tpu.master.servicer import (
            SERVICE_NAME, MasterServicer,
        )
        from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
        from elasticdl_tpu.comm.rpc import RpcServer

        eval_reader = create_data_reader(
            os.path.join(data_dir, "eval.rec")
        )
        dispatcher = TaskDispatcher(
            training_shards=reader.create_shards(),
            evaluation_shards=eval_reader.create_shards(),
            records_per_task=32,
            num_epochs=1,
        )
        eval_service = EvaluationService(
            dispatcher, spec.eval_metrics_fn(), eval_steps=3,
        )
        servicer = MasterServicer(dispatcher, eval_service)
        server = RpcServer(
            f"localhost:{master_port}",
            {SERVICE_NAME: servicer.handlers()},
        ).start()

    master = MasterClient(
        f"localhost:{master_port}", worker_id=pid,
        connect_timeout=60, retries=5,
    )
    # Coordinated multi-host checkpointing: EVERY process holds a hook
    # (orbax saves are collective writes).
    from elasticdl_tpu.checkpoint import CheckpointHook

    hook = CheckpointHook(
        checkpoint_dir=os.path.join(data_dir, "ckpt"),
        checkpoint_steps=3, backend="orbax",
    )
    worker = Worker(
        worker_id=pid,
        master_client=master,
        model_spec=spec,
        data_reader=reader,
        minibatch_size=16,
        step_runner=runner,
        checkpoint_hook=hook,
    )
    result = worker.run()
    from elasticdl_tpu.checkpoint.orbax_backend import OrbaxSaver

    ckpt_version = OrbaxSaver(
        os.path.join(data_dir, "ckpt")
    ).get_valid_latest_version()
    print(f"RESULT pid={pid} version={result['final_version']} "
          f"batches={result['trained_batches']} "
          f"ckpt={ckpt_version} "
          f"loss_finite={result['final_loss'] == result['final_loss']}",
          flush=True)
    if pid == 0:
        deadline = time.time() + 60
        while not dispatcher.finished() and time.time() < deadline:
            time.sleep(0.2)
        print(f"MASTER finished={dispatcher.finished()} "
              f"evals={len(eval_service.completed_results)}", flush=True)
        server.stop(0)
""").replace("@REPO@", REPO)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_job_with_eval(tmp_path):
    sys.path.insert(0, REPO)
    try:
        from elasticdl_tpu.testing.data import create_mnist_record_file

        create_mnist_record_file(str(tmp_path / "train.rec"), 192, seed=1)
        create_mnist_record_file(str(tmp_path / "eval.rec"), 32, seed=2)
    finally:
        sys.path.pop(0)
    script = tmp_path / "proc.py"
    script.write_text(_SCRIPT)
    jax_port, master_port = str(_free_port()), str(_free_port())
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), jax_port,
             master_port, str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=str(tmp_path),
        )
        for pid in (0, 1)
    ]
    outputs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed job hung (barrier broken?)")
        outputs.append(out)
    for pid, out in enumerate(outputs):
        assert procs[pid].returncode == 0, f"pid {pid}:\n{out}"
    results = {}
    for out in outputs:
        for line in out.splitlines():
            if line.startswith("RESULT"):
                fields = dict(
                    kv.split("=") for kv in line.split()[1:]
                )
                results[int(fields["pid"])] = fields
            if line.startswith("MASTER"):
                assert "finished=True" in line, line
                evals = int(line.split("evals=")[1])
                assert evals >= 1, line
    assert set(results) == {0, 1}
    # One true global state: both processes end at the same version.
    assert results[0]["version"] == results[1]["version"]
    assert int(results[0]["version"]) >= 1
    assert results[0]["loss_finite"] == "True"
    # Coordinated orbax checkpoint landed (final save = final version).
    assert results[0]["ckpt"] == results[0]["version"]
    # Both workers really pulled tasks (12 batches split between them).
    total = int(results[0]["batches"]) + int(results[1]["batches"])
    assert total == 12, results
